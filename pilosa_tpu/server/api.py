"""API facade: one method per externally-reachable operation.

Port of /root/reference/api.go — the single surface shared by the HTTP
handler, the cluster-message dispatcher, and the CLI. Methods validate
against cluster state (api.go:870-939): while RESIZING only resize-abort
and common methods are allowed.
"""

from __future__ import annotations

import threading
from datetime import datetime
from typing import Any, Dict, List, Optional, Sequence


from ..cluster.node import STATE_NORMAL
from ..constants import SHARD_WIDTH
from ..core.field import FieldOptions
from ..core.index import IndexOptions
from ..core.row import Row
from ..errors import PilosaError, QueryError
from ..executor import ExecOptions, Executor, ValCount
from ..obs import current as obs_current
from ..core.cache import Pair


class ApiError(PilosaError):
    pass


def _by_shard(column_ids, *payloads):
    """Group an import batch by owning shard.

    Yields (shard, column_ids, payloads) where each payload list is sliced
    to that shard's positions; a None payload stays None.
    """
    groups: Dict[int, List[int]] = {}
    for i, col in enumerate(column_ids):
        groups.setdefault(col // SHARD_WIDTH, []).append(i)
    for sh, idxs in sorted(groups.items()):
        cols = [column_ids[i] for i in idxs]
        sliced = tuple(
            [p[i] for i in idxs] if p is not None else None for p in payloads
        )
        yield sh, cols, sliced


# Methods valid in any cluster state (api.go apiMethod "common" set).
_COMMON_METHODS = {
    "status", "info", "schema", "version", "cluster_message",
    "resize_abort", "set_coordinator", "state", "shards_max",
}


class API:
    def __init__(self, server):
        self.server = server
        # Ingest observability (/debug/vars `ingest` group): shard batches
        # applied or routed through this node's import surface.
        self.import_batches = 0
        self._import_mu = threading.Lock()

    def _note_import_batches(self, n: int = 1) -> None:
        with self._import_mu:
            self.import_batches += n

    @property
    def ingest_config(self):
        cfg = getattr(self.server, "ingest_config", None)
        if cfg is None:
            from ..ingest import IngestConfig

            cfg = IngestConfig()
        return cfg

    @property
    def holder(self):
        return self.server.holder

    @property
    def cluster(self):
        return self.server.cluster

    @property
    def executor(self) -> Executor:
        return self.server.executor

    def _validate(self, method: str) -> None:
        state = self.cluster.state
        if state == STATE_NORMAL or method in _COMMON_METHODS:
            return
        raise ApiError(f"api method {method} unavailable in state {state}")

    # ---------------------------------------------------------------- query

    def query(
        self,
        index: str,
        query: str,
        shards: Optional[Sequence[int]] = None,
        column_attrs: bool = False,
        exclude_row_attrs: bool = False,
        exclude_columns: bool = False,
        remote: bool = False,
        deadline=None,
        traffic_class: Optional[str] = None,
        epoch: Optional[int] = None,
        at_position: Optional[int] = None,
        max_staleness: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> List[Any]:
        """Execute PQL under the query scheduler's lifecycle: admit (429
        when the queue is full) -> wait (bounded by `deadline`) ->
        execute, with the deadline riding ExecOptions so the executor
        aborts expired work before the next device dispatch. `deadline`
        is a sched.Deadline (or None); `traffic_class` defaults to
        interactive. `tenant` (the X-Pilosa-Tenant header, defaulting to
        the index name) is the QoS budget identity — see sched/qos.py."""
        self._validate("query")
        # Tenant identity defaults to the index name: single-tenant
        # deployments get per-index budgets for free, multi-tenant ones
        # send X-Pilosa-Tenant. Tagged onto the trace so the QoS ledger
        # and trace consumers can attribute the measured cost.
        tenant = tenant or index
        opt = ExecOptions(
            remote=remote,
            column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns,
            deadline=deadline,
            epoch=epoch,
            at_position=at_position,
            max_staleness=max_staleness,
            tenant=tenant,
        )
        t = obs_current()
        if t is not None:
            t.tag(tenant=tenant)
        sched = getattr(self.server, "scheduler", None)
        if sched is None:
            return self.executor.execute(index, query, shards=shards, opt=opt)
        from ..sched import CLASS_INTERACTIVE, DeadlineExceededError

        # Per-index traffic signal for the tier manager's prefetch
        # (docs/tiered-storage.md): forwarded sub-queries count too —
        # on a data node they ARE this index's serving traffic.
        sched.note_index(index)
        try:
            if remote:
                # Remote (forwarded) sub-queries are fan-out fragments of
                # a request the COORDINATOR already admitted — re-admitting
                # them here would double-count the work and, when every
                # node's interactive slots hold coordinators blocked on
                # each other's peers, form a cross-node slot-wait cycle
                # that only breaks on HTTP timeouts. Deadlines still apply
                # via opt; backpressure belongs at the admission edge.
                # They DO register as pressure, so concurrent fragment
                # queries coalesce on data nodes too.
                with sched.track_remote():
                    return self.executor.execute(
                        index, query, shards=shards, opt=opt)
            with sched.admit(traffic_class or CLASS_INTERACTIVE, deadline,
                             tenant=tenant):
                return self.executor.execute(index, query, shards=shards, opt=opt)
        except DeadlineExceededError as e:
            # Expiries detected downstream (executor map/reduce, remote
            # fan-out, micro-batch wait) surface here — on forwarded
            # sub-queries too; count each once so every abort is
            # observable in scheduler stats.
            if not getattr(e, "counted", False):
                e.counted = True
                sched.note_deadline_exceeded()
            raise

    def query_response(self, index: str, query: str, **kw) -> Dict[str, Any]:
        """Query + serialize results to the JSON wire shape
        (reference http/handler.go response encoding)."""
        column_attrs = kw.get("column_attrs", False)
        results = self.query(index, query, **kw)
        out: Dict[str, Any] = {"results": [serialize_result(r) for r in results]}
        if column_attrs:
            cols = set()
            for r in results:
                if isinstance(r, Row):
                    cols.update(int(c) for c in r.columns())
            idx = self.holder.index(index)
            attrs = []
            for col in sorted(cols):
                a = idx.column_attr_store.attrs(col)
                if a:
                    attrs.append({"id": col, "attrs": a})
            out["columnAttrs"] = attrs
        return out

    # ------------------------------------------------------------------ cdc

    @property
    def cdc(self):
        return getattr(self.server, "cdc", None)

    def _require_cdc(self):
        mgr = self.cdc
        if mgr is None:
            raise ApiError(
                "change capture is disabled (set cdc.enabled = true)")
        return mgr

    def cdc_stream(self, index: str, from_pos: int,
                   incarnation: Optional[str] = None,
                   timeout: Optional[float] = None,
                   max_bytes: int = 4 << 20):
        """One chunk of the resumable change stream: raw framed op
        records for positions > from_pos (cdc/log.py framing), the next
        cursor, and the log incarnation. Raises CdcGoneError (410) when
        the cursor fell behind retention or the index was recreated."""
        return self._require_cdc().stream(
            index, from_pos, inc=incarnation, timeout=timeout,
            max_bytes=max_bytes)

    def cdc_bootstrap(self, index: str) -> dict:
        """Snapshot re-seed for a behind-retention consumer: compressed
        fragment images + the position each was cut at."""
        return self._require_cdc().bootstrap(index)

    def cdc_standing_register(self, index: str, pql: str) -> dict:
        mgr = self._require_cdc()
        sq, created = mgr.standing.register(index, pql)
        out = sq.to_dict()
        out["created"] = created
        return out

    def cdc_standing_list(self) -> dict:
        return {"queries": self._require_cdc().standing.list()}

    def cdc_standing_poll(self, sid: str, after_version: int,
                          timeout: Optional[float] = None) -> dict:
        mgr = self._require_cdc()
        if timeout is None:
            timeout = mgr.config.poll_timeout
        return mgr.standing.poll(sid, after_version, timeout)

    def cdc_standing_delete(self, sid: str) -> None:
        self._require_cdc().standing.delete(sid)

    # ------------------------------------------------------------------ geo

    @property
    def geo(self):
        return getattr(self.server, "geo", None)

    def _require_geo(self):
        mgr = self.geo
        if mgr is None:
            raise ApiError(
                "geo replication is disabled (set geo.role)")
        return mgr

    def geo_promote(self) -> dict:
        """Operator-initiated leader-loss promotion (POST /geo/promote,
        docs/geo-replication.md): this follower becomes the leader
        under a bumped fencing geo epoch and starts pushing the demote
        handshake at the old leader."""
        return self._require_geo().promote()

    def geo_demote(self, leader: str, epoch: int) -> dict:
        """Fencing handshake target (POST /geo/demote): re-tail
        `leader` under the authoritative `epoch`, or 409 when we are
        already fenced at or past it."""
        return self._require_geo().demote(leader, epoch)

    def geo_status(self) -> dict:
        return self._require_geo().status()

    def _geo_check_write(self) -> None:
        """Import-path write fence: a geo follower refuses external
        writes with a typed 409 pointing at the leader; a leader
        tallies the accepting epoch (the split-brain evidence). The
        tail applies replicated records through apply_hint_ops, which
        deliberately does NOT pass this gate."""
        mgr = self.geo
        if mgr is not None:
            mgr.check_write()

    # --------------------------------------------------------------- schema

    def schema(self) -> List[dict]:
        self._validate("schema")
        return self.holder.schema()

    def apply_schema(self, schema: List[dict]) -> None:
        self.holder.apply_schema(schema)

    def create_index(self, name: str, options: Optional[dict] = None) -> dict:
        self._validate("create_index")
        opts = IndexOptions.from_dict(options or {})
        index = self.holder.create_index(name, opts)
        self.server.broadcast_message({"type": "create-index", "index": name,
                                       "options": opts.to_dict()})
        return index.to_info()

    def delete_index(self, name: str) -> None:
        self._validate("delete_index")
        self.holder.delete_index(name)
        self.server.broadcast_message({"type": "delete-index", "index": name})

    def create_field(self, index: str, name: str, options: Optional[dict] = None) -> dict:
        self._validate("create_field")
        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        opts = FieldOptions.from_dict(options or {})
        field = idx.create_field(name, opts)
        self.server.broadcast_message({"type": "create-field", "index": index,
                                       "field": name, "options": opts.to_dict()})
        return field.to_info()

    def delete_field(self, index: str, name: str) -> None:
        self._validate("delete_field")
        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        idx.delete_field(name)
        self.server.broadcast_message({"type": "delete-field", "index": index, "field": name})

    # --------------------------------------------------------------- import

    def _fan_out_import(self, index: str, shard: int, apply_local, send_remote,
                        remote: bool) -> None:
        """Bulk imports ride the executor's shared tolerant owner fan-out
        (one source of truth for the cluster's write-tolerance policy:
        dead replicas hinted or skipped + marked, deterministic rejections
        surfaced after the loop, the [replication] consistency level
        gating the ack). The local apply runs under hint capture so a
        missed replica forward enqueues this batch's exact WAL op bytes."""
        from ..core.fragment import capture_hint_ops

        captured: list = []

        def local():
            captured.clear()  # cutover retries must not double the batch
            with capture_hint_ops(captured):
                apply_local()

        def hint(node):
            hints = self.executor.hints
            if hints is None:
                return False
            return hints.add(node.id, index, shard, captured)

        self.executor.tolerant_owner_fanout(
            index, shard, remote, local, send_remote, hint=hint
        )

    def import_bits(self, index: str, field: str, shard: int, row_ids, column_ids,
                    timestamps=None, remote: bool = False,
                    row_keys=None, column_keys=None) -> None:
        """Route or apply a shard's worth of bits (api.go:653-698).

        String keys (row_keys/column_keys) are translated to ids here and
        the bits re-grouped by shard before routing — the key-mode import
        path (reference api.go key translation + ctl/import.go -k).
        """
        self._validate("import")
        if not remote:
            self._geo_check_write()
        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        fld = idx.field(field)
        if fld is None:
            from ..errors import FieldNotFoundError

            raise FieldNotFoundError(field)

        store = self.server.translate_store
        if row_keys or column_keys:
            n = len(column_keys) if column_keys else len(column_ids or [])
            n_rows = len(row_keys) if row_keys else len(row_ids or [])
            if n != n_rows:
                raise QueryError(
                    f"import row/column length mismatch: {n_rows} rows vs {n} columns"
                )
            if timestamps is not None and len(timestamps) != n:
                raise QueryError(
                    f"import timestamps length mismatch: {len(timestamps)} vs {n}"
                )
            if store.read_only:
                # Key allocation happens on the translation primary
                # (reference PrimaryTranslateStore); forward the whole
                # key-mode import there.
                self.server.client.import_keys_node(
                    self.server.primary_translate_store_url, index, field,
                    row_ids, column_ids, row_keys, column_keys, timestamps,
                )
                return
            if column_keys:
                if not idx.keys():
                    raise QueryError("column keys require index 'keys' option")
                column_ids = store.translate_columns_to_uint64(index, list(column_keys))
            if row_keys:
                if not fld.keys():
                    raise QueryError("row keys require field 'keys' option")
                row_ids = store.translate_rows_to_uint64(index, field, list(row_keys))
            # Re-group by shard now that column ids are known, then fan
            # the shard batches out across the executor worker pool (one
            # forward stream per peer) instead of the old serial loop.
            groups = {
                sh: (rows, cols, ts)
                for sh, cols, (rows, ts) in _by_shard(
                    column_ids, row_ids, timestamps)
            }

            def apply_local(shard):
                rows, cols, ts = groups[shard]
                tsl = None
                if ts is not None and any(t is not None for t in ts):
                    tsl = [_to_datetime(t) for t in ts]
                fld.import_bits(rows, cols, tsl)

            def send(node, shard):
                rows, cols, ts = groups[shard]
                self.server.client.import_node(
                    node, index, field, shard, rows, cols, ts)

            self.executor.tolerant_group_fanout(
                index, list(groups), remote, apply_local, send,
                workers=self.ingest_config.import_workers,
            )
            self._note_import_batches(len(groups))
            return

        n = len(column_ids or [])
        if len(row_ids or []) != n:
            raise QueryError(
                f"import row/column length mismatch: {len(row_ids or [])} rows vs {n} columns"
            )
        if timestamps is not None and len(timestamps) != n:
            raise QueryError(
                f"import timestamps length mismatch: {len(timestamps)} vs {n}"
            )
        def apply_local():
            ts = None
            # Presence = "any entry is not None": a truthiness check here
            # silently dropped an explicit epoch-0 timestamp.
            if timestamps is not None and any(t is not None for t in timestamps):
                ts = [_to_datetime(t) for t in timestamps]
            fld.import_bits(row_ids, column_ids, ts)

        self._note_import_batches()
        self._fan_out_import(
            index, shard, apply_local,
            lambda node: self.server.client.import_node(
                node, index, field, shard, row_ids, column_ids, timestamps
            ),
            remote,
        )

    def import_values(self, index: str, field: str, shard: int, column_ids, values,
                      remote: bool = False, column_keys=None) -> None:
        self._validate("import")
        if not remote:
            self._geo_check_write()
        idx = self.holder.index(index)
        fld = self.holder.field(index, field)
        if fld is None:
            from ..errors import FieldNotFoundError

            raise FieldNotFoundError(field)
        if column_keys:
            if len(column_keys) != len(values):
                raise QueryError(
                    f"import columns/values length mismatch: {len(column_keys)} vs {len(values)}"
                )
            if not idx.keys():
                raise QueryError("column keys require index 'keys' option")
            store = self.server.translate_store
            if store.read_only:
                # Same primary forwarding as key-mode bit imports: key
                # allocation only happens on the translation primary.
                self.server.client.import_value_keys_node(
                    self.server.primary_translate_store_url, index, field,
                    column_keys, values,
                )
                return
            column_ids = store.translate_columns_to_uint64(index, list(column_keys))
            groups = {
                sh: (cols, vals)
                for sh, cols, (vals,) in _by_shard(column_ids, values)
            }
            self.executor.tolerant_group_fanout(
                index, list(groups), remote,
                lambda shard: fld.import_value(*groups[shard]),
                lambda node, shard: self.server.client.import_value_node(
                    node, index, field, shard, *groups[shard]),
                workers=self.ingest_config.import_workers,
            )
            self._note_import_batches(len(groups))
            return
        if len(column_ids or []) != len(values or []):
            raise QueryError(
                f"import columns/values length mismatch: "
                f"{len(column_ids or [])} vs {len(values or [])}"
            )
        self._note_import_batches()
        self._fan_out_import(
            index, shard, lambda: fld.import_value(column_ids, values),
            lambda node: self.server.client.import_value_node(
                node, index, field, shard, column_ids, values
            ),
            remote,
        )

    # --------------------------------------------------------------- export

    def export_csv(self, index: str, field: str, shard: int) -> str:
        self._validate("export")
        frag = self.holder.fragment(index, field, "standard", shard)
        if frag is None:
            from ..errors import FragmentNotFoundError

            raise FragmentNotFoundError(f"{index}/{field}/standard/{shard}")
        lines = []
        for pos in frag.storage.slice():
            row_id = int(pos) // SHARD_WIDTH
            col_id = frag.shard * SHARD_WIDTH + int(pos) % SHARD_WIDTH
            lines.append(f"{row_id},{col_id}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -------------------------------------------------------------- cluster

    def status(self) -> dict:
        return {
            "state": self.cluster.state,
            "nodes": [n.to_dict() for n in self.cluster.nodes],
            "localID": self.cluster.node.id,
            # NodeStatus payload (reference gossip.go:240-273 push/pull sync):
            # schema + max shards ride the probe so peers converge without a
            # dedicated gossip plane.
            "maxShards": self.shards_max(),
            "schema": self.holder.schema(),
            # jax.distributed identity rides the status probe so static
            # clusters converge on every node's process index (the
            # collective plane's placement needs all of them).
            "processIdx": self.cluster.node.process_idx,
            # Routing epoch + whether a live rebalance is in flight: a
            # follower that lost the rebalance-complete broadcast (flaky
            # link, all retries dropped) converges by adopting a peer's
            # newer COMMITTED topology off the probe (_monitor_members).
            "routingEpoch": self.cluster.routing_epoch,
            "midRebalance": self.cluster.next_nodes is not None,
        }

    def info(self) -> dict:
        return {"shardWidth": SHARD_WIDTH}

    def shards_max(self) -> Dict[str, int]:
        return {name: idx.max_shard() for name, idx in self.holder.indexes.items()}

    def fragment_blocks(self, index: str, field: str, shard: int,
                        view: str = "standard") -> List[dict]:
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            from ..errors import FragmentNotFoundError

            raise FragmentNotFoundError(f"{index}/{field}/{view}/{shard}")
        return [b.to_dict() for b in frag.blocks()]

    def apply_block_diff(self, index: str, field: str, view: str, shard: int,
                         sets, clears) -> None:
        """View-exact anti-entropy write-back: apply consensus Set/Clear
        pairs to the addressed view (columns are global ids). Creates the
        view/fragment if the replica is missing them, like the reference
        syncer does locally (holder.go:751-762)."""
        fld = self.holder.field(index, field)
        if fld is None:
            from ..errors import FieldNotFoundError

            raise FieldNotFoundError(f"{index}/{field}")
        v = fld.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard, broadcast=False)
        for row, col in sets:
            frag.set_bit(int(row), int(col))
        for row, col in clears:
            frag.clear_bit(int(row), int(col))

    def apply_hint_ops(self, index: str, field: str, view: str, shard: int,
                       data: bytes) -> None:
        """Hinted-handoff delivery target (cluster/hints.py): replay a
        shipped run of WAL op records — the coordinator's byte-exact
        capture of a write this replica missed — into the addressed
        fragment. Creates the view/fragment if this replica never saw
        them (it was down when the write landed), like apply_block_diff.
        Replay is idempotent set/clear, so redelivery after a crashed
        checkpoint is harmless."""
        from ..storage.bitmap import decode_op_records

        fld = self.holder.field(index, field)
        if fld is None:
            from ..errors import FieldNotFoundError

            raise FieldNotFoundError(f"{index}/{field}")
        records = decode_op_records(data)  # raises typed on a torn stream
        v = fld.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard, broadcast=False)
        for adds, removes in records:
            frag.apply_hint_positions(adds, removes)

    def fragment_block_data(self, index: str, field: str, view: str, shard: int, block: int) -> dict:
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            from ..errors import FragmentNotFoundError

            raise FragmentNotFoundError(f"{index}/{field}/{view}/{shard}")
        rows, cols = frag.block_data(block)
        return {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()}

    def collective_count(self, index: str, field: str, rows: List[int]) -> int:
        """Leader side of multi-host collective execution: Count(Intersect)
        over `rows` through the generalized collective backend
        (parallel/collective.py) — placement follows jump-hash, entry is
        barrier-guarded and seq-ordered, failures surface instead of
        hanging. Degenerates to a local device count on single-process
        jobs."""
        self._validate("collective_count")
        if not rows:
            raise QueryError("collective_count requires at least one row")
        if len(self.cluster.nodes) > 1:
            import jax

            if jax.process_count() < len(self.cluster.nodes):
                # Without a shared job each node's "global" mesh is just its
                # local devices and the count would silently miss peer-owned
                # shards — refuse rather than return a wrong answer.
                raise ApiError(
                    "collective_count requires a jax.distributed job spanning "
                    f"the cluster ({len(self.cluster.nodes)} nodes, "
                    f"{jax.process_count()} jax processes); "
                    "set PILOSA_JAX_COORDINATOR on every node"
                )
        from ..pql.parser import parse

        terms = ", ".join(f"Row({field}={int(r)})" for r in rows)
        query = terms if len(rows) == 1 else f"Intersect({terms})"
        call = parse(query).calls[0]
        return self.server.collective.count(index, call)

    def cluster_message(self, msg: dict) -> None:
        self._validate("cluster_message")
        self.server.receive_message(msg)

    def recalculate_caches(self) -> None:
        for index in self.holder.indexes.values():
            for field in index.fields.values():
                for view in field.views.values():
                    for frag in view.fragments.values():
                        frag.cache.invalidate(force=True)
        self.server.broadcast_message({"type": "recalculate-caches"})

    def max_inverse_shards(self):  # parity stub: inverse views removed upstream
        return {}

    def set_coordinator(self, node_id: str) -> None:
        self._validate("set_coordinator")
        for n in self.cluster.nodes:
            n.is_coordinator = n.id == node_id
        self.server.broadcast_message({"type": "set-coordinator", "nodeID": node_id})

    def remove_node(self, node_id: str) -> None:
        self.server.handle_node_leave(node_id)

    def translate_data(self, offset: int) -> bytes:
        store = self.server.translate_store
        return store.read_from(offset) if store else b""

    def attr_diff(self, index: str, field: Optional[str], blocks: List[dict]) -> Dict[int, dict]:
        """Return attrs for blocks whose checksums differ (api.go attr diff)."""
        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        if field:
            fld = idx.field(field)
            if fld is None:
                from ..errors import FieldNotFoundError

                raise FieldNotFoundError(field)
            store = fld.row_attr_store
        else:
            store = idx.column_attr_store
        remote = {b["id"]: bytes.fromhex(b["checksum"]) for b in blocks}
        out: Dict[int, dict] = {}
        for bid, chk in store.blocks():
            if remote.get(bid) != chk:
                out.update(store.block_data(bid))
        return out


def _to_datetime(t):
    """Timestamp from wire: RFC3339-minute string (JSON) or epoch
    nanoseconds (protobuf ImportRequest.Timestamps). Only None means
    "absent": an explicit epoch-0 is a real timestamp (the protobuf
    boundary, which cannot distinguish absent from 0, already maps its
    zeros to None at decode — proto/__init__.py)."""
    if t is None:
        return None
    if isinstance(t, str):
        return datetime.strptime(t, "%Y-%m-%dT%H:%M")
    if isinstance(t, (int, float)):
        return datetime.utcfromtimestamp(t / 1e9)
    return t


def serialize_result(r) -> Any:
    if isinstance(r, Row):
        d = {"attrs": r.attrs or {}, "columns": [int(c) for c in r.columns()]}
        if r.keys:
            d["keys"] = r.keys
        return d
    if isinstance(r, ValCount):
        return r.to_dict()
    if isinstance(r, list) and (not r or isinstance(r[0], Pair)):
        return [p.to_dict() for p in r]
    if isinstance(r, (bool, int, float)) or r is None:
        return r
    return str(r)
