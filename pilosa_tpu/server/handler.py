"""HTTP transport: stdlib ThreadingHTTPServer REST handler.

Route table mirrors /root/reference/http/handler.go:189-231 (public
/index//field//query/import/schema/status plus /internal/* node-to-node
routes). Wire format is JSON (the reference negotiates JSON/protobuf;
JSON is canonical here). Remote (node-to-node) query responses carry type
tags so the coordinator can rehydrate Row/Pair/ValCount objects.
"""

from __future__ import annotations

import json
import re
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..core.cache import Pair
from ..core.row import Row
from ..errors import PilosaError
from ..executor import ValCount
from .api import API


def serialize_remote(r) -> dict:
    """Type-tagged result encoding for node-to-node responses."""
    if isinstance(r, Row):
        return {"type": "row", "columns": [int(c) for c in r.columns()],
                "attrs": r.attrs or {}}
    if isinstance(r, ValCount):
        return {"type": "valcount", "value": r.val, "count": r.count}
    if isinstance(r, list) and (not r or isinstance(r[0], Pair)):
        return {"type": "pairs", "pairs": [p.to_dict() for p in r]}
    if isinstance(r, bool):
        return {"type": "bool", "value": r}
    if isinstance(r, int):
        return {"type": "uint64", "value": r}
    return {"type": "none", "value": None}


def deserialize_remote(d: dict):
    t = d.get("type")
    if t == "row":
        row = Row(columns=d.get("columns", []))
        row.attrs = d.get("attrs", {})
        return row
    if t == "valcount":
        return ValCount(val=d["value"], count=d["count"])
    if t == "pairs":
        return [Pair(id=p["id"], count=p["count"], key=p.get("key", "")) for p in d["pairs"]]
    if t in ("bool", "uint64"):
        return d["value"]
    return None


def _json_body(body: bytes, default=None) -> dict:
    """Parse a JSON request body; malformed input is a client error (400),
    not an internal one."""
    if not body:
        if default is not None:
            return default
        raise PilosaError("request body required")
    try:
        return json.loads(body)
    except json.JSONDecodeError as e:
        raise PilosaError(f"malformed JSON body: {e}") from None


class Route:
    def __init__(self, method: str, pattern: str, fn: Callable):
        self.method = method
        self.regex = re.compile("^" + pattern + "$")
        self.fn = fn


class Handler:
    """Routes HTTP requests to API methods."""

    def __init__(self, api: API, logger=None, allowed_origins: Optional[List[str]] = None,
                 internal_key: Optional[str] = None):
        self.api = api
        self.logger = logger
        # Cluster shared secret (gossip.key analog): when set, /internal/*
        # requires a matching X-Pilosa-Key header — an unkeyed or
        # wrong-keyed node cannot join or deliver cluster messages. Public
        # API routes (incl. /status, which heartbeat probes read) stay
        # open, matching the reference's HTTP plane.
        self.internal_key = internal_key
        # CORS allowed origins (reference http/handler.go:83-91 wraps the
        # router in gorilla handlers.CORS when configured; empty = no CORS,
        # preflight gets 405 per server/handler_test.go:555-567).
        self.allowed_origins = list(allowed_origins or [])
        self.routes: List[Route] = [
            Route("GET", r"/", self.handle_home),
            Route("GET", r"/index", self.handle_get_indexes),
            Route("GET", r"/index/(?P<index>[^/]+)", self.handle_get_index),
            Route("POST", r"/index/(?P<index>[^/]+)", self.handle_post_index),
            Route("DELETE", r"/index/(?P<index>[^/]+)", self.handle_delete_index),
            Route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)", self.handle_post_field),
            Route("DELETE", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)", self.handle_delete_field),
            Route("POST", r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import", self.handle_post_import),
            Route("POST", r"/index/(?P<index>[^/]+)/query", self.handle_post_query),
            Route("GET", r"/export", self.handle_get_export),
            Route("GET", r"/schema", self.handle_get_schema),
            Route("GET", r"/status", self.handle_get_status),
            Route("GET", r"/info", self.handle_get_info),
            Route("GET", r"/version", self.handle_get_version),
            Route("POST", r"/recalculate-caches", self.handle_recalculate_caches),
            Route("POST", r"/cluster/resize/abort", self.handle_resize_abort),
            Route("POST", r"/cluster/resize/remove-node", self.handle_remove_node),
            Route("POST", r"/cluster/resize/set-coordinator", self.handle_set_coordinator),
            Route("POST", r"/internal/cluster/message", self.handle_cluster_message),
            Route("POST", r"/internal/collective/count", self.handle_collective_count),
            Route("GET", r"/internal/fragment/blocks", self.handle_fragment_blocks),
            Route("GET", r"/internal/fragment/block/data", self.handle_fragment_block_data),
            Route("POST", r"/internal/fragment/block/data", self.handle_post_block_data),
            Route("GET", r"/internal/fragment/nodes", self.handle_fragment_nodes),
            Route("GET", r"/internal/fragment/data", self.handle_fragment_data),
            Route("POST", r"/internal/fragment/data", self.handle_post_fragment_data),
            Route("POST", r"/internal/migrate/begin", self.handle_migrate_begin),
            Route("POST", r"/internal/migrate/delta", self.handle_migrate_delta),
            Route("POST", r"/internal/migrate/freeze", self.handle_migrate_freeze),
            Route("POST", r"/internal/migrate/close", self.handle_migrate_close),
            Route("GET", r"/internal/shards/max", self.handle_shards_max),
            Route("GET", r"/internal/translate/data", self.handle_translate_data),
            Route("POST", r"/internal/index/(?P<index>[^/]+)/attr/diff", self.handle_index_attr_diff),
            Route("POST", r"/internal/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/attr/diff", self.handle_field_attr_diff),
            Route("POST", r"/internal/fragment/hints", self.handle_post_hint_ops),
            Route("GET", r"/cdc/stream", self.handle_cdc_stream),
            Route("GET", r"/cdc/bootstrap", self.handle_cdc_bootstrap),
            Route("POST", r"/cdc/standing", self.handle_cdc_standing_register),
            Route("GET", r"/cdc/standing", self.handle_cdc_standing_list),
            Route("GET", r"/cdc/standing/(?P<sid>[^/]+)/poll", self.handle_cdc_standing_poll),
            Route("DELETE", r"/cdc/standing/(?P<sid>[^/]+)", self.handle_cdc_standing_delete),
            Route("POST", r"/geo/promote", self.handle_geo_promote),
            Route("POST", r"/geo/demote", self.handle_geo_demote),
            Route("GET", r"/geo/status", self.handle_geo_status),
            Route("GET", r"/debug/vars", self.handle_debug_vars),
            Route("GET", r"/debug/traces", self.handle_debug_traces),
            Route("GET", r"/metrics", self.handle_metrics),
            Route("POST", r"/debug/profile", self.handle_debug_profile),
            Route("GET", r"/debug/threads", self.handle_debug_threads),
            Route("GET", r"/internal/diagnostics", self.handle_diagnostics),
        ]

    def dispatch(self, method: str, path: str, query: Dict[str, List[str]], body: bytes,
                 headers: Optional[Dict[str, str]] = None):
        """Returns (status, content_type, payload_bytes) or the same plus
        an extra-response-headers dict (429 carries Retry-After)."""
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        if self.internal_key and path.startswith("/internal/"):
            import hmac

            # compare_digest on BYTES: the shared secret must not leak
            # through comparison timing, and the str overload raises
            # TypeError on non-ASCII input (http.server decodes headers as
            # latin-1, so an arbitrary-byte header must not crash the
            # connection — it must 403).
            presented = headers.get("x-pilosa-key", "").encode("latin-1", "replace")
            if not hmac.compare_digest(presented, self.internal_key.encode()):
                return 403, "application/json", json.dumps(
                    {"error": "cluster key required"}
                ).encode()
        for route in self.routes:
            if route.method != method:
                continue
            m = route.regex.match(path)
            if m is None:
                continue
            try:
                start = time.monotonic()
                result = route.fn(query=query, body=body, headers=headers, **m.groupdict())
                elapsed = time.monotonic() - start
                lqt = getattr(self.api.server, "long_query_time", 0)
                if lqt and elapsed > lqt and self.logger:
                    self.logger.info("%s %s %.3fs > long-query-time", method, path, elapsed)
                if isinstance(result, tuple):
                    return result
                return 200, "application/json", json.dumps(result).encode()
            except PilosaError as e:
                from ..errors import FragmentNotFoundError
                from ..sched import DeadlineExceededError, QueueFullError

                if isinstance(e, QueueFullError):
                    # Load shed: tell the client WHEN to come back instead
                    # of letting it hammer a saturated queue (Retry-After
                    # is integer seconds per RFC 9110). A tenant-budget
                    # shed (TenantBudgetError) echoes the tenant so a
                    # multiplexing client can throttle ONE tenant's
                    # traffic instead of backing everything off.
                    import math

                    retry = str(max(1, math.ceil(e.retry_after)))
                    hdrs = {"Retry-After": retry}
                    tenant = getattr(e, "tenant", None)
                    if tenant is not None:
                        hdrs["X-Pilosa-Tenant"] = str(tenant)
                    return (429, "application/json",
                            json.dumps({"error": str(e)}).encode(),
                            hdrs)
                if isinstance(e, DeadlineExceededError):
                    # The budget ran out server-side; 503 (not 400) so
                    # clients/balancers treat it as overload, not a bad
                    # request.
                    return (503, "application/json",
                            json.dumps({"error": str(e)}).encode())
                from ..errors import WriteConsistencyError

                if isinstance(e, WriteConsistencyError):
                    # Degraded write path (too few live owners for the
                    # configured [replication] write-consistency level, or
                    # total owner loss): RETRYABLE 503, not a 400 — the
                    # request is fine, the cluster is degraded. The
                    # applied copies stand (no rollback) and hints were
                    # enqueued before this surfaced, so a client retry
                    # after Retry-After re-applies idempotent ops.
                    return (503, "application/json",
                            json.dumps({"error": str(e)}).encode(),
                            {"Retry-After": "1"})
                from ..errors import CdcGoneError

                if isinstance(e, CdcGoneError):
                    # Typed retention miss (docs/cdc.md): the cursor or
                    # at-position fell behind the change log's fold line,
                    # or the index was deleted+recreated (stale
                    # incarnation). 410 GONE — retrying the same cursor
                    # can never succeed; the body carries the retained
                    # window + live incarnation so the consumer re-seeds
                    # via /cdc/bootstrap instead of guessing.
                    payload = {"error": str(e)}
                    if e.first is not None:
                        payload["first"] = e.first
                    if e.last is not None:
                        payload["last"] = e.last
                    if e.incarnation is not None:
                        payload["incarnation"] = e.incarnation
                    return (410, "application/json",
                            json.dumps(payload).encode())
                from ..errors import ShardMovedError, StaleRoutingEpochError

                if isinstance(e, (ShardMovedError, StaleRoutingEpochError)):
                    # Routing conflict (live rebalance cutover): 409 tells
                    # the sender to re-route once on refreshed placement —
                    # distinct from 400 (deterministic rejection) and 5xx
                    # (node fault), neither of which should re-route.
                    return (409, "application/json",
                            json.dumps({"error": str(e)}).encode())
                from ..errors import StaleGeoEpochError, StaleReadError

                if isinstance(e, StaleReadError):
                    # Bounded-staleness refusal (docs/geo-replication.md):
                    # a geo follower's replication lag exceeds the
                    # request's X-Pilosa-Max-Staleness bound. 409 with
                    # the CURRENT lag so the client can choose — relax
                    # the bound and re-read here, or fail over to the
                    # leader. Never a silently-stale answer.
                    payload = {"error": str(e)}
                    if e.lag is not None:
                        payload["lag"] = (e.lag if e.lag != float("inf")
                                          else None)
                    if e.bound is not None:
                        payload["bound"] = e.bound
                    if e.position is not None:
                        payload["position"] = e.position
                    return (409, "application/json",
                            json.dumps(payload).encode())
                if isinstance(e, StaleGeoEpochError):
                    # Geo fence (split-brain guard): a write reached a
                    # follower, or a demote handshake presented an epoch
                    # this cluster is already fenced past. 409; a deposed
                    # leader demotes and re-tails, a client re-routes to
                    # the leader.
                    payload = {"error": str(e)}
                    if e.epoch is not None:
                        payload["epoch"] = e.epoch
                    if e.current is not None:
                        payload["current"] = e.current
                    return (409, "application/json",
                            json.dumps(payload).encode())
                # Missing fragments map to 404 so the anti-entropy client can
                # treat the replica as empty instead of failing the sync
                # (reference http/handler.go:776,984,1030).
                status = 404 if isinstance(e, FragmentNotFoundError) else 400
                return status, "application/json", json.dumps({"error": str(e)}).encode()
            except Exception as e:  # pragma: no cover - defensive
                if self.logger:
                    self.logger.error("handler error: %s", traceback.format_exc())
                return 500, "application/json", json.dumps({"error": str(e)}).encode()
        if path == "/index/" or re.match(r"^/index/[^/]+/query$", path):
            return 405, "text/plain", b"method not allowed"
        return 404, "application/json", json.dumps({"error": "not found"}).encode()

    # ---------------------------------------------------------------- CORS

    def cors_origin(self, origin: Optional[str]) -> Optional[str]:
        """The Access-Control-Allow-Origin value for a request, or None."""
        if not origin or not self.allowed_origins:
            return None
        if "*" in self.allowed_origins:
            return "*"
        return origin if origin in self.allowed_origins else None

    def preflight(self, origin: Optional[str]):
        """Handle an OPTIONS preflight. Returns (status, extra_headers)."""
        if not self.allowed_origins:
            return 405, {}
        headers = {
            "Access-Control-Allow-Methods": "GET, POST, DELETE, OPTIONS",
            "Access-Control-Allow-Headers": "Content-Type",
            "Vary": "Origin",
        }
        allow = self.cors_origin(origin)
        if allow:
            headers["Access-Control-Allow-Origin"] = allow
        return 200, headers

    # ------------------------------------------------------------- handlers

    def handle_home(self, **kw):
        return {"message": "pilosa-tpu server. Send queries to /index/{index}/query"}

    def handle_get_indexes(self, **kw):
        return {"indexes": self.api.schema()}

    def handle_get_schema(self, **kw):
        return {"indexes": self.api.schema()}

    def handle_get_index(self, index, **kw):
        for info in self.api.schema():
            if info["name"] == index:
                return info
        from ..errors import IndexNotFoundError

        raise IndexNotFoundError(index)

    def handle_post_index(self, index, body, **kw):
        opts = _json_body(body, default={}).get("options", {})
        return self.api.create_index(index, opts)

    def handle_delete_index(self, index, **kw):
        self.api.delete_index(index)
        return {}

    def handle_post_field(self, index, field, body, **kw):
        opts = _json_body(body, default={}).get("options", {})
        return self.api.create_field(index, field, opts)

    def handle_delete_field(self, index, field, **kw):
        self.api.delete_field(index, field)
        return {}

    def handle_post_import(self, index, field, body, headers=None, **kw):
        headers = headers or {}
        if "application/x-protobuf" in headers.get("content-type", ""):
            from . import proto
            from ..constants import FIELD_TYPE_INT

            fld = self.api.holder.field(index, field)
            if fld is not None and fld.type() == FIELD_TYPE_INT:
                req = proto.decode_import_value_request(body)
            else:
                req = proto.decode_import_request(body)
        else:
            req = _json_body(body)
        shard = req.get("shard", 0)

        def run():
            if "values" in req:
                self.api.import_values(
                    index, field, shard, req.get("columnIDs"), req["values"],
                    remote=req.get("remote", False),
                    column_keys=req.get("columnKeys"),
                )
            else:
                self.api.import_bits(
                    index, field, shard, req.get("rowIDs", []), req.get("columnIDs", []),
                    req.get("timestamps"), remote=req.get("remote", False),
                    row_keys=req.get("rowKeys"), column_keys=req.get("columnKeys"),
                )

        # Imports ride the scheduler's batch class — bounded concurrency
        # keeps bulk loads from starving interactive queries of executor
        # slots, and a full queue sheds with 429 backpressure. Admission
        # happens HERE (not inside import_bits) because key-mode imports
        # recurse per shard; admitting inside the recursion would nest
        # slot acquisitions and self-deadlock at low concurrency limits.
        # Replication forwards (remote=True) and key-mode imports
        # forwarded to the translation primary (X-Pilosa-Forwarded; the
        # body can't say remote:true because the primary must run its own
        # owner fan-out) skip admission for the same reason remote
        # queries do: the originating node already admitted the work, and
        # nodes holding batch slots while blocked in each other's
        # admission queues would deadlock the write path.
        scheduler = getattr(self.api.server, "scheduler", None)
        forwarded = (headers or {}).get("x-pilosa-forwarded") == "1"
        if forwarded and self.internal_key:
            # On a keyed cluster, only an authenticated peer may claim
            # "already admitted" — otherwise any public client could strap
            # the header onto bulk imports and bypass batch-class shedding.
            # (Open clusters trust it, matching the trust model of the
            # equally-spoofable remote flag in the body.)
            import hmac

            presented = (headers or {}).get(
                "x-pilosa-key", "").encode("latin-1", "replace")
            forwarded = hmac.compare_digest(
                presented, self.internal_key.encode())
        if scheduler is None or req.get("remote") or forwarded:
            run()
        else:
            from ..sched import CLASS_BATCH

            # Imports charge the tenant's budget too (X-Pilosa-Tenant,
            # default: index) — bulk-load device time is exactly the
            # noisy-tenant cost the ledger exists to bound. Batch class
            # sheds FIRST when the bucket runs dry (docs/scheduler.md).
            tenant = (headers or {}).get("x-pilosa-tenant") or index
            with scheduler.admit(CLASS_BATCH, tenant=tenant):
                run()
        return {}

    def handle_post_query(self, index, body, query, headers=None, **kw):
        headers = headers or {}
        wants_proto = "application/x-protobuf" in headers.get("accept", "")
        is_proto = "application/x-protobuf" in headers.get("content-type", "")
        shards = None
        # Per-request budget: X-Pilosa-Deadline carries REMAINING seconds
        # (coordinators forward their leftover budget to peers); absent,
        # the scheduler's configured default applies.
        scheduler = getattr(self.api.server, "scheduler", None)
        deadline = None
        if scheduler is not None:
            deadline = scheduler.deadline_for(headers.get("x-pilosa-deadline"))
        # Sender's routing epoch (live rebalance): lets this node detect a
        # forwarded request routed under a placement older than its own.
        epoch = None
        raw_epoch = headers.get("x-pilosa-epoch")
        if raw_epoch:
            try:
                epoch = int(raw_epoch)
            except ValueError:
                epoch = None
        # Point-in-time read (docs/cdc.md): execute against the index as
        # of this CDC position instead of live storage. Also accepted as
        # ?atPosition= for clients that can't set headers.
        at_position = None
        raw_at = headers.get("x-pilosa-at-position") or \
            query.get("atPosition", [None])[0]
        if raw_at:
            try:
                at_position = int(raw_at)
            except ValueError:
                raise PilosaError(
                    f"invalid at-position value: {raw_at!r}") from None
        # Bounded-staleness read (docs/geo-replication.md): on a geo
        # follower, answer from local state only when replication lag is
        # within this many seconds, else 409 with the current lag. On a
        # leader or non-geo node the header is a clean no-op — local
        # state is the source of truth, never stale.
        max_staleness = None
        raw_stale = headers.get("x-pilosa-max-staleness")
        if raw_stale:
            try:
                max_staleness = float(raw_stale)
            except ValueError:
                raise PilosaError(
                    f"invalid max-staleness value: {raw_stale!r}") from None
            if max_staleness < 0:
                raise PilosaError(
                    f"invalid max-staleness value: {raw_stale!r}")
        # QoS tenant identity (docs/scheduler.md): budget charging and
        # SLO-classed shedding key on this. Defaults (in api.query) to
        # the index name so single-tenant deployments need no header.
        tenant = headers.get("x-pilosa-tenant") or None
        remote = query.get("remote", ["false"])[0] == "true"
        column_attrs = query.get("columnAttrs", ["false"])[0] == "true"
        exclude_row_attrs = query.get("excludeRowAttrs", ["false"])[0] == "true"
        exclude_columns = query.get("excludeColumns", ["false"])[0] == "true"

        if is_proto:
            from . import proto

            req = proto.decode_query_request(body)
            pql = req["query"]
            shards = req["shards"]
            remote = remote or req["remote"]
            column_attrs = column_attrs or req["columnAttrs"]
            exclude_row_attrs = exclude_row_attrs or req["excludeRowAttrs"]
            exclude_columns = exclude_columns or req["excludeColumns"]
        else:
            body_text = body.decode() if body else ""
            if body_text.startswith("{"):
                req = _json_body(body)
                pql = req.get("query", "")
                shards = req.get("shards")
            else:
                pql = body_text
        if "shards" in query:
            shards = [int(s) for s in query["shards"][0].split(",")]

        # Per-query tracing (docs/observability.md): adopt the
        # coordinator's trace id from X-Pilosa-Trace (stamped next to the
        # deadline/epoch headers) so this node's spans splice into ONE
        # cross-node tree, else roll the ingress sampler. Downstream
        # stages record through the obs contextvar; the trace lands in
        # the /debug/traces ring (and the slow-query log) at finish.
        from .. import obs as _obs

        recorder = getattr(self.api.server, "trace_recorder", None)
        trace = None
        if recorder is not None:
            trace_hdr = headers.get("x-pilosa-trace")
            if trace_hdr and remote:
                # Adoption is for coordinator-forwarded sub-queries ONLY
                # (remote=true): they bypass the local sampler because
                # the coordinator already rolled it. An ordinary client
                # stamping the header must not force tracing on a node
                # whose operator set sample-rate 0 — the knob's whole
                # point is bounding overhead and /debug/traces retention.
                trace = recorder.adopt(trace_hdr, index=index, pql=pql)
            elif not remote:
                trace = recorder.maybe_start(index=index, pql=pql)
        if trace is None:
            return self._post_query_traced(
                index, pql, shards, remote, column_attrs, exclude_row_attrs,
                exclude_columns, deadline, epoch, wants_proto, headers,
                None, None, at_position, max_staleness, tenant)
        token = _obs.activate(trace)
        try:
            return self._post_query_traced(
                index, pql, shards, remote, column_attrs, exclude_row_attrs,
                exclude_columns, deadline, epoch, wants_proto, headers,
                recorder, trace, at_position, max_staleness, tenant)
        except BaseException:
            recorder.finish(trace, status="error")
            raise
        finally:
            _obs.deactivate(token)
            recorder.finish(trace)

    def _post_query_traced(self, index, pql, shards, remote, column_attrs,
                           exclude_row_attrs, exclude_columns, deadline,
                           epoch, wants_proto, headers, recorder, trace,
                           at_position=None, max_staleness=None, tenant=None):
        if wants_proto:
            from . import proto
            from ..errors import PilosaError

            try:
                results = self.api.query(
                    index, pql, shards=shards, remote=remote,
                    exclude_row_attrs=exclude_row_attrs,
                    exclude_columns=exclude_columns,
                    deadline=deadline,
                    at_position=at_position,
                    max_staleness=max_staleness,
                    tenant=tenant,
                )
            except PilosaError as e:
                from ..sched import DeadlineExceededError, QueueFullError

                if isinstance(e, (QueueFullError, DeadlineExceededError)):
                    raise  # keep 429/503 semantics over a proto 400
                return 400, "application/x-protobuf", proto.encode_query_response([], err=str(e))
            cas = None
            if column_attrs:
                cas = self._column_attr_sets(index, results)
            payload = proto.encode_query_response(results, cas)
            return 200, "application/x-protobuf", payload

        if remote:
            results = self.api.query(index, pql, shards=shards, remote=True,
                                     deadline=deadline, epoch=epoch,
                                     at_position=at_position,
                                     max_staleness=max_staleness,
                                     tenant=tenant)
            from . import wire

            extra = {}
            if trace is not None:
                # The peer side of cross-node splicing: finish THIS node's
                # trace now (all spans are complete — the query returned)
                # and return its stage summary, size-bounded, so the
                # coordinator attaches it as child spans of its
                # remote:<peer> span. finish() is idempotent; the
                # handler's finally only re-lands errors.
                recorder.finish(trace)
                from ..obs.trace import SUMMARY_MAX_BYTES

                extra["X-Pilosa-Trace-Summary"] = trace.summary_header(
                    SUMMARY_MAX_BYTES)
            if wire.CONTENT_TYPE in headers.get("accept", ""):
                # Binary data plane: packed bitplanes instead of JSON column
                # lists (a dense 1M-column Row is 128KiB, not ~10MB).
                return 200, wire.CONTENT_TYPE, wire.encode_results(results), extra
            return (200, "application/json",
                    json.dumps({"results": [serialize_remote(r)
                                            for r in results]}).encode(),
                    extra)
        return self.api.query_response(
            index, pql, shards=shards, column_attrs=column_attrs,
            exclude_row_attrs=exclude_row_attrs, exclude_columns=exclude_columns,
            deadline=deadline, at_position=at_position,
            max_staleness=max_staleness, tenant=tenant,
        )

    def _column_attr_sets(self, index, results):
        cols = set()
        for r in results:
            if isinstance(r, Row):
                cols.update(int(c) for c in r.columns())
        idx = self.api.holder.index(index)
        out = []
        for col in sorted(cols):
            a = idx.column_attr_store.attrs(col)
            if a:
                out.append({"id": col, "attrs": a})
        return out

    def handle_get_export(self, query, **kw):
        index = query["index"][0]
        field = query["field"][0]
        shard = int(query["shard"][0])
        csv = self.api.export_csv(index, field, shard)
        return 200, "text/csv", csv.encode()

    def handle_get_status(self, **kw):
        return self.api.status()

    def handle_get_info(self, **kw):
        return self.api.info()

    def handle_get_version(self, **kw):
        from .. import __version__

        return {"version": __version__}

    def handle_recalculate_caches(self, **kw):
        self.api.recalculate_caches()
        return {}

    def handle_resize_abort(self, **kw):
        self.api.server.resize_abort()
        return {}

    def handle_remove_node(self, body, **kw):
        req = _json_body(body, default={})
        self.api.remove_node(req.get("id", ""))
        return {}

    def handle_set_coordinator(self, body, **kw):
        req = _json_body(body, default={})
        self.api.set_coordinator(req.get("id", ""))
        return {}

    def handle_cluster_message(self, body, headers=None, **kw):
        """Cluster envelope receive: protobuf type-byte envelope on
        Content-Type: application/x-protobuf (the reference's only wire
        format, broadcast.go:116-162), JSON otherwise (debug fallback)."""
        ctype = (headers or {}).get("content-type", "")
        if "protobuf" in ctype:
            from .proto import envelope

            self.api.cluster_message(envelope.decode_message(body))
        else:
            self.api.cluster_message(_json_body(body))
        return {}

    def handle_collective_count(self, body, **kw):
        data = _json_body(body)
        return {
            "count": self.api.collective_count(
                data["index"], data["field"], data.get("rows", [])
            )
        }

    def handle_fragment_blocks(self, query, **kw):
        # view is optional for reference parity (its RPC has no view param);
        # absent means standard.
        view = query.get("view", ["standard"])[0]
        return {
            "blocks": self.api.fragment_blocks(
                query["index"][0], query["field"][0], int(query["shard"][0]),
                view=view,
            )
        }

    def handle_fragment_block_data(self, query, **kw):
        return self.api.fragment_block_data(
            query["index"][0], query["field"][0], query["view"][0],
            int(query["shard"][0]), int(query["block"][0]),
        )

    def handle_post_hint_ops(self, query, body, **kw):
        """Hinted-handoff delivery (cluster/hints.py): the body is a raw
        run of storage/bitmap.py WAL op records for one fragment."""
        self.api.apply_hint_ops(
            query["index"][0], query["field"][0], query["view"][0],
            int(query["shard"][0]), body,
        )
        return {}

    # ------------------------------------------------------------------ cdc

    def handle_cdc_stream(self, query, **kw):
        """GET /cdc/stream?index=X&from=P — one long-poll chunk of the
        change stream: raw framed op records (cdc/log.py framing — the
        response bytes are byte-identical to the on-disk log slice) for
        positions > P. X-Pilosa-Cdc-Next is the cursor for the next
        request; X-Pilosa-Cdc-Incarnation pins the index generation
        (pass it back as &incarnation= to get a 410 instead of silent
        aliasing after a delete+recreate). Empty body = timeout with no
        new records (re-poll from the same cursor)."""
        if "index" not in query:
            raise PilosaError("index parameter required")
        index = query["index"][0]
        try:
            from_pos = int(query.get("from", ["0"])[0])
            timeout = (float(query["timeout"][0]) if "timeout" in query
                       else None)
            max_bytes = int(query.get("max-bytes", [str(4 << 20)])[0])
        except ValueError as e:
            raise PilosaError(f"invalid /cdc/stream parameter: {e}") from None
        inc = query.get("incarnation", [None])[0]
        data, nxt, incarnation = self.api.cdc_stream(
            index, from_pos, incarnation=inc, timeout=timeout,
            max_bytes=max_bytes)
        # Lag anchors for geo followers (docs/geo-replication.md): the
        # newest assigned position and THIS node's wall clock, read
        # together, so the consumer computes staleness entirely from
        # leader-side times (its own clock never enters the formula).
        head_pos, head_time = self.api.server.cdc.head(index)
        return (200, "application/octet-stream", data,
                {"X-Pilosa-Cdc-Next": str(nxt),
                 "X-Pilosa-Cdc-Incarnation": incarnation,
                 "X-Pilosa-Cdc-Head-Pos": str(head_pos),
                 "X-Pilosa-Cdc-Head-Time": repr(head_time)})

    def handle_cdc_bootstrap(self, query, **kw):
        """GET /cdc/bootstrap?index=X — snapshot re-seed for a consumer
        whose cursor 410'd: zlib-compressed base64 roaring images per
        fragment plus the position each was cut at. Resume the stream
        from the returned `from`; overlap replays idempotently."""
        if "index" not in query:
            raise PilosaError("index parameter required")
        return self.api.cdc_bootstrap(query["index"][0])

    def handle_cdc_standing_register(self, body, **kw):
        req = _json_body(body)
        index = req.get("index", "")
        pql = req.get("query", "")
        if not index or not pql:
            raise PilosaError("index and query fields required")
        return self.api.cdc_standing_register(index, pql)

    def handle_cdc_standing_list(self, **kw):
        return self.api.cdc_standing_list()

    def handle_cdc_standing_poll(self, sid, query, **kw):
        try:
            after = int(query.get("version", ["0"])[0])
            timeout = (float(query["timeout"][0]) if "timeout" in query
                       else None)
        except ValueError as e:
            raise PilosaError(
                f"invalid /cdc/standing poll parameter: {e}") from None
        return self.api.cdc_standing_poll(sid, after, timeout)

    def handle_cdc_standing_delete(self, sid, **kw):
        self.api.cdc_standing_delete(sid)
        return {}

    # ------------------------------------------------------------------ geo

    def handle_geo_promote(self, **kw):
        """POST /geo/promote — operator-initiated leader-loss promotion
        (docs/geo-replication.md): this follower becomes the leader
        under a bumped fencing geo epoch. Idempotent on a leader."""
        return self.api.geo_promote()

    def handle_geo_demote(self, body, **kw):
        """POST /geo/demote {"leader": uri, "epoch": n} — the fencing
        handshake: re-tail `leader` under the authoritative epoch, or
        409 when already fenced at or past it."""
        req = _json_body(body)
        leader = req.get("leader")
        if not leader:
            raise PilosaError("leader required")
        try:
            epoch = int(req["epoch"])
        except (KeyError, TypeError, ValueError):
            raise PilosaError("valid epoch required") from None
        return self.api.geo_demote(leader, epoch)

    def handle_geo_status(self, **kw):
        return self.api.geo_status()

    def handle_post_block_data(self, query, body, **kw):
        data = _json_body(body)
        self.api.apply_block_diff(
            query["index"][0], query["field"][0], query["view"][0],
            int(query["shard"][0]),
            data.get("sets", []), data.get("clears", []),
        )
        return {}

    def handle_fragment_nodes(self, query, **kw):
        index = query["index"][0]
        shard = int(query["shard"][0])
        return [n.to_dict() for n in self.api.cluster.shard_nodes(index, shard)]

    def handle_fragment_data(self, query, **kw):
        """Stream a fragment's storage for shard relocation (resize)."""
        import io

        frag = self.api.holder.fragment(
            query["index"][0], query["field"][0], query["view"][0], int(query["shard"][0])
        )
        if frag is None:
            from ..errors import FragmentNotFoundError

            raise FragmentNotFoundError("fragment not found")
        if frag.quarantined:
            # Serving a quarantined fragment's (empty, degraded) storage as
            # the real shard would let a resize install the empty copy and
            # then garbage-collect the healthy replicas — permanent loss.
            # Erroring makes the resize abort/pick another source and makes
            # a repairing peer try the next replica.
            from ..errors import PilosaError

            raise PilosaError(
                "fragment is quarantined pending repair; refusing to serve "
                "as a shard source"
            )
        buf = io.BytesIO()
        frag.write_to(buf)
        return 200, "application/octet-stream", buf.getvalue()

    def handle_post_fragment_data(self, query, body, **kw):
        import io

        holder = self.api.holder
        index, field = query["index"][0], query["field"][0]
        view, shard = query["view"][0], int(query["shard"][0])
        fld = holder.field(index, field)
        v = fld.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        frag.read_from(io.BytesIO(body))
        return {}

    def handle_migrate_begin(self, body, **kw):
        """Open a live-migration stream for one fragment: the response is
        a binary frame (header json + raw base bytes, cluster/rebalance.py
        framing) so a multi-MiB fragment base never rides base64."""
        from ..cluster.rebalance import pack_framed

        req = _json_body(body)
        hdr, data = self.api.server.migration_source.begin(
            req["index"], req["field"], req["view"], int(req["shard"]))
        return 200, "application/octet-stream", pack_framed(hdr, data)

    def handle_migrate_delta(self, body, **kw):
        from ..cluster.rebalance import pack_framed

        req = _json_body(body)
        hdr, data = self.api.server.migration_source.delta(
            req["session"], from_pos=req.get("from"))
        return 200, "application/octet-stream", pack_framed(hdr, data)

    def handle_migrate_freeze(self, body, **kw):
        req = _json_body(body)
        return self.api.server.migration_source.freeze(
            req["index"], int(req["shard"]))

    def handle_migrate_close(self, body, **kw):
        req = _json_body(body)
        self.api.server.migration_source.close(req.get("sessions", []))
        return {}

    def handle_shards_max(self, **kw):
        return {"standard": self.api.shards_max()}

    def handle_translate_data(self, query, **kw):
        offset = int(query.get("offset", ["0"])[0])
        return 200, "application/octet-stream", self.api.translate_data(offset)

    def handle_debug_vars(self, **kw):
        """expvar equivalent (reference mounts /debug/vars,
        http/handler.go:196): stats counters/gauges/timings as JSON, plus
        the device engine's cache hit/eviction counters."""
        stats = self.api.server.stats
        out = stats.snapshot() if hasattr(stats, "snapshot") else {}
        # Peek the lazy slot, NOT the .engine property: a stats scrape must
        # never be the thing that first initializes the device backend (a
        # dead TPU tunnel would hang the endpoint).
        engine = getattr(getattr(self.api, "executor", None), "_engine", None)
        if engine is not None:
            out = dict(out)
            engine_cache = engine.snapshot()
            out["engine_cache"] = engine_cache
            # Delta-refresh health pulled out as its own group: the on-call
            # question under mixed read/write traffic is "are writes
            # costing scattered KiB updates or full plane re-uploads", and
            # that should not require knowing the counter-dict layout.
            # Derived from the one locked snapshot above so the two groups
            # can never disagree within a single response.
            out["delta_refresh"] = {
                k: engine_cache.get(k, 0)
                for k in ("leaf_delta_hits", "stack_delta_hits",
                          "delta_bytes", "full_refresh_bytes")
            }
            # Effective cache bounds after env > [engine] > [tier] >
            # platform-default resolution — the knobs are spread across
            # three config surfaces, so a deployment must be able to SEE
            # what they resolved to without reading the resolution code.
            out["engine_budgets"] = dict(engine.budgets)
            # Tiered-storage health (docs/tiered-storage.md): per-tier
            # bytes/entries plus promotion/demotion/prefetch/delta-fold
            # counters — the on-call question under HBM pressure is "are
            # evictions coming back as sub-ms promotions or full
            # regathers" (leaf_tier_hits vs leaf_misses above answers the
            # other half).
            if engine.tier is not None:
                out["tier"] = engine.tier.snapshot()
            # Device-plane fault health (docs/fault-tolerance.md): breaker
            # states, classified dispatch failures, and the host-ladder
            # counters from engine_cache above — the on-call question
            # during a device incident is "is the plane breaker open, and
            # are queries being answered from the host ladder or erroring".
            out["device_plane"] = engine.device_health.snapshot()
        # Query-plan compiler health (docs/query-compiler.md):
        # canonical lowerings vs on-Call cache hits plus the
        # canonicalization effect counters (reorders / k-ary flattens).
        # Module-level (the plan compiler serves every engine in the
        # process), so the group is present even before the lazy engine
        # initializes.
        from ..plan import snapshot as _plan_snapshot

        out = dict(out)
        out["plan"] = _plan_snapshot()
        # Scheduler lifecycle metrics: queue depth, admit/shed/deadline
        # counts, and the micro-batcher's launch/coalesce counters (wait
        # time and batch-size histograms live in the stats timings above).
        scheduler = getattr(self.api.server, "scheduler", None)
        if scheduler is not None:
            out = dict(out)
            out["scheduler"] = scheduler.snapshot()
        batcher = getattr(self.api.server, "batcher", None)
        if batcher is not None:
            out = dict(out)
            out["batcher"] = batcher.snapshot()
        # Multi-tenant QoS health (docs/scheduler.md "Tenant budgets"):
        # per-tenant balances/debt/mean cost plus charge/shed/defer
        # counters — the on-call question during a noisy-neighbor event
        # is "which tenant is over budget, and is it being shed or just
        # deferred behind in-budget traffic".
        qos = getattr(self.api.server, "qos", None)
        if qos is not None:
            out = dict(out)
            out["qos"] = qos.snapshot()
        # Autoscaler health (docs/rebalance.md "Autoscaling"): the sample
        # window, last decision, scale/skip counters, and which nodes the
        # controller added — the on-call question is "why did (or didn't)
        # the cluster scale, and what does the controller think the load
        # is".
        autoscaler = getattr(self.api.server, "autoscaler", None)
        if autoscaler is not None:
            out = dict(out)
            out["autoscale"] = autoscaler.snapshot()
        # Crash-safety health: which fragments are serving degraded
        # (quarantined at open, repair pending), how often queries touched
        # one, and any armed failpoints (nonempty only under fault tests).
        quarantined = self.api.holder.quarantined_fragments()
        executor = getattr(self.api, "executor", None)
        out = dict(out)
        out["storage"] = {
            "quarantined": [
                {
                    "index": f.index, "field": f.field, "view": f.view,
                    "shard": f.shard, "reason": f.quarantine_reason,
                }
                for f in quarantined
            ],
            "quarantined_reads": getattr(executor, "quarantined_reads", 0),
        }
        # Ingest health (docs/ingest.md): un-snapshotted WAL bytes across
        # fragments, background-snapshot counters and queue depth, and how
        # many shard batches the import surface has applied/routed — the
        # on-call question under heavy ingest is "are snapshots keeping up
        # with the write rate" (wal_bytes climbing without bound means no).
        ingest = self.api.holder.ingest_stats() if hasattr(
            self.api.holder, "ingest_stats") else {}
        ingest["import_batches"] = getattr(self.api, "import_batches", 0)
        out["ingest"] = ingest
        # Peer fault-tolerance health: per-peer breaker states plus the
        # breaker/retry/hedge counters — the evidence for "a blackholed
        # peer costs zero connect attempts between half-open probes" and
        # "replica retries stayed inside the budget".
        out["resilience"] = self.api.server.cluster.health.snapshot()
        # Collective-plane health (docs/multichip.md): served/batched
        # counts, fallbacks BY REASON, barrier timeouts, resident-stack
        # hit/delta/eviction counters, and the plane/slice breaker states
        # — the on-call question when full-index qps drops is "did the
        # fast path stop serving, and WHY did it refuse".
        coll = getattr(self.api.server, "collective", None)
        if coll is not None:
            out["collective"] = coll.snapshot()
        # Live-rebalance health (docs/rebalance.md): fragments moved vs
        # pending, bytes streamed, catch-up rounds, cutover write-pause
        # percentiles, and the routing epoch — the on-call question during
        # an elastic resize is "is the migration making progress, and what
        # did cutovers cost the write path".
        stats = getattr(self.api.server, "rebalance_stats", None)
        if stats is not None:
            cluster = self.api.server.cluster
            rb = stats.snapshot()
            rb["epoch"] = cluster.routing_epoch
            rb["active"] = cluster.next_nodes is not None
            rb["migrated_shards"] = len(cluster.migrated)
            out["rebalance"] = rb
        # Durable write replication (docs/durability.md "Write-path
        # consistency"): configured ack level, per-peer pending hint
        # backlog, append/deliver/expire counters — the on-call question
        # after a replica outage is "are the missed writes queued and
        # draining, or waiting on the anti-entropy backstop".
        hints = getattr(self.api.server, "hints", None)
        if hints is not None:
            out["replication"] = hints.snapshot()
        # CDC health (docs/cdc.md): per-index position window + retention
        # counters, PIT cache hit rate, standing-query eval/push/stale
        # totals — the on-call question for a lagging consumer is "did my
        # cursor fall behind the fold line, and how fast is it moving".
        cdc = getattr(self.api.server, "cdc", None)
        if cdc is not None:
            out["cdc"] = cdc.debug_vars()
        # Geo replication (docs/geo-replication.md): role/epoch, per-link
        # tail positions + lag, breaker state, promotion/demotion/fence
        # counters — the on-call question is "how far behind is this
        # follower, and who holds the fencing epoch".
        geo = getattr(self.api.server, "geo", None)
        if geo is not None:
            out["geo"] = geo.debug_vars()
        # pmux internal transport (docs/transport.md): connection churn,
        # frame/byte totals, handshake fallbacks, inflight high-water —
        # the on-call question after flipping [transport] on is "are
        # hops actually riding the mux, and is any peer demoted to
        # HTTP". Always present (the stats object exists even when
        # disabled) so dashboards need no conditional.
        tstats = getattr(self.api.server, "transport_stats", None)
        if tstats is not None:
            tr = tstats.snapshot()
            tcfg = getattr(self.api.server, "transport_config", None)
            tr["enabled"] = bool(tcfg.enabled) if tcfg is not None else False
            mux_t = getattr(self.api.server, "mux_transport", None)
            if mux_t is not None:
                tr.update(mux_t.snapshot())
            mux_s = getattr(self.api.server, "mux_server", None)
            if mux_s is not None:
                tr["server"] = mux_s.snapshot()
            out["transport"] = tr
        # Per-query tracing health (docs/observability.md): sampler
        # counters, ring depth, slow-query count — the aggregate next to
        # the per-trace detail /debug/traces serves.
        recorder = getattr(self.api.server, "trace_recorder", None)
        if recorder is not None:
            out["obs"] = recorder.snapshot()
        from .. import failpoints as _fp

        if _fp.active():
            out["failpoints"] = _fp.active()
        return out

    def handle_debug_traces(self, query, **kw):
        """Completed per-query traces from the recorder's bounded ring,
        newest first. Filters: ?min-ms= (minimum duration), ?index=,
        ?limit= (default 64). Each trace is the FULL cross-node tree the
        coordinator assembled (remote hops carry the peer's spliced child
        spans)."""
        recorder = getattr(self.api.server, "trace_recorder", None)
        if recorder is None:
            return {"traces": []}
        try:
            min_ms = float(query.get("min-ms", ["0"])[0])
            limit = int(query.get("limit", ["64"])[0])
        except ValueError as e:
            # Malformed operator input is a 400, not a 500 traceback.
            raise PilosaError(f"invalid /debug/traces parameter: {e}") from None
        index = query.get("index", [None])[0]
        return {"traces": recorder.traces(min_ms=min_ms, index=index,
                                          limit=limit)}

    def handle_metrics(self, **kw):
        """Prometheus text exposition: the /debug/vars counter groups
        (same dict — the two surfaces cannot disagree) plus the trace
        recorder's per-stage latency histograms, so the node is
        scrapeable without custom tooling."""
        from ..obs import metrics as _metrics

        out = self.handle_debug_vars()
        recorder = getattr(self.api.server, "trace_recorder", None)
        hists = recorder.stage_histograms() if recorder is not None else {}
        text = _metrics.render_prometheus(out, hists)
        return 200, _metrics.CONTENT_TYPE, text.encode()

    _profile_lock = threading.Lock()

    def handle_debug_profile(self, query, **kw):
        """Capture a JAX profiler trace (the pprof-equivalent for the
        device hot path). POST /debug/profile?seconds=2 writes a trace
        under <data_dir>/profiles and returns its path. The profiler is
        process-global: concurrent captures are rejected with 409."""
        import os
        import uuid

        import jax

        seconds = min(max(float(query.get("seconds", ["1"])[0]), 0.0), 30.0)
        if not self._profile_lock.acquire(blocking=False):
            return 409, "application/json", json.dumps(
                {"error": "a profile capture is already running"}
            ).encode()
        try:
            base = self.api.server.data_dir or "/tmp"
            out = os.path.join(base, "profiles",
                               f"{int(time.time())}-{uuid.uuid4().hex[:6]}")
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
            try:
                # pilint: allow-blocking(the sleep IS the capture window; _profile_lock is a try-acquire busy flag — contenders 409 instead of waiting, so nothing can queue behind this)
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        finally:
            self._profile_lock.release()
        return {"path": out}

    def handle_debug_threads(self, **kw):
        """Stack dump of every live Python thread — the goroutine-dump half
        of the reference's /debug/pprof mount (http/handler.go:195). A hung
        monitor or a stuck device dispatch shows up here without attaching
        a debugger to the live node."""
        import sys
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t for t in threading.enumerate()}
        out = {}
        for ident, frame in frames.items():
            t = names.get(ident)
            # The ident keeps duplicate-named threads distinct (multiple
            # in-process nodes each run a 'collective-runner' etc.).
            label = (
                f"{t.name}-{ident} ({'daemon' if t.daemon else 'thread'})"
                if t else f"thread-{ident}"
            )
            out[label] = traceback.format_stack(frame)
        return {"threads": out, "count": len(out)}

    def handle_diagnostics(self, **kw):
        return self.api.server.diagnostics.gather()

    def handle_index_attr_diff(self, index, body, **kw):
        req = _json_body(body)
        attrs = self.api.attr_diff(index, None, req.get("blocks", []))
        return {"attrs": {str(k): v for k, v in attrs.items()}}

    def handle_field_attr_diff(self, index, field, body, **kw):
        req = _json_body(body)
        attrs = self.api.attr_diff(index, field, req.get("blocks", []))
        return {"attrs": {str(k): v for k, v in attrs.items()}}


class _RequestHandler(BaseHTTPRequestHandler):
    handler: Handler = None  # set by serve()
    protocol_version = "HTTP/1.1"
    # Nagle off (StreamRequestHandler.setup reads this): the response is
    # written as several small sends, and with Nagle on a keep-alive
    # client stalls ~40ms per request on the delayed-ACK interaction.
    disable_nagle_algorithm = True
    # Idle keep-alive read timeout: without it every silent client pins a
    # handler thread in readline() forever (handle_one_request maps a
    # socket timeout to close_connection). Clients bound their reuse to
    # well under this (InternalClient.IDLE_REUSE_S).
    timeout = 60

    def _do(self, method: str):
        parsed = urlparse(self.path)
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        result = self.handler.dispatch(
            method, parsed.path.rstrip("/") or "/", parse_qs(parsed.query), body,
            headers=dict(self.headers),
        )
        extra_headers = {}
        if len(result) == 4:
            status, ctype, payload, extra_headers = result
        else:
            status, ctype, payload = result
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in extra_headers.items():
            self.send_header(k, v)
        if self.handler.allowed_origins:
            # The ACAO value varies with the request Origin; shared caches
            # must not serve one origin's response to another.
            self.send_header("Vary", "Origin")
            allow = self.handler.cors_origin(self.headers.get("Origin"))
            if allow:
                self.send_header("Access-Control-Allow-Origin", allow)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        self._do("GET")

    def do_POST(self):
        self._do("POST")

    def do_DELETE(self):
        self._do("DELETE")

    def do_OPTIONS(self):
        status, headers = self.handler.preflight(self.headers.get("Origin"))
        self.send_response(status)
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, fmt, *args):  # silence default stderr logging
        pass


class _Server(ThreadingHTTPServer):
    # The stdlib default backlog of 5 drops (RSTs) connections under
    # concurrent load — 16 clients opening sockets faster than the accept
    # loop drains them is routine for a serving benchmark, let alone
    # production. Match Go's effective unbounded accept behavior closely
    # enough that the OS queue, not the library, is the limit.
    request_queue_size = 128
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Live per-connection sockets: keep-alive means a handler thread
        # can sit in readline() long after the listener closes, so
        # server_close must SEVER established connections too (Go's
        # http.Server.Close semantics) — otherwise an in-process "dead"
        # node keeps answering its pooled peers forever.
        self._live = set()
        self._live_mu = threading.Lock()

    def process_request(self, request, client_address):
        with self._live_mu:
            self._live.add(request)
        super().process_request(request, client_address)

    def close_request(self, request):
        with self._live_mu:
            self._live.discard(request)
        super().close_request(request)

    def server_close(self):
        super().server_close()
        import socket as _socket

        with self._live_mu:
            live = list(self._live)
            self._live.clear()
        for sock in live:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def handle_error(self, request, client_address):
        """Peer disconnects (reset/broken pipe/timeouts) are routine with
        keep-alive pools and severed-on-close peers — not stderr-traceback
        events. Anything else keeps the stdlib's loud default."""
        import sys

        # sys.exc_info, not sys.exception: the latter is 3.11+ and this
        # runs on 3.10 — an AttributeError here replaced every quiet
        # disconnect with a scarier traceback of its own.
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


def serve(handler: Handler, host: str = "localhost", port: int = 0,
          ssl_context=None) -> Tuple[ThreadingHTTPServer, threading.Thread, int]:
    cls = type("BoundHandler", (_RequestHandler,), {"handler": handler})
    httpd = _Server((host, port), cls)
    if ssl_context is not None:
        # https bind (reference server/server.go:367-375 getListener wraps
        # the listener in tls.Listen when the bind scheme is https).
        # do_handshake_on_connect=False: the handshake must run in the
        # per-connection worker thread, not the single accept loop, or one
        # stalled client blocks every other connection.
        httpd.socket = ssl_context.wrap_socket(
            httpd.socket, server_side=True, do_handshake_on_connect=False
        )
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread, httpd.server_address[1]
