"""InternalClient: node-to-node HTTP operations.

Port of the interface in /root/reference/client.go:34-60 and implementation
http/client.go: query fan-out, import routing, fragment block diff, shard
retrieval for resize, cluster message send, translate-log streaming.
Transport: stdlib http.client over per-thread keep-alive connection pools
(see _conn); wire format JSON/protobuf per route.
"""

from __future__ import annotations

import http.client
import json
import socket
import struct
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

from ..errors import PilosaError
from .handler import deserialize_remote
from .mux import MuxError, MuxUnavailable


class ClientError(PilosaError):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


def load_cluster_key(path: str) -> str:
    """Read + validate a cluster shared-secret file (gossip.key analog).

    One loader shared by Server and the ctl CLI so both reject the same
    misconfigurations the same way: a missing file, an empty file (which
    would silently produce an unauthenticated client), or non-ASCII
    content (HTTP headers are latin-1 on the wire; an emoji key would
    brick every authenticated request with opaque errors)."""
    try:
        with open(path) as f:
            key = f.read().strip()
    except OSError as e:
        raise PilosaError(f"cannot read gossip key file {path!r}: {e}") from e
    if not key:
        raise PilosaError(f"gossip key file {path!r} is empty")
    if not key.isascii() or any(ord(c) < 33 or ord(c) == 127 for c in key):
        # Printable ASCII with no whitespace/control chars: anything else
        # either breaks http.client at header-send time (interior newline
        # -> 'Invalid header value') or invites invisible mismatches.
        raise PilosaError(
            f"gossip key file {path!r} must be printable ASCII on one line"
        )
    return key


def _node_url(node) -> str:
    uri = node.uri if not isinstance(node, str) else node
    if not uri.startswith("http"):
        uri = "http://" + uri
    return uri.rstrip("/")


class InternalClient:
    def __init__(self, timeout: float = 30.0, skip_verify: bool = False,
                 key: Optional[str] = None):
        self.timeout = timeout
        # Cluster shared secret (gossip.key analog): sent on every request;
        # peers with a key configured refuse unauthenticated /internal/*.
        self.key = key
        # Optional mux.MuxTransport (docs/transport.md), installed by the
        # owning Server when [transport] enabled: http-scheme requests
        # ride persistent multiplexed frames, with per-peer HTTP fallback
        # when the handshake fails (mixed / mux-disabled clusters).
        self.mux = None
        # Per-thread keep-alive connection pool (see _conn). Every
        # thread's pool dict is also tracked in _pools so close() can
        # drain sockets owned by threads that no longer exist.
        self._local = threading.local()
        self._pools_mu = threading.Lock()
        self._pools: list = []
        # TLS peer-verification opt-out for self-signed cluster certs
        # (reference server/server.go:216-218 InsecureSkipVerify).
        self._ssl_context = None
        if skip_verify:
            import ssl

            self._ssl_context = ssl.create_default_context()
            self._ssl_context.check_hostname = False
            self._ssl_context.verify_mode = ssl.CERT_NONE

    # Reuse a pooled connection only if it was used this recently: the
    # server closes idle keep-alive connections (handler read timeout
    # 60s), and reusing one the server is about to (or did) close risks
    # a request that cannot be safely replayed. Well under the server
    # timeout, so stale reuse needs a peer crash/restart, not mere idleness.
    IDLE_REUSE_S = 20.0

    def _conn(self, scheme: str, netloc: str):
        """Per-thread keep-alive connection to `netloc`, returned as
        (conn, fresh). urllib opens a fresh TCP connection per request,
        which put ~0.7 ms of setup on every node-to-node call (fan-out,
        replication, heartbeats); pooled HTTP/1.1 connections cut a serial
        query round trip ~2x. Thread-local, so no cross-thread sharing of
        http.client state. `fresh` is True when the connection was just
        opened — the retry policy needs to know, because only on a fresh
        connection does a send-phase error prove the peer never saw the
        request (a pooled connection's close race can deliver a partial
        body the peer may have already acted on)."""
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
            with self._pools_mu:
                self._pools.append(pool)
        entry = pool.get((scheme, netloc))
        if entry is not None:
            conn, last_used = entry
            if time.monotonic() - last_used < self.IDLE_REUSE_S:
                return conn, False
            conn.close()
            del pool[(scheme, netloc)]
        if scheme == "https":
            import ssl

            ctx = self._ssl_context or ssl.create_default_context()
            conn = http.client.HTTPSConnection(
                netloc, timeout=self.timeout, context=ctx)
        else:
            conn = http.client.HTTPConnection(netloc, timeout=self.timeout)
        conn.connect()
        # Nagle off: small keep-alive requests otherwise stall ~40ms
        # per round trip on the delayed-ACK interaction.
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        pool[(scheme, netloc)] = (conn, time.monotonic())
        return conn, True

    def _touch_conn(self, scheme: str, netloc: str) -> None:
        pool = getattr(self._local, "conns", None)
        if pool is not None and (scheme, netloc) in pool:
            pool[(scheme, netloc)] = (
                pool[(scheme, netloc)][0], time.monotonic())

    def _drop_conn(self, scheme: str, netloc: str) -> None:
        pool = getattr(self._local, "conns", None)
        if pool is not None:
            entry = pool.pop((scheme, netloc), None)
            if entry is not None:
                entry[0].close()

    def close(self) -> None:
        """Drain every thread's keep-alive pool. The pools are per-thread
        but registered centrally at creation, so shutdown can close
        sockets opened by worker threads that have since exited —
        previously they leaked until process exit (visible as climbing
        open-fd counts in tests that churn servers). Idempotent, and a
        send AFTER close builds (and re-registers) a fresh pool, so the
        Server and the Executor both closing the shared client is fine."""
        with self._pools_mu:
            pools, self._pools = self._pools, []
        for pool in pools:
            for entry in list(pool.values()):
                try:
                    entry[0].close()
                except OSError:  # pragma: no cover - best-effort teardown
                    pass
            pool.clear()

    def _request(self, method: str, url: str, body: Optional[bytes] = None,
                 content_type: str = "application/json",
                 accept: Optional[str] = None,
                 extra_headers: Optional[Dict[str, str]] = None,
                 want_headers: bool = False, idempotent: bool = False):
        """Returns the response body, or (body, lowercased-header-dict)
        when want_headers — the tracing path reads the peer's
        X-Pilosa-Trace-Summary off the response. ``idempotent`` marks a
        POST whose replay is harmless (PQL forwards: WRITE_CALLS all
        have value semantics) so the mux may retry it over HTTP when
        the peer cannot fit the response in a frame."""
        parts = urllib.parse.urlsplit(url)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        headers = {}
        if body is not None:
            headers["Content-Type"] = content_type
        if accept:
            headers["Accept"] = accept
        if self.key:
            headers["X-Pilosa-Key"] = self.key
        if extra_headers:
            headers.update(extra_headers)
        if self.mux is not None and parts.scheme == "http":
            try:
                status, data, rheaders = self.mux.request(
                    method, parts.netloc, path, body=body,
                    content_type=content_type if body is not None else None,
                    accept=accept, headers=extra_headers,
                    idempotent=idempotent)
            except MuxUnavailable:
                # Disabled / peer demoted / handshake failed / oversized
                # frame: routing, not an error — serve over plain HTTP.
                if self.mux.stats is not None:
                    self.mux.stats.bump("requests_http")
            except MuxError as e:
                # Same evidence shape as an HTTP socket fault: status 0
                # feeds the breaker and the executor's replica-retry
                # classification exactly like a connect failure.
                self._local.transport = "mux"
                raise ClientError(f"{method} {url}: {e}") from e
            else:
                self._local.transport = "mux"
                if status >= 400:
                    detail = data.decode(errors="replace")
                    raise ClientError(
                        f"{method} {url}: {status} {detail}", status=status)
                if want_headers:
                    return data, rheaders
                return data
        self._local.transport = "http"
        # Retry policy (one silent retry, always on a FRESH connection):
        #   - send-phase errors on a FRESHLY-OPENED connection: the peer
        #     provably never processed the request — retry any method;
        #   - send-phase errors on a POOLED connection: the keep-alive
        #     close race can deliver a partial body that proto3 may parse
        #     as a valid truncated message, so a non-GET replay could
        #     double-apply (e.g. a cluster message) — retry GET only.
        #     Deliberate tradeoff: the unretried POST surfaces as status 0
        #     and may transiently mark a healthy peer unavailable, but the
        #     member monitor re-marks it available on its next successful
        #     probe (~seconds), while a double-applied write diverges
        #     replicas until anti-entropy (~minutes);
        #   - response-phase zero-byte disconnects (RemoteDisconnected):
        #     the keep-alive race; retry only idempotent methods (GET) —
        #     a POST may have been processed before the connection died,
        #     and replaying e.g. a create turns success into a conflict.
        # Upper layers own non-idempotent recovery (executor replica
        # retry, member monitor), so surfacing the POST error is correct.
        from .. import failpoints

        for attempt in (0, 1):
            sent = False
            # Starts True so an exception INSIDE _conn (connect refused,
            # DNS) keeps any-method retry: a failed connection attempt
            # provably never reached the peer. Overwritten with the real
            # freshness once _conn returns (False = pooled keep-alive).
            fresh = True
            try:
                # Inside the try: an injected send fault (OSError) takes the
                # SAME classification path as a real one — it is retried
                # only when the policy below says a real fault would be.
                # The peer's netloc rides along so chaos tests can target
                # one node's link (drop/latency/flaky) and leave the rest
                # of the cluster healthy.
                failpoints.fire("client-send", target=parts.netloc)
                conn, fresh = self._conn(parts.scheme, parts.netloc)
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                self._drop_conn(parts.scheme, parts.netloc)
                retryable = (not sent and (fresh or method == "GET")) or (
                    method == "GET"
                    and isinstance(e, (http.client.RemoteDisconnected,
                                       http.client.BadStatusLine,
                                       ConnectionResetError))
                )
                if attempt == 0 and retryable and not isinstance(
                        e, TimeoutError):
                    continue
                raise ClientError(f"{method} {url}: {e}") from e
            if resp.will_close:
                # Server asked to close (send_error, HTTP/1.0 downgrade):
                # http.client would silently auto-reconnect WITHOUT our
                # TCP_NODELAY setup — evict so the next call rebuilds.
                self._drop_conn(parts.scheme, parts.netloc)
            else:
                self._touch_conn(parts.scheme, parts.netloc)
            if resp.status >= 400:
                detail = data.decode(errors="replace")
                raise ClientError(
                    f"{method} {url}: {resp.status} {detail}", status=resp.status
                )
            if want_headers:
                return data, {k.lower(): v for k, v in resp.getheaders()}
            return data

    def last_transport(self) -> str:
        """Which path the calling thread's most recent _request rode —
        'mux' or 'http'. query_node tags its remote span with it so
        traces show per-hop which transport carried the request."""
        return getattr(self._local, "transport", "http")

    # ---------------------------------------------------------------- query

    def query_node(self, node, index: str, query: str,
                   shards: Optional[Sequence[int]] = None, remote: bool = True,
                   deadline: Optional[float] = None,
                   epoch: Optional[int] = None, trace=None,
                   tenant: Optional[str] = None) -> List[Any]:
        """Execute PQL on a peer restricted to its shards (http/client.go
        QueryNode). `deadline` is the coordinator's REMAINING budget in
        seconds; it rides X-Pilosa-Deadline so the peer aborts its own
        device dispatches at the same cutoff. `epoch` is the sender's
        routing epoch (X-Pilosa-Epoch): a peer that has advanced past it
        and no longer serves the requested shards answers 409 instead of
        a hole from a migrated/GC'd fragment. `trace` is the caller's
        remote-hop Span (obs.Span): the trace id rides X-Pilosa-Trace so
        the peer records into the same cross-node tree, and the peer's
        X-Pilosa-Trace-Summary response header is spliced back as the
        hop's child spans."""
        from . import wire

        params = {"remote": "true"} if remote else {}
        url = f"{_node_url(node)}/index/{index}/query"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        body = json.dumps({"query": query, "shards": list(shards) if shards else None}).encode()
        extra = {}
        if deadline is not None:
            extra["X-Pilosa-Deadline"] = f"{max(deadline, 0.0):.6f}"
        if epoch is not None:
            extra["X-Pilosa-Epoch"] = str(int(epoch))
        if trace is not None:
            extra["X-Pilosa-Trace"] = trace.wire_id()
        if tenant is not None:
            # QoS identity rides the hop so the data node's trace spans
            # carry the same tenant tag (budget charging itself stays on
            # the coordinator: forwarded sub-queries bypass admission).
            extra["X-Pilosa-Tenant"] = tenant
        extra = extra or None
        raw, resp_headers = self._request(
            "POST", url, body, accept=wire.CONTENT_TYPE,
            extra_headers=extra, want_headers=True, idempotent=True)
        if trace is not None:
            trace.tag(transport=self.last_transport())
            summary = resp_headers.get("x-pilosa-trace-summary")
            if summary:
                trace.splice(summary)
        # Binary data plane when the peer speaks it (packed bitplanes);
        # JSON fallback keeps mixed-version clusters working.
        if wire.is_wire(raw):
            try:
                return wire.decode_results(raw)
            except (ValueError, KeyError, TypeError, struct.error) as e:
                # A corrupt body is a NODE fault, whatever shape the
                # corruption takes (bad spans, truncated frame, missing
                # header fields): status 0 routes it through the
                # executor's replica-retry classification instead of
                # killing the whole query.
                raise ClientError(f"corrupt wire body from {url}: {e!r}") from e
        data = json.loads(raw)
        if "error" in data:
            # The peer executed the request and rejected it: a deterministic
            # application error, not node death. status=400 lets callers
            # (executor retry logic) distinguish it from transport failures
            # (status=0) and server faults (5xx).
            raise ClientError(data["error"], status=400)
        return [deserialize_remote(r) for r in data["results"]]

    def query(self, host: str, index: str, query: str, **params) -> dict:
        """Public query against a host; returns the raw JSON response."""
        url = f"{_node_url(host)}/index/{index}/query"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return json.loads(self._request("POST", url, query.encode(), "text/plain"))

    # --------------------------------------------------------------- schema

    def create_index(self, host, index: str, options: Optional[dict] = None) -> dict:
        body = json.dumps({"options": options or {}}).encode()
        return json.loads(self._request("POST", f"{_node_url(host)}/index/{index}", body))

    def create_field(self, host, index: str, field: str, options: Optional[dict] = None) -> dict:
        body = json.dumps({"options": options or {}}).encode()
        return json.loads(
            self._request("POST", f"{_node_url(host)}/index/{index}/field/{field}", body)
        )

    def ensure_index(self, host, index: str, options: Optional[dict] = None) -> None:
        try:
            self.create_index(host, index, options)
        except ClientError as e:
            if "exists" not in str(e).lower():
                raise

    def ensure_field(self, host, index: str, field: str, options: Optional[dict] = None) -> None:
        try:
            self.create_field(host, index, field, options)
        except ClientError as e:
            if "exists" not in str(e).lower():
                raise

    def schema(self, host) -> List[dict]:
        return json.loads(self._request("GET", f"{_node_url(host)}/schema"))["indexes"]

    def status(self, host) -> dict:
        return json.loads(self._request("GET", f"{_node_url(host)}/status"))

    def shards_max(self, host) -> Dict[str, int]:
        return json.loads(self._request("GET", f"{_node_url(host)}/internal/shards/max"))["standard"]

    # --------------------------------------------------------------- import

    def import_node(self, node, index: str, field: str, shard: int,
                    row_ids, column_ids, timestamps=None) -> None:
        body = json.dumps({
            "shard": shard,
            "rowIDs": [int(r) for r in row_ids],
            "columnIDs": [int(c) for c in column_ids],
            "timestamps": timestamps,
            "remote": True,
        }).encode()
        self._request("POST", f"{_node_url(node)}/index/{index}/field/{field}/import", body)

    # Marks a request as already admitted by the sending node's scheduler:
    # the receiver skips re-admission (the body cannot carry remote:true —
    # the translation primary must still run its own owner fan-out).
    FORWARDED_HEADER = {"X-Pilosa-Forwarded": "1"}

    def import_keys_node(self, node, index: str, field: str,
                         row_ids, column_ids, row_keys, column_keys, timestamps) -> None:
        """Forward a key-mode import to the translation primary."""
        body = json.dumps({
            "rowIDs": list(row_ids) if row_ids is not None and not row_keys else None,
            "columnIDs": list(column_ids) if column_ids is not None and not column_keys else None,
            "rowKeys": list(row_keys) if row_keys else None,
            "columnKeys": list(column_keys) if column_keys else None,
            "timestamps": list(timestamps) if timestamps else None,
        }).encode()
        self._request("POST", f"{_node_url(node)}/index/{index}/field/{field}/import",
                      body, extra_headers=self.FORWARDED_HEADER)

    def import_value_keys_node(self, node, index: str, field: str,
                               column_keys, values) -> None:
        """Forward a key-mode value import to the translation primary."""
        body = json.dumps({
            "columnKeys": list(column_keys),
            "values": [int(v) for v in values],
        }).encode()
        self._request("POST", f"{_node_url(node)}/index/{index}/field/{field}/import",
                      body, extra_headers=self.FORWARDED_HEADER)

    def import_value_node(self, node, index: str, field: str, shard: int,
                          column_ids, values) -> None:
        body = json.dumps({
            "shard": shard,
            "columnIDs": [int(c) for c in column_ids],
            "values": [int(v) for v in values],
            "remote": True,
        }).encode()
        self._request("POST", f"{_node_url(node)}/index/{index}/field/{field}/import", body)

    def import_bits(self, host, index: str, field: str, bits) -> None:
        """Public bulk import: group (row, col) bits by shard and POST each
        group to an owning node (http/client.go:276 Import). Bits with
        string row/column values go through the key-translation import."""
        from ..constants import SHARD_WIDTH

        if bits and (isinstance(bits[0][0], str) or isinstance(bits[0][1], str)):
            body = json.dumps({
                "rowKeys": [b[0] for b in bits] if isinstance(bits[0][0], str) else None,
                "rowIDs": None if isinstance(bits[0][0], str) else [b[0] for b in bits],
                "columnKeys": [b[1] for b in bits] if isinstance(bits[0][1], str) else None,
                "columnIDs": None if isinstance(bits[0][1], str) else [b[1] for b in bits],
                "timestamps": [b[2] if len(b) > 2 else None for b in bits],
            }).encode()
            self._request("POST", f"{_node_url(host)}/index/{index}/field/{field}/import", body)
            return

        by_shard: Dict[int, List] = {}
        for bit in bits:
            row, col = bit[0], bit[1]
            ts = bit[2] if len(bit) > 2 else None
            by_shard.setdefault(col // SHARD_WIDTH, []).append((row, col, ts))
        by_node: Dict[str, List] = {}
        for shard, group in sorted(by_shard.items()):
            nodes = self.fragment_nodes(host, index, shard)
            target = nodes[0]["uri"] if nodes else host
            body = json.dumps({
                "shard": shard,
                "rowIDs": [b[0] for b in group],
                "columnIDs": [b[1] for b in group],
                "timestamps": [b[2] for b in group],
            }).encode()
            by_node.setdefault(target, []).append(body)
        self._send_import_groups(index, field, by_node)

    def import_values(self, host, index: str, field: str, field_values) -> None:
        from ..constants import SHARD_WIDTH

        if field_values and isinstance(field_values[0][0], str):
            body = json.dumps({
                "columnKeys": [c for c, _ in field_values],
                "values": [int(v) for _, v in field_values],
            }).encode()
            self._request("POST", f"{_node_url(host)}/index/{index}/field/{field}/import", body)
            return

        by_shard: Dict[int, List] = {}
        for col, val in field_values:
            by_shard.setdefault(col // SHARD_WIDTH, []).append((col, val))
        by_node: Dict[str, List] = {}
        for shard, group in sorted(by_shard.items()):
            nodes = self.fragment_nodes(host, index, shard)
            target = nodes[0]["uri"] if nodes else host
            body = json.dumps({
                "shard": shard,
                "columnIDs": [g[0] for g in group],
                "values": [g[1] for g in group],
            }).encode()
            by_node.setdefault(target, []).append(body)
        self._send_import_groups(index, field, by_node)

    def _send_import_groups(self, index: str, field: str,
                            by_node: Dict[str, List]) -> None:
        """POST pre-encoded shard import bodies, nodes in PARALLEL and a
        node's batches in order: each worker thread owns its per-thread
        keep-alive pool, so a multi-node bulk load streams every target
        concurrently instead of serializing the whole import behind one
        node's round trips. Every node is attempted; the first error is
        raised after all sends complete (partial progress is repaired by
        anti-entropy, exactly like the server-side tolerant fan-out)."""
        def run(target, bodies):
            for body in bodies:
                self._request(
                    "POST",
                    f"{_node_url(target)}/index/{index}/field/{field}/import",
                    body)

        if len(by_node) <= 1:
            for target, bodies in by_node.items():
                run(target, bodies)
            return
        from concurrent.futures import ThreadPoolExecutor

        first_error = None
        with ThreadPoolExecutor(max_workers=min(len(by_node), 8)) as pool:
            futs = [pool.submit(run, t, b) for t, b in by_node.items()]
            for f in futs:
                try:
                    f.result()
                except Exception as e:
                    first_error = first_error or e
        if first_error is not None:
            raise first_error

    # ------------------------------------------------------------- internal

    def fragment_nodes(self, host, index: str, shard: int) -> List[dict]:
        url = f"{_node_url(host)}/internal/fragment/nodes?index={index}&shard={shard}"
        return json.loads(self._request("GET", url))

    def fragment_blocks(self, node, index: str, field: str, shard: int,
                        view: str = "standard") -> List[dict]:
        # The reference RPC is view-blind (http/handler.go:1058 hardcodes
        # standard); carrying the view avoids cross-view checksum
        # comparisons when the syncer walks time/bsig views.
        url = (f"{_node_url(node)}/internal/fragment/blocks?"
               f"index={index}&field={field}&view={view}&shard={shard}")
        try:
            return json.loads(self._request("GET", url))["blocks"]
        except ClientError as e:
            if e.status == 404:
                # Replica doesn't have the fragment yet: empty block set, so
                # the syncer pushes everything (client.go:666-668).
                return []
            raise

    def send_block_diff(self, node, index: str, field: str, view: str, shard: int,
                        block: int, sets, clears) -> None:
        """Apply a merged block diff to a replica's exact view. Set/Clear
        PQL (the reference's push, fragment.go:1814-1903) can only reach the
        standard view; non-standard views need a view-addressed write."""
        url = (f"{_node_url(node)}/internal/fragment/block/data?"
               f"index={index}&field={field}&view={view}&shard={shard}&block={block}")
        body = json.dumps({"sets": sets, "clears": clears}).encode()
        self._request("POST", url, body)

    def send_hint_ops(self, node, index: str, field: str, view: str,
                      shard: int, data: bytes) -> None:
        """Deliver one hinted-handoff record (cluster/hints.py): a raw
        run of storage/bitmap.py WAL op records the peer replays into the
        addressed fragment. Idempotent on the receiver, so the client's
        fresh-connection send retry is safe here like everywhere else."""
        url = (f"{_node_url(node)}/internal/fragment/hints?"
               f"index={index}&field={field}&view={view}&shard={shard}")
        self._request("POST", url, data,
                      content_type="application/octet-stream")

    def block_data(self, node, index: str, field: str, view: str, shard: int, block: int) -> dict:
        url = (f"{_node_url(node)}/internal/fragment/block/data?"
               f"index={index}&field={field}&view={view}&shard={shard}&block={block}")
        try:
            return json.loads(self._request("GET", url))
        except ClientError as e:
            if e.status == 404:
                return {"rowIDs": [], "columnIDs": []}
            raise

    # ------------------------------------------------------ live migration

    def migrate_begin(self, uri, index: str, field: str, view: str,
                      shard: int):
        """Open a migration stream for one fragment: returns (header,
        base_bytes) where header carries the session id and the WAL
        position the base corresponds to (cluster/rebalance.py framing)."""
        from ..cluster.rebalance import unpack_framed

        body = json.dumps({"index": index, "field": field, "view": view,
                           "shard": shard}).encode()
        raw = self._request(
            "POST", f"{_node_url(uri)}/internal/migrate/begin", body)
        return unpack_framed(raw)

    def migrate_delta(self, uri, session: str, from_pos=None):
        """Pull the WAL tail appended since `from_pos` (the receiver's
        cursor — sending it makes a retried pull re-read the same chunk,
        never skip one): (header, wal_bytes); header {"restart": true}
        means the source's file layout changed and the stream must begin
        again."""
        from ..cluster.rebalance import unpack_framed

        body = json.dumps({"session": session, "from": from_pos}).encode()
        raw = self._request(
            "POST", f"{_node_url(uri)}/internal/migrate/delta", body)
        return unpack_framed(raw)

    def migrate_freeze(self, uri, index: str, shard: int) -> dict:
        """Cut a shard over on its source: fragments stop accepting
        writes and the source's routing flips to the new owner."""
        body = json.dumps({"index": index, "shard": shard}).encode()
        return json.loads(self._request(
            "POST", f"{_node_url(uri)}/internal/migrate/freeze", body))

    def migrate_close(self, uri, sessions) -> None:
        body = json.dumps({"sessions": list(sessions)}).encode()
        self._request(
            "POST", f"{_node_url(uri)}/internal/migrate/close", body)

    def retrieve_shard_from_uri(self, uri: str, index: str, field: str, view: str, shard: int) -> bytes:
        url = (f"{_node_url(uri)}/internal/fragment/data?"
               f"index={index}&field={field}&view={view}&shard={shard}")
        return self._request("GET", url)

    def send_fragment_data(self, node, index: str, field: str, view: str, shard: int, data: bytes) -> None:
        url = (f"{_node_url(node)}/internal/fragment/data?"
               f"index={index}&field={field}&view={view}&shard={shard}")
        self._request("POST", url, data, "application/octet-stream")

    def send_message(self, node, msg: dict) -> None:
        """Cluster envelope POST (reference http/client.go SendMessage).

        Default wire format is the reference's type-byte + protobuf
        envelope (broadcast.go:52-162, proto/envelope.py); repo-native
        message types ride a JSON extension frame inside it.
        PILOSA_TPU_CLUSTER_JSON=1 forces plain JSON (the debug fallback
        the handler always accepts)."""
        import os

        if os.environ.get("PILOSA_TPU_CLUSTER_JSON") == "1":
            body, ctype = json.dumps(msg).encode(), "application/json"
        else:
            from .proto import envelope

            body, ctype = envelope.encode_message(msg), "application/x-protobuf"
        self._request("POST", f"{_node_url(node)}/internal/cluster/message",
                      body, ctype)

    # ------------------------------------------------------------- cdc + geo

    def cdc_stream(self, host, index: str, from_pos: int,
                   incarnation: Optional[str] = None,
                   timeout: Optional[float] = None,
                   max_bytes: Optional[int] = None):
        """One long-poll chunk of a peer's change stream (GET
        /cdc/stream — the geo tailer's feed). Returns (raw framed
        records, lowercased response headers); the caller reads the
        resume cursor off x-pilosa-cdc-next and the lag anchors off
        x-pilosa-cdc-head-pos/-time. A 410 ClientError means the cursor
        fell behind retention (or the index was recreated): re-seed via
        cdc_bootstrap. Safe to retry: a replayed GET re-reads the same
        positions."""
        url = f"{_node_url(host)}/cdc/stream?index={index}&from={int(from_pos)}"
        if incarnation:
            qinc = urllib.parse.quote(incarnation, safe="")
            url += f"&incarnation={qinc}"
        if timeout is not None:
            url += f"&timeout={timeout}"
        if max_bytes is not None:
            url += f"&max-bytes={int(max_bytes)}"
        return self._request("GET", url, want_headers=True)

    def cdc_bootstrap(self, host, index: str) -> dict:
        return json.loads(self._request(
            "GET", f"{_node_url(host)}/cdc/bootstrap?index={index}"))

    def geo_demote(self, host, leader: str, epoch: int) -> dict:
        """The fencing handshake (POST /geo/demote): tell a deposed
        leader it has been fenced at `epoch` and should re-tail
        `leader`. 409 means the target holds an equal-or-higher epoch."""
        body = json.dumps({"leader": leader, "epoch": int(epoch)}).encode()
        return json.loads(self._request(
            "POST", f"{_node_url(host)}/geo/demote", body))

    def geo_status(self, host) -> dict:
        return json.loads(self._request(
            "GET", f"{_node_url(host)}/geo/status"))

    def translate_data(self, node, offset: int) -> bytes:
        url = f"{_node_url(node)}/internal/translate/data?offset={offset}"
        return self._request("GET", url)

    def attr_diff(self, node, index: str, field: Optional[str], blocks: List[dict]) -> Dict[int, dict]:
        if field:
            url = f"{_node_url(node)}/internal/index/{index}/field/{field}/attr/diff"
        else:
            url = f"{_node_url(node)}/internal/index/{index}/attr/diff"
        data = json.loads(self._request("POST", url, json.dumps({"blocks": blocks}).encode()))
        return {int(k): v for k, v in data["attrs"].items()}
