"""Binary wire codec for node-to-node query results.

Replaces the JSON column-list encoding for remote results (the reference
fans out protobuf QueryResponses, internal/private.proto:5-176;
http/client.go:44). A dense 1M-column Row is ~10MB as a JSON int list but
128KiB as a packed bitplane — and decoding a plane keeps the Row in its
device-plane representation end to end, so the coordinator's reduce step
never re-packs column lists.

Body layout (little-endian):
    <I header_len> <header JSON> <blob bytes>

The header is the small type-tagged structure (valcounts, pairs, scalars
inline); Row results reference spans in the blob section:
    {"type": "row", "attrs": {...}, "segs": [[shard, form, off, len], ...]}
      form 0: uint64 local column ids (sparse segments)
      form 1: packed uint32 plane words, WORDS_PER_ROW of them (dense)

The form is chosen per segment by size: columns win below one-eighth
density (8 bytes/column vs 4 bytes/word).
"""

from __future__ import annotations

import json
import struct
from typing import Any, List, Tuple

import numpy as np

from ..constants import WORDS_PER_ROW
from ..core.cache import Pair
from ..core.row import Row
from ..executor import ValCount
from ..ops import bitplane as bp

CONTENT_TYPE = "application/x-pilosa-remote"
MAGIC = b"PILr"

_FORM_COLUMNS = 0
_FORM_PLANE = 1


def is_wire(data: bytes) -> bool:
    return data[:4] == MAGIC


def encode_results(results: List[Any]) -> bytes:
    header: List[dict] = []
    blobs: List[bytes] = []
    off = 0

    def blob(data: bytes) -> Tuple[int, int]:
        nonlocal off
        blobs.append(data)
        start, off = off, off + len(data)
        return start, len(data)

    for r in results:
        if isinstance(r, Row):
            segs = []
            for shard in sorted(r.segments):
                words = np.ascontiguousarray(np.asarray(r.segments[shard]), dtype=np.uint32)
                n = int(np.bitwise_count(words).sum())
                if n * 8 < words.nbytes:
                    data = bp.unpack_bits(words).astype("<u8").tobytes()
                    form = _FORM_COLUMNS
                else:
                    data = words.astype("<u4").tobytes()
                    form = _FORM_PLANE
                o, ln = blob(data)
                segs.append([int(shard), form, o, ln])
            header.append({"type": "row", "attrs": r.attrs or {}, "segs": segs})
        elif isinstance(r, ValCount):
            header.append({"type": "valcount", "value": r.val, "count": r.count})
        elif isinstance(r, list) and (not r or isinstance(r[0], Pair)):
            header.append({"type": "pairs", "pairs": [p.to_dict() for p in r]})
        elif isinstance(r, bool):
            header.append({"type": "bool", "value": r})
        elif isinstance(r, int):
            header.append({"type": "uint64", "value": int(r)})
        else:
            header.append({"type": "none", "value": None})

    head = json.dumps({"results": header}).encode()
    return MAGIC + struct.pack("<I", len(head)) + head + b"".join(blobs)


def decode_results(data: bytes) -> List[Any]:
    import jax.numpy as jnp

    if not is_wire(data):
        raise ValueError("not a pilosa remote-wire body")
    (head_len,) = struct.unpack_from("<I", data, 4)
    header = json.loads(data[8 : 8 + head_len])
    blob_base = 8 + head_len

    out: List[Any] = []
    for h in header["results"]:
        t = h.get("type")
        if t == "row":
            segments = {}
            for shard, form, o, ln in h.get("segs", []):
                # Bounds-check before slicing: a corrupt offset would
                # otherwise wrap (negative) or silently truncate (past
                # the end) into a wrong-but-plausible column list.
                if (
                    not isinstance(o, int) or not isinstance(ln, int)
                    or isinstance(o, bool) or isinstance(ln, bool)
                    or o < 0 or ln < 0 or blob_base + o + ln > len(data)
                ):
                    raise ValueError(
                        f"bad blob span: off={o!r} len={ln!r} body={len(data)}"
                    )
                raw = data[blob_base + o : blob_base + o + ln]
                if form == _FORM_PLANE:
                    words = np.frombuffer(raw, dtype="<u4")
                    if len(words) != WORDS_PER_ROW:
                        raise ValueError(
                            f"bad plane segment: {len(words)} words"
                        )
                    segments[int(shard)] = jnp.asarray(words.astype(np.uint32))
                else:
                    cols = np.frombuffer(raw, dtype="<u8").astype(np.uint32)
                    segments[int(shard)] = jnp.asarray(bp.pack_bits(cols))
            row = Row(segments)
            row.attrs = h.get("attrs", {})
            out.append(row)
        elif t == "valcount":
            out.append(ValCount(val=h["value"], count=h["count"]))
        elif t == "pairs":
            out.append(
                [Pair(id=p["id"], count=p["count"], key=p.get("key", ""))
                 for p in h["pairs"]]
            )
        elif t in ("bool", "uint64"):
            out.append(h["value"])
        else:
            out.append(None)
    return out
