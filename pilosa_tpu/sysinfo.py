"""System info (port of /root/reference/gopsutil/ SystemInfo).

Uptime, platform, memory — via /proc and the platform module (no
third-party deps; gopsutil equivalent for Linux hosts).
"""

from __future__ import annotations

import os
import platform
from typing import Dict


def _meminfo() -> Dict[str, int]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if parts[0] in ("MemTotal:", "MemFree:", "MemAvailable:"):
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024
    except OSError:
        pass
    return out


def uptime() -> int:
    try:
        with open("/proc/uptime") as f:
            return int(float(f.read().split()[0]))
    except OSError:
        return 0


def system_info() -> dict:
    mem = _meminfo()
    return {
        "OS": platform.system(),
        "platform": platform.platform(),
        "kernelVersion": platform.release(),
        "machine": platform.machine(),
        "pythonVersion": platform.python_version(),
        "memTotal": mem.get("MemTotal", 0),
        "memFree": mem.get("MemFree", 0),
        "hostUptime": uptime(),
        "numCPU": os.cpu_count() or 0,
    }
