"""String key <-> uint64 id translation.

Equivalent of the reference's TranslateFile (translate.go): an append-only
log of (namespace, key, id) entries replayed into in-memory maps on open.
Namespaces are per-index column keys ("i:<index>") and per-field row keys
("f:<index>:<field>"). Ids are 1-based dense sequences per namespace (the
reference's allocator semantics).

Read-only replicas can follow a primary by streaming the log (reference
PrimaryTranslateStore, translate.go:259-310) — see server/client.py.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Dict, List, Optional, Sequence


class TranslateStore:
    def __init__(self, path: Optional[str] = None, read_only: bool = False):
        self.path = path
        self.read_only = read_only
        self._lock = threading.Lock()
        self._key_to_id: Dict[str, Dict[str, int]] = {}
        self._id_to_key: Dict[str, Dict[int, str]] = {}
        self._log = None
        self._size = 0

    # ------------------------------------------------------------ lifecycle

    def open(self) -> "TranslateStore":
        if self.path and os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, pos)
                if pos + 4 + n > len(data):
                    break  # truncated trailing entry
                ns, key, id = json.loads(data[pos + 4 : pos + 4 + n])
                self._apply(ns, key, id)
                pos += 4 + n
            self._size = pos
        if self.path and not self.read_only:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._log = open(self.path, "ab")
        return self

    def close(self) -> None:
        if self._log:
            self._log.close()
            self._log = None

    def _apply(self, ns: str, key: str, id: int) -> None:
        self._key_to_id.setdefault(ns, {})[key] = id
        self._id_to_key.setdefault(ns, {})[id] = key

    def _append(self, ns: str, key: str, id: int) -> None:
        if self._log:
            entry = json.dumps([ns, key, id]).encode()
            self._log.write(struct.pack("<I", len(entry)) + entry)
            self._log.flush()
            self._size += 4 + len(entry)

    # ----------------------------------------------------------- translate

    def _create(self, ns: str, keys: Sequence[str]) -> List[int]:
        from .errors import TranslateStoreReadOnlyError

        out = []
        with self._lock:
            m = self._key_to_id.setdefault(ns, {})
            for key in keys:
                id = m.get(key)
                if id is None:
                    if self.read_only:
                        raise TranslateStoreReadOnlyError(ns)
                    id = len(m) + 1
                    self._apply(ns, key, id)
                    self._append(ns, key, id)
                out.append(id)
        return out

    def translate_columns_to_uint64(self, index: str, keys: Sequence[str]) -> List[int]:
        return self._create(f"i:{index}", keys)

    def translate_column_to_string(self, index: str, id: int) -> str:
        return self._id_to_key.get(f"i:{index}", {}).get(id, "")

    def translate_columns_to_string(self, index: str, ids: Sequence[int]) -> List[str]:
        m = self._id_to_key.get(f"i:{index}", {})
        return [m.get(i, "") for i in ids]

    def translate_rows_to_uint64(self, index: str, field: str, keys: Sequence[str]) -> List[int]:
        return self._create(f"f:{index}:{field}", keys)

    def translate_row_to_string(self, index: str, field: str, id: int) -> str:
        return self._id_to_key.get(f"f:{index}:{field}", {}).get(id, "")

    def translate_rows_to_string(self, index: str, field: str, ids: Sequence[int]) -> List[str]:
        m = self._id_to_key.get(f"f:{index}:{field}", {})
        return [m.get(i, "") for i in ids]

    # ---------------------------------------------------------- replication

    def size(self) -> int:
        return self._size

    def read_from(self, offset: int):
        """Raw log bytes from offset (for replica streaming)."""
        if not self.path or not os.path.exists(self.path):
            return b""
        with open(self.path, "rb") as f:
            f.seek(offset)
            return f.read()

    def apply_log(self, data: bytes) -> int:
        """Apply streamed log bytes on a replica; returns bytes consumed."""
        pos = 0
        with self._lock:
            while pos + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, pos)
                if pos + 4 + n > len(data):
                    break
                ns, key, id = json.loads(data[pos + 4 : pos + 4 + n])
                self._apply(ns, key, id)
                pos += 4 + n
            self._size += pos
        return pos
