"""String key <-> uint64 id translation.

Equivalent of the reference's TranslateFile (translate.go): an append-only
binary log of (namespace, key, id) entries with an in-memory *offset* index
(translate.go:733-900 keeps a robin-hood table of log offsets over a 10GB
mmap — key bytes live on disk, memory holds fixed-size offsets). Here the
same shape: an open-addressing int64 offset table for key->id and a per-
namespace offset array for id->key; every lookup reads the entry lazily
from the log (pread / in-memory tail). Memory cost is ~16 bytes per key
regardless of key length, so billion-key stores fit.

Namespaces are per-index column keys ("i:<index>") and per-field row keys
("f:<index>:<field>"). Ids are 1-based dense sequences per namespace (the
reference's allocator semantics).

Read-only replicas follow a primary by streaming the log (reference
PrimaryTranslateStore, translate.go:259-310) — see server/client.py.

Log entry layout (little-endian):
    <I payload_len> <Q id> <H ns_len> <ns bytes> <key bytes>
Legacy JSON-framed logs (round 1) are detected and migrated on open.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from array import array
from hashlib import blake2b as _blake2b
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_HDR = struct.Struct("<I")
_ENT = struct.Struct("<QH")


class _OffsetTable:
    """Linear-probe open-addressing map: key bytes -> log offset. Stores
    only int64 offsets; key comparison reads the log through `read_key`."""

    __slots__ = ("slots", "n")

    def __init__(self, capacity: int = 1024):
        self.slots = np.full(capacity, -1, dtype=np.int64)
        self.n = 0

    @staticmethod
    def _hash(full_key: bytes) -> int:
        # Deterministic across processes (unlike PYTHONHASHSEED-randomized
        # hash(bytes)) so probe distribution and rebuild cost are
        # reproducible; blake2b is C-speed for the short keys involved.
        return int.from_bytes(_blake2b(full_key, digest_size=8).digest(), "little")

    def _idx(self, h: int) -> int:
        return h % len(self.slots)

    def get(self, full_key: bytes, read_key) -> int:
        """Offset for full_key, or -1."""
        slots = self.slots
        i = self._idx(self._hash(full_key))
        for _ in range(len(slots)):
            off = slots[i]
            if off < 0:
                return -1
            if read_key(int(off)) == full_key:
                return int(off)
            i = (i + 1) % len(slots)
        return -1

    def put(self, full_key: bytes, offset: int, read_key) -> None:
        if (self.n + 1) * 10 > len(self.slots) * 7:  # load factor 0.7
            self._grow(read_key)
        slots = self.slots
        i = self._idx(self._hash(full_key))
        while slots[i] >= 0:
            i = (i + 1) % len(slots)
        slots[i] = offset
        self.n += 1

    def _grow(self, read_key) -> None:
        old = self.slots[self.slots >= 0]
        self.slots = np.full(len(self.slots) * 2, -1, dtype=np.int64)
        slots = self.slots
        for off in old:
            i = self._idx(self._hash(read_key(int(off))))
            while slots[i] >= 0:
                i = (i + 1) % len(slots)
            slots[i] = off

class TranslateStore:
    def __init__(self, path: Optional[str] = None, read_only: bool = False):
        self.path = path
        self.read_only = read_only
        self._lock = threading.Lock()
        self._table = _OffsetTable()
        # ns -> array('q') of entry offsets indexed by id-1 (dense 1-based)
        self._ids: Dict[str, array] = {}
        self._log = None          # append handle (writable stores with a path)
        self._fd: Optional[int] = None  # pread handle over the on-disk log
        self._tail = bytearray()  # entries not yet on disk (read-only stores)
        self._disk_size = 0       # bytes of log on disk (pread range)
        self._size = 0            # total log bytes (disk + tail)

    # ------------------------------------------------------------ lifecycle

    def open(self) -> "TranslateStore":
        if self.path and os.path.exists(self.path):
            if self._is_legacy_log():
                if self.read_only:
                    # A read-only replica must not rewrite shared on-disk
                    # state: decode the legacy log into the in-memory tail
                    # and leave the file untouched (only the store that owns
                    # the append handle migrates).
                    for ns, key, id in self._parse_legacy():
                        off = self._append_raw(self._encode(ns, key, id))
                        self._index_entry(off)
                    return self
                self._migrate_legacy()
            self._fd = os.open(self.path, os.O_RDONLY)
            self._disk_size = os.fstat(self._fd).st_size
            self._build_index()
            if self._size < os.fstat(self._fd).st_size and not self.read_only:
                # Drop a truncated trailing entry (crash mid-write) so the
                # append handle continues at the clean prefix — otherwise
                # every new entry's recorded offset points into garbage.
                os.truncate(self.path, self._size)
        if self.path and not self.read_only:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._log = open(self.path, "ab")
            if self._fd is None:
                self._fd = os.open(self.path, os.O_RDONLY)
        return self

    def _is_legacy_log(self) -> bool:
        """Round-1 logs framed JSON arrays after the length prefix; probe
        the first entry — a binary payload is valid JSON only by freak
        coincidence, and a JSON payload never parses as a sane binary
        entry, so parsing disambiguates."""
        with open(self.path, "rb") as f:
            head = f.read(4)
            if len(head) < 4:
                return False
            (n,) = _HDR.unpack(head)
            payload = f.read(n)
        if len(payload) < n or not payload.startswith(b"["):
            return False
        try:
            entry = json.loads(payload)
        except ValueError:
            return False
        return isinstance(entry, list) and len(entry) == 3

    def _parse_legacy(self) -> List[Tuple[str, str, int]]:
        """Decode a round-1 JSON-framed log into (ns, key, id) entries."""
        entries: List[Tuple[str, str, int]] = []
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 4 <= len(data):
            (n,) = _HDR.unpack_from(data, pos)
            if pos + 4 + n > len(data):
                break
            try:
                ns, key, id = json.loads(data[pos + 4 : pos + 4 + n])
            except ValueError:
                break
            entries.append((ns, key, id))
            pos += 4 + n
        return entries

    def _migrate_legacy(self) -> None:
        """Rewrite a round-1 JSON-framed log in the binary layout."""
        tmp = self.path + ".migrate"
        with open(tmp, "wb") as f:
            for ns, key, id in self._parse_legacy():
                f.write(self._encode(ns, key, id))
        os.replace(tmp, self.path)

    def close(self) -> None:
        if self._log:
            self._log.close()
            self._log = None
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    # ------------------------------------------------------------- log I/O

    @staticmethod
    def _encode(ns: str, key: str, id: int) -> bytes:
        nsb, keyb = ns.encode(), key.encode()
        payload = _ENT.pack(id, len(nsb)) + nsb + keyb
        return _HDR.pack(len(payload)) + payload

    def _entry_at(self, offset: int) -> Tuple[int, str, str]:
        """(id, ns, key) parsed lazily from the log."""
        raw = self._read(offset, 4)
        (n,) = _HDR.unpack(raw)
        payload = self._read(offset + 4, n)
        id, ns_len = _ENT.unpack_from(payload, 0)
        ns = payload[10 : 10 + ns_len].decode()
        key = payload[10 + ns_len :].decode()
        return id, ns, key

    def _full_key_at(self, offset: int) -> bytes:
        raw = self._read(offset, 4)
        (n,) = _HDR.unpack(raw)
        payload = self._read(offset + 4, n)
        (_, ns_len) = _ENT.unpack_from(payload, 0)
        return payload[2 + 8 : 2 + 8 + ns_len] + b"\x00" + payload[10 + ns_len :]

    def _read(self, offset: int, n: int) -> bytes:
        if offset < self._disk_size:
            return os.pread(self._fd, n, offset)
        t = offset - self._disk_size
        return bytes(self._tail[t : t + n])

    def _build_index(self) -> None:
        """One sequential scan of the log; memory gets offsets only."""
        pos = 0
        size = self._disk_size
        while pos + 4 <= size:
            raw = os.pread(self._fd, 4, pos)
            (n,) = _HDR.unpack(raw)
            if pos + 4 + n > size:
                break  # truncated trailing entry
            self._index_entry(pos)
            pos += 4 + n
        self._size = pos
        self._disk_size = pos  # ignore a truncated tail

    def _index_entry(self, offset: int) -> None:
        id, ns, key = self._entry_at(offset)
        self._table.put(f"{ns}\x00{key}".encode(), offset, self._full_key_at)
        ids = self._ids.setdefault(ns, array("q"))
        while len(ids) < id:
            ids.append(-1)
        ids[id - 1] = offset

    def _append_raw(self, entry: bytes) -> int:
        """Write entry bytes to the log (disk or tail); returns its offset."""
        offset = self._size
        if self._log:
            self._log.write(entry)
            self._log.flush()
            self._disk_size += len(entry)
        else:
            self._tail.extend(entry)
        self._size += len(entry)
        return offset

    def _append(self, ns: str, key: str, id: int) -> None:
        offset = self._append_raw(self._encode(ns, key, id))
        self._table.put(f"{ns}\x00{key}".encode(), offset, self._full_key_at)
        ids = self._ids.setdefault(ns, array("q"))
        while len(ids) < id:
            ids.append(-1)
        ids[id - 1] = offset

    # ----------------------------------------------------------- translate

    def _lookup(self, ns: str, key: str) -> int:
        off = self._table.get(f"{ns}\x00{key}".encode(), self._full_key_at)
        if off < 0:
            return 0
        return self._entry_at(off)[0]

    def _key_for(self, ns: str, id: int) -> str:
        ids = self._ids.get(ns)
        if ids is None or not (1 <= id <= len(ids)) or ids[id - 1] < 0:
            return ""
        return self._entry_at(ids[id - 1])[2]

    def _create(self, ns: str, keys: Sequence[str]) -> List[int]:
        from .errors import TranslateStoreReadOnlyError

        out = []
        with self._lock:
            for key in keys:
                id = self._lookup(ns, key)
                if id == 0:
                    if self.read_only:
                        raise TranslateStoreReadOnlyError(ns)
                    id = len(self._ids.get(ns, ())) + 1
                    self._append(ns, key, id)
                out.append(id)
        return out

    def translate_columns_to_uint64(self, index: str, keys: Sequence[str]) -> List[int]:
        return self._create(f"i:{index}", keys)

    def translate_column_to_string(self, index: str, id: int) -> str:
        return self._key_for(f"i:{index}", id)

    def translate_columns_to_string(self, index: str, ids: Sequence[int]) -> List[str]:
        return [self._key_for(f"i:{index}", i) for i in ids]

    def translate_rows_to_uint64(self, index: str, field: str, keys: Sequence[str]) -> List[int]:
        return self._create(f"f:{index}:{field}", keys)

    def translate_row_to_string(self, index: str, field: str, id: int) -> str:
        return self._key_for(f"f:{index}:{field}", id)

    def translate_rows_to_string(self, index: str, field: str, ids: Sequence[int]) -> List[str]:
        return [self._key_for(f"f:{index}:{field}", i) for i in ids]

    # ---------------------------------------------------------- replication

    def size(self) -> int:
        return self._size

    def read_from(self, offset: int):
        """Raw log bytes from offset (for replica streaming): the binary
        disk prefix followed by the in-memory tail, so size() and the bytes
        served agree even on read-only replicas whose applied entries only
        live in the tail (a chained downstream replica must see them)."""
        out = b""
        if self._fd is not None and offset < self._disk_size:
            out = os.pread(self._fd, self._disk_size - offset, offset)
            offset = self._disk_size
        t = offset - self._disk_size
        if t < len(self._tail):
            out += bytes(self._tail[t:])
        return out

    def apply_log(self, data: bytes) -> int:
        """Apply streamed log bytes on a replica; returns bytes consumed."""
        pos = 0
        with self._lock:
            while pos + 4 <= len(data):
                (n,) = _HDR.unpack_from(data, pos)
                if pos + 4 + n > len(data):
                    break
                offset = self._append_raw(data[pos : pos + 4 + n])
                self._index_entry(offset)
                pos += 4 + n
        return pos
