"""Logger abstraction (port of /root/reference/logger.go)."""

from __future__ import annotations

import logging
import sys


class Logger:
    def __init__(self, name: str = "pilosa_tpu", verbose: bool = False, stream=None):
        self._log = logging.getLogger(name)
        if not self._log.handlers:
            handler = logging.StreamHandler(stream or sys.stderr)
            handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(message)s"))
            self._log.addHandler(handler)
        self._log.setLevel(logging.DEBUG if verbose else logging.INFO)
        self.verbose = verbose

    def info(self, msg, *args):
        self._log.info(msg, *args)

    def debug(self, msg, *args):
        if self.verbose:
            self._log.debug(msg, *args)

    def error(self, msg, *args):
        self._log.error(msg, *args)


class NopLogger:
    verbose = False

    def info(self, msg, *args):
        pass

    def debug(self, msg, *args):
        pass

    def error(self, msg, *args):
        pass


class BufferLogger(NopLogger):
    """Captures log lines for assertions (reference test/logger.go:25)."""

    def __init__(self):
        self.lines = []

    def info(self, msg, *args):
        self.lines.append(("INFO", msg % args if args else msg))

    def debug(self, msg, *args):
        self.lines.append(("DEBUG", msg % args if args else msg))

    def error(self, msg, *args):
        self.lines.append(("ERROR", msg % args if args else msg))
