"""PQL executor: recursive evaluator + distributed map/reduce.

Port of /root/reference/executor.go. Per-shard bitmap math runs on device
bitplanes (ops/bitplane.py via core/fragment.py); this module owns call
dispatch, the shard map/reduce (executor.go:1464-1593), two-phase TopN
(executor.go:524-560), writes, and string-key translation
(executor.go:1595-1699).
"""

from __future__ import annotations

import time as _time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from .constants import MAX_WRITES_PER_REQUEST, SHARD_WIDTH, VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from .core.cache import Pair, add_pairs, sort_pairs
from .core.fragment import TopOptions
from .core.holder import Holder
from .core.row import Row
from .errors import (
    FieldNotFoundError,
    BSIGroupNotFoundError,
    IndexNotFoundError,
    PilosaError,
    QueryError,
    TooManyWritesError,
)
from .obs import NOP_SPAN, current as obs_current, span as obs_span
from .parallel.device_health import DeviceDispatchError
from .pql import parser as pql_parser
from .pql.ast import BETWEEN, Call, Condition, GT, GTE, LT, LTE, NEQ
from .timeq import parse_timestamp, views_by_time_range

DEFAULT_FIELD = "general"
DEFAULT_MIN_THRESHOLD = 1


def _topn_chunk(n_shards: int) -> int:
    """Candidate rows per TopN device program, bounded by BYTES not rows:
    a fixed 512-row chunk is 512 MiB at 8 shards but 16 GiB at 256 shards
    (each row costs n_shards * 128 KiB in the stacked tensor). The byte
    budget (PILOSA_TOPN_CHUNK_BYTES, default 2 GiB) trades dispatches per
    TopN against stacked-tensor working set; row counts pad to pow2 in the
    engine so varied chunk sizes reuse compiled programs. The floor is ONE
    row (not a fixed 16): at extreme shard counts even 16 rows overruns
    the budget (16 rows x 4096 shards x 128 KiB = 8 GiB), and a single
    row per program is the smallest dispatch that still makes progress."""
    import os

    from .constants import WORDS_PER_ROW

    budget = int(os.environ.get("PILOSA_TOPN_CHUNK_BYTES", 2 << 30))
    return max(1, min(512, budget // max(1, n_shards * WORDS_PER_ROW * 4)))

_WRITE_CALLS = {"Set", "Clear", "SetValue", "SetRowAttrs", "SetColumnAttrs"}


def _is_node_failure(e) -> bool:
    """True when a ClientError indicates the NODE failed (connect/transport
    error carries status 0, server fault is 5xx) rather than the REQUEST
    (4xx application errors are deterministic: the peer is healthy and
    every replica would answer the same). A deadline-expiry 503 is the
    REQUEST's budget running out on a healthy peer — one client's tight
    deadline must not mark nodes unavailable and poison routing."""
    status = getattr(e, "status", 0)
    if status == 503 and ("deadline exceeded" in str(e)
                          or "write consistency" in str(e)):
        # Deadline expiry is the REQUEST's budget dying on a healthy peer;
        # a write-consistency 503 is the PEER's own replica set being
        # degraded — both are deterministic answers from a live node, not
        # evidence the node itself failed.
        return False
    return status == 0 or status >= 500


@dataclass
class ExecOptions:
    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    column_attrs: bool = False
    # Per-request time budget (sched/deadline.py), installed at admission.
    # Checked before every device dispatch and every remote fan-out hop so
    # an expired query stops consuming device time instead of pinning
    # threads; the REMAINING budget rides forwarded requests' headers.
    deadline: Optional[Any] = None
    # Sender's routing epoch on forwarded requests (live rebalance,
    # cluster/rebalance.py): when this node has advanced past it AND no
    # longer serves a requested shard, the request 409s so the sender
    # re-routes once — never an empty answer from a migrated/GC'd shard.
    epoch: Optional[int] = None
    # LOCAL routing epoch captured by execute() before the stale-epoch
    # gate (remote requests only): the post-gather re-check in _fan_out
    # compares against this anchor, so a cutover committing anywhere in
    # the window from gate to gather end — translation, or an earlier
    # call of a multi-call query — is still detected. Anchoring inside
    # _fan_out would capture a post-cutover epoch and miss the GC.
    entry_epoch: Optional[int] = None
    # Point-in-time read (cdc/): execute against fragments materialized
    # at this CDC position (base image + op replay, cdc/pit.py) instead
    # of live storage. Read-only, node-local, requires cdc.enabled.
    at_position: Optional[int] = None
    # Bounded-staleness read (geo/, X-Pilosa-Max-Staleness header): on a
    # geo follower, serve locally only when replication lag <= this many
    # seconds, else raise StaleReadError (409) carrying the current lag.
    # No-op on a leader or non-geo node: local state is the source of
    # truth there, never stale (docs/geo-replication.md).
    max_staleness: Optional[float] = None
    # QoS budget identity (X-Pilosa-Tenant header, default: the index
    # name). Tags the query's trace so the per-tenant ledger
    # (sched/qos.py) can attribute the measured device cost, and rides
    # forwarded requests' headers so data-node spans carry it too.
    tenant: Optional[str] = None


class _NoDeviceHealth:
    """Ladder stub for the shadow executor: never route to the device."""

    @staticmethod
    def plan(sig):
        return "shard"


class _NoDeviceEngine:
    """Engine stub installed on the point-in-time shadow executor
    (_execute_at_position): refuses every fast-path gate, forcing the
    host per-shard map/reduce walk. Historical fragments are pathless
    one-shot materializations — pushing them through the device engine
    would enroll frozen snapshots in resident-stack/generation tracking
    keyed by (index, field, view, shard), colliding with the LIVE
    fragments of the same coordinates."""

    device_health = _NoDeviceHealth()

    @staticmethod
    def supports(call, index=None):
        return False

    @staticmethod
    def host_supports(call):
        return False


_NO_DEVICE_ENGINE = _NoDeviceEngine()


@dataclass
class ValCount:
    """Sum/Min/Max result (reference executor.go:1762-1808)."""

    val: int = 0
    count: int = 0

    def add(self, other: "ValCount") -> "ValCount":
        return ValCount(self.val + other.val, self.count + other.count)

    def smaller(self, other: "ValCount") -> "ValCount":
        if self.count == 0 or (other.val < self.val and other.count > 0):
            return other
        return ValCount(self.val, self.count)

    def larger(self, other: "ValCount") -> "ValCount":
        if self.count == 0 or (other.val > self.val and other.count > 0):
            return other
        return ValCount(self.val, self.count)

    def to_dict(self):
        return {"value": self.val, "count": self.count}


class Executor:
    def __init__(
        self,
        holder: Holder,
        cluster=None,
        client=None,
        translate_store=None,
        max_writes_per_request: int = MAX_WRITES_PER_REQUEST,
        workers: int = 8,
        engine_config=None,
        tier_config=None,
    ):
        from .cluster.node import Cluster

        self.holder = holder
        # Device-engine knobs (parallel.EngineConfig); held here because
        # the engine itself is constructed lazily on first device use.
        self.engine_config = engine_config
        # [tier] residency budgets (tier.TierConfig) + the scheduler's
        # per-index traffic signal for the tier prefetcher; the server
        # wires traffic_fn before any query can build the engine.
        self.tier_config = tier_config
        self.tier_traffic_fn = None
        self.cluster = cluster or Cluster()
        self.client = client
        self.translate_store = translate_store
        self.max_writes_per_request = max_writes_per_request
        self._pool = ThreadPoolExecutor(max_workers=workers) if workers > 1 else None
        self._engine = None  # lazy ShardedQueryEngine
        # Cross-query micro-batcher (sched/batcher.py), wired by the
        # server's scheduler. When present, compatible local count
        # dispatches coalesce into one fused engine launch; None keeps the
        # direct single-query engine path (library/embedded use).
        self.batcher = None
        # Multi-host collective backend (parallel/collective.py), wired by
        # the server. When a jax.distributed job spans the cluster, full-
        # index fast-path queries run as ONE SPMD program over the global
        # mesh instead of the HTTP fan-out; failures fall back to fan-out.
        self.collective = None
        # Queries touching a quarantined fragment (corrupt file moved
        # aside at open, not yet repaired by anti-entropy) are served with
        # that fragment reading as EMPTY rather than erroring — this
        # counter surfaces how often results were degraded (/debug/vars).
        self.quarantined_reads = 0
        # How long a write caught in a live-rebalance cutover window
        # (ShardMovedError locally, 409 from a frozen remote owner) keeps
        # re-routing while the commit broadcast lands, before surfacing a
        # clean retryable error. The server installs
        # [rebalance] cutover-pause-max here.
        self.cutover_wait = 2.0
        # Hinted handoff (cluster/hints.py), wired by the server: when a
        # replica forward is skipped (breaker open) or fails at the
        # transport, the write's captured op batch lands in the peer's
        # durable hint log instead of waiting for the next anti-entropy
        # sweep. None (library use) keeps the skip-and-sweep behavior.
        self.hints = None
        # [replication] section (write-consistency ack gating); None =
        # the reference's ack-on-first-apply behavior.
        self.replication_config = None
        # Geo replication (geo/manager.py), wired by the server when
        # [geo] role != "none": the read-path staleness gate and the
        # follower write fence. None (library/single-cluster use) makes
        # X-Pilosa-Max-Staleness a documented no-op.
        self.geo = None
        from .logger import NopLogger

        self.logger = NopLogger()  # server wires its logger in open()

    @property
    def engine(self):
        if self._engine is None:
            from .parallel.engine import ShardedQueryEngine

            self._engine = ShardedQueryEngine(
                self.holder, config=self.engine_config,
                tier_config=self.tier_config,
                traffic_fn=self.tier_traffic_fn,
                # The device-plane breakers share the [resilience] section
                # with the peer breakers they are modeled on; the cluster's
                # health registry already holds the resolved config, so the
                # lazily-built engine needs no extra plumbing.
                resilience_config=self.cluster.health.config)
        return self._engine

    def close(self) -> None:
        """Release serving resources (thread pools, client sockets)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self._engine is not None:
            self._engine.close()
        # The internal client's per-thread keep-alive pools are registered
        # for exactly this moment: embedded/library users own client
        # lifetime through the executor (close() is idempotent, so the
        # server closing the same shared client again is harmless).
        if self.client is not None and hasattr(self.client, "close"):
            self.client.close()

    @property
    def health(self):
        """Per-peer breaker/budget/latency state (cluster/health.py)."""
        return self.cluster.health


    @property
    def node(self):
        return self.cluster.node

    # ------------------------------------------------------------- execute

    def execute(
        self,
        index: str,
        query,
        shards: Optional[Sequence[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> List[Any]:
        if not index:
            raise PilosaError("index required")
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        if isinstance(query, str):
            with obs_span("parse"):
                query = pql_parser.parse(query)
        if self.max_writes_per_request > 0 and len(query.write_calls()) > self.max_writes_per_request:
            raise TooManyWritesError(
                f"too many writes: {len(query.write_calls())} > {self.max_writes_per_request}"
            )
        opt = opt or ExecOptions()
        if opt.remote and opt.entry_epoch is None:
            opt.entry_epoch = self.cluster.routing_epoch
        if opt.max_staleness is not None and self.geo is not None:
            # Bounded-staleness contract (docs/geo-replication.md):
            # refuse BEFORE translation/dispatch — a 409 with the current
            # lag, never a silently-stale answer. Leaders and non-geo
            # nodes pass unconditionally inside the gate.
            self.geo.check_staleness(opt.max_staleness)
        if self.geo is not None and not opt.remote and query.write_calls():
            # Geo write fence: a follower never accepts an external
            # write (409 pointing at the leader); a leader tallies the
            # accepting epoch. Only the external entry is gated —
            # remote=True forwards were fenced at their coordinator.
            self.geo.check_write()

        for call in query.calls:
            self._translate_call(index, idx, call)

        needs_shards = any(c.name not in _WRITE_CALLS for c in query.calls)
        if not shards and needs_shards:
            shards = list(range(idx.max_shard() + 1))
        shards = list(shards or [])

        if opt.remote and (opt.epoch or 0) < self.cluster.routing_epoch:
            # The sender routed under an older placement than ours. Serving
            # a shard we no longer own would read a migrated (possibly
            # GC'd) fragment as empty — a silent hole. 409 instead; the
            # sender re-routes once on refreshed placement. An UNSTAMPED
            # request counts as epoch 0: a sender that never saw the
            # rebalance (lost the begin broadcast, or predates it) is the
            # stalest possible router, not an exempt one.
            for shard in shards:
                if not self._serves_shard(index, shard):
                    from .errors import StaleRoutingEpochError

                    raise StaleRoutingEpochError(
                        f"shard {shard} of {index} no longer served here "
                        f"(request epoch {opt.epoch} < local "
                        f"{self.cluster.routing_epoch})"
                    )

        if opt.at_position is not None:
            return self._execute_at_position(index, idx, query, shards, opt)

        results = []
        for call in query.calls:
            results.append(self._execute_call(index, call, shards, opt))

        return [
            self._translate_result(index, idx, call, r)
            for call, r in zip(query.calls, results)
        ]

    def _execute_at_position(self, index: str, idx, query, shards, opt):
        """Point-in-time execution: the whole call tree runs against a
        SHADOW executor whose holder materializes every fragment at the
        requested CDC position (cdc/pit.py HistoricalHolder). The shadow
        is a shallow copy with the device/cluster fast paths stubbed out
        — materialized fragments live outside the engine's resident
        stacks and generation tracking, so counts must take the host
        map/reduce walk, and coalescing a frozen-past query with live
        ones would poison the batcher's epoch-keyed groups. Per-shard
        dispatch still uses the shared thread pool: every closure binds
        the shadow, so pool threads see the historical holder too."""
        import copy as _copy

        from .cdc.pit import HistoricalHolder

        cdc = getattr(self.holder, "cdc", None)
        if cdc is None:
            raise QueryError(
                "at-position reads require change capture (cdc.enabled)")
        if query.write_calls():
            raise QueryError("at-position queries must be read-only")
        if opt.remote or len(self.cluster.nodes) > 1:
            # Positions are per-index but assigned per-node: another
            # node's fragments carry DIFFERENT position stamps, so a
            # fanned-out at-position read would mix timelines.
            raise QueryError("at-position reads are node-local")
        # Fast 410 gate before any materialization work.
        cdc.check_position(index, opt.at_position)
        shadow = _copy.copy(self)
        shadow.holder = HistoricalHolder(
            self.holder, cdc, index, opt.at_position)
        shadow.collective = None
        shadow.batcher = None
        shadow.hints = None
        shadow._engine = _NO_DEVICE_ENGINE
        results = []
        for call in query.calls:
            results.append(shadow._execute_call(index, call, shards, opt))
        return [
            self._translate_result(index, idx, call, r)
            for call, r in zip(query.calls, results)
        ]

    def _execute_call(self, index: str, c: Call, shards: List[int], opt: ExecOptions):
        if c.name == "Sum":
            return self._execute_val_count(index, c, shards, opt, "sum")
        if c.name == "Min":
            return self._execute_val_count(index, c, shards, opt, "min")
        if c.name == "Max":
            return self._execute_val_count(index, c, shards, opt, "max")
        if c.name == "Count":
            return self._execute_count(index, c, shards, opt)
        if c.name == "Set":
            return self._execute_set_bit(index, c, opt)
        if c.name == "Clear":
            return self._execute_clear_bit(index, c, opt)
        if c.name == "SetValue":
            self._execute_set_value(index, c, opt)
            return None
        if c.name == "SetRowAttrs":
            self._execute_set_row_attrs(index, c, opt)
            return None
        if c.name == "SetColumnAttrs":
            self._execute_set_column_attrs(index, c, opt)
            return None
        if c.name == "TopN":
            return self._execute_topn(index, c, shards, opt)
        return self._execute_bitmap_call(index, c, shards, opt)

    # ---------------------------------------------------------- collective

    def _collective_ok(self, index: str, shards: List[int], opt: ExecOptions) -> bool:
        """True when the multi-host collective plane should serve this
        query: a jax.distributed job spans the cluster and the query covers
        the full shard range (the collective program always covers all
        shards; subsets go through the fan-out)."""
        c = self.collective
        if c is None or opt.remote or not shards:
            return False
        try:
            if not c.active():
                return False
        except Exception as e:
            # A probe failure routes the query to the HTTP fan-out; record
            # it like every other refusal so a climbing fallback counter
            # stays diagnosable.
            self._collective_fallback(e)
            return False
        idx = self.holder.index(index)
        if idx is None:
            return False
        return set(shards) == set(range(idx.max_shard() + 1))

    def _collective_fallback(self, e) -> None:
        """Record WHY the fast path refused, where the decision was made —
        a climbing CollectiveFallback counter is undiagnosable without it.
        The per-reason breakdown lands in the backend's `collective`
        counter group (/debug/vars) next to its serve counters."""
        self._count_stat("CollectiveFallback")
        if self.collective is not None:
            self.collective.note_fallback(getattr(e, "reason", "error"))
        self.logger.error("collective fallback: %s", e)

    # ----------------------------------------------------------- mapReduce

    def _assign_shards(self, index: str, shards: List[int], exclude=()):
        """Shards -> (local list, {node_id: shards}) using health info.

        Prefers self when a replica (maximizes local device work,
        executor.go:1444-1458); skips nodes in `exclude` and peers whose
        circuit breaker refuses traffic. The breaker gate is consulted
        lazily in placement order and memoized per assignment round, so a
        down peer whose backoff elapsed is admitted for its WHOLE shard
        batch — that one batched request is the half-open probe, and its
        outcome (recorded by the fan-out) decides re-close vs re-open."""
        health = self.cluster.health
        admitted: Dict[str, bool] = {}

        def ok(node_id: str) -> bool:
            if node_id not in admitted:
                admitted[node_id] = health.allow_request(node_id)
            return admitted[node_id]

        local: List[int] = []
        remote: Dict[str, List[int]] = {}
        for shard in shards:
            nodes = self.cluster.shard_nodes(index, shard)
            owner = None
            if any(n.id == self.node.id for n in nodes) and (
                self.node.id not in exclude
            ):
                owner = self.node
            else:
                for n in nodes:  # placement order, like the reference
                    if n.id in exclude:
                        continue
                    if ok(n.id):
                        owner = n
                        break
            if owner is None:
                raise PilosaError(f"no available node owns shard {shard}")
            if owner.id == self.node.id:
                local.append(shard)
            else:
                remote.setdefault(owner.id, []).append(shard)
        return local, remote

    def _map_reduce(self, index: str, shards: List[int], c: Call, opt: ExecOptions, map_fn, reduce_fn):
        """Group shards by owning node; local shards run concurrently on the
        device, remote nodes get one batched query. Failed nodes are marked
        and their shards re-mapped onto replicas (executor.go:1464-1555)."""

        deadline = opt.deadline

        def checked_map(shard):
            # Per-shard deadline gate: mid-map-reduce expiry aborts before
            # the NEXT shard's work rather than draining the whole list.
            if deadline is not None:
                deadline.check("shard map")
            return map_fn(shard)

        def local_runner(local_shards):
            if self._pool is not None and len(local_shards) > 1:
                values = list(self._pool.map(checked_map, local_shards))
            else:
                values = [checked_map(s) for s in local_shards]
            result = None
            for v in values:
                result = v if result is None else reduce_fn(result, v)
            return result

        return self._fan_out(index, shards, c, opt, local_runner, reduce_fn)

    def _count_stat(self, name: str) -> None:
        """stats.count guarded for library use (Holder(None) has no stats
        client); the ladder counters must not be the thing that breaks a
        degraded query."""
        if self.holder.stats is not None:
            self.holder.stats.count(name, 1)

    def _serves_shard(self, index: str, shard: int) -> bool:
        """True when this node serves (index, shard) under the CURRENT
        routing view — the one predicate behind every stale-placement
        gate (entry 409, receiver/local post-gather re-checks), kept in
        one place so the epoch gates cannot drift apart."""
        return any(n.id == self.node.id
                   for n in self.cluster.shard_nodes(index, shard))

    def _fan_out(self, index, shards, c, opt, local_runner, reduce_fn):
        from .server.client import ClientError

        # A remote (forwarded) execution runs EXACTLY the shards it was
        # handed — no ownership re-check (executor.go:1476-1480). The
        # coordinator chose them; re-deriving placement here would silently
        # drop shards whenever membership views differ mid-transition.
        if opt.remote:
            if not shards:
                return None
            # Same mid-gather hazard the local batch below guards: the
            # entry gate passed, but a cutover committing AFTER it can GC
            # a moved shard's fragment mid-read so it reads as silently
            # empty. Compare against the epoch execute() anchored BEFORE
            # the gate (a snapshot taken here could already be
            # post-cutover — translation and earlier calls of a
            # multi-call query sit inside the window); a moved shard
            # means the result may hold a hole, so 409 back to the
            # sender for its free re-route.
            epoch_at_entry = opt.entry_epoch
            if epoch_at_entry is None:
                epoch_at_entry = self.cluster.routing_epoch
            v = local_runner(list(shards))
            if self.cluster.routing_epoch != epoch_at_entry:
                moved = [s for s in shards
                         if not self._serves_shard(index, s)]
                if moved:
                    if self.holder.stats is not None:
                        self.holder.stats.count("RemoteEpochReread", 1)
                    from .errors import StaleRoutingEpochError

                    raise StaleRoutingEpochError(
                        f"shards {sorted(moved)} of {index} moved during "
                        f"forwarded execution (epoch {epoch_at_entry} -> "
                        f"{self.cluster.routing_epoch})"
                    )
            return v

        trace = obs_current()
        reduce_acc = [0.0]
        if trace is not None:
            # One "reduce" span per fan-out (accumulated merge cost), not
            # one span per reduce_fn call — merges interleave with
            # gathers and per-merge spans would be noise.
            t_fanout = _time.monotonic()
            inner_reduce = reduce_fn

            def reduce_fn(a, b, _f=inner_reduce):
                t0 = _time.monotonic()
                r = _f(a, b)
                reduce_acc[0] += _time.monotonic() - t0
                return r

        result = None
        failed: set = set()
        app_error = None
        pending = list(shards)
        while pending:
            # Epoch BEFORE the placement read: the dispatch stamp and the
            # local re-check below must reflect the routing decision, not
            # the epoch at send time. Stamping the CURRENT epoch would let
            # a cutover that lands between assign and dispatch defeat the
            # receiver's stale-epoch gate (sender epoch caught up, stale
            # placement rides along) — the receiver would serve a shard
            # whose fragment it already GC'd as silently empty. An epoch
            # that advances right after this read only causes a spurious
            # 409 + free re-route, the safe direction.
            epoch_at_assign = self.cluster.routing_epoch
            try:
                local, remote = self._assign_shards(index, pending, exclude=failed)
            except PilosaError:
                if app_error is not None:
                    # Owners exhausted chasing a deterministic 4xx (e.g.
                    # schema lag on every replica): the application error is
                    # the real story, not "no available node".
                    raise app_error
                raise
            pending = []
            if local:
                if opt.deadline is not None:
                    opt.deadline.check("local dispatch")
                v = local_runner(local)
                moved = [] if self.cluster.routing_epoch == epoch_at_assign else [
                    s for s in local if not self._serves_shard(index, s)
                ]
                if moved:
                    # A live-rebalance cutover committed since this batch
                    # was assigned: post-commit GC may have removed a
                    # moved shard's fragment mid-read, so it read as
                    # EMPTY — a silent hole, not an error. Discard this
                    # batch and re-run it on refreshed placement (the
                    # moved shards dispatch to their new owner next
                    # round).
                    if self.holder.stats is not None:
                        self.holder.stats.count("LocalEpochReread", 1)
                    pending.extend(local)
                elif v is not None:
                    result = v if result is None else reduce_fn(result, v)
            for node_id, node_shards in remote.items():
                if opt.remote:
                    continue  # remote calls are restricted to local shards
                node = self.cluster.node_by_id(node_id)
                kw = {}
                if epoch_at_assign:
                    # Stamp the epoch the placement decision was made
                    # under (only once a rebalance has ever advanced it —
                    # duck-typed test clients without the parameter keep
                    # working untouched). See the capture above: the
                    # current epoch could have caught up with the
                    # receiver's after a mid-flight cutover, masking the
                    # stale placement from its 409 gate.
                    kw["epoch"] = epoch_at_assign
                if opt.deadline is not None:
                    # Abort before the hop, and forward only the REMAINING
                    # budget so the peer never works past our cutoff. The
                    # kwarg rides only when a deadline exists, so duck-typed
                    # test clients without the parameter keep working.
                    opt.deadline.check("remote fan-out")
                    kw["deadline"] = opt.deadline.remaining()
                if opt.tenant is not None:
                    # Tenant identity rides the hop (trace attribution on
                    # the peer); kwarg only when set so duck-typed test
                    # clients without the parameter keep working.
                    kw["tenant"] = opt.tenant
                try:
                    v = self._remote_dispatch(node, index, c, node_shards, kw)
                except ClientError as e:
                    if opt.deadline is not None and opt.deadline.expired():
                        # The peer failed while OUR budget ran out (its
                        # forwarded budget is a slice of ours, so a peer
                        # expiry implies ours): abort cleanly as a deadline
                        # miss instead of spending the corpse of the budget
                        # chasing replicas or re-marking healthy nodes.
                        opt.deadline.check("remote fan-out")
                    if not _is_node_failure(e):
                        # 4xx: the peer executed and rejected the query.
                        # The node is TRANSPORT-healthy, so this counts as
                        # breaker success (a half-open probe answered with
                        # an app error must re-close, not wedge HALF_OPEN
                        # until probe_ttl) — but the error may be transient
                        # schema lag, so try the shards on a replica first
                        # and only surface it once owners are exhausted.
                        self.health.record_success(node_id)
                        app_error = app_error or e
                        failed.add(node_id)
                        if getattr(e, "status", 0) == 409:
                            # Routing conflict (live-rebalance cutover):
                            # ONE free re-route on refreshed placement —
                            # this is a placement change, not survivor
                            # load amplification, so it must not drain
                            # the retry budget into a retry storm.
                            if self.holder.stats is not None:
                                self.holder.stats.count(
                                    "StaleEpochReroute", 1)
                            pending.extend(node_shards)
                            continue
                        if not self.health.try_spend_retry():
                            # Budget drained: surface the rejection now
                            # instead of adding replica load.
                            raise app_error
                        pending.extend(node_shards)
                        continue
                    # The breaker already advanced inside _remote_dispatch
                    # (opens after breaker_failures consecutive transport
                    # failures; default 1 matches executor.go:1498-1508
                    # mark-dead-on-first-failure). Re-map the shards onto
                    # replicas — but only within the retry budget, so a
                    # brown-out cannot amplify load onto the survivors.
                    failed.add(node_id)
                    if not self.health.try_spend_retry():
                        raise PilosaError(
                            f"retry budget exhausted re-mapping shards of "
                            f"{node_id}: {e}"
                        )
                    pending.extend(node_shards)
                    continue
                result = v if result is None else reduce_fn(result, v)
        if trace is not None:
            trace.record(
                "executor.fanout",
                (_time.monotonic() - t_fanout) * 1000.0, shards=len(shards))
            trace.record("reduce", reduce_acc[0] * 1000.0)
        return result

    def _remote_dispatch(self, node, index: str, c: Call, node_shards, kw):
        """One batched query to a peer, with per-peer latency accounting
        and (when a worker pool exists) a hedged backup request: if the
        primary hasn't answered within the peer's hedge delay (rolling
        p99 or the configured fixed delay), the same shard batch is fired
        at a replica that also owns every shard in it, and the first good
        response wins. Hedge volume is capped by the health registry."""
        health = self.health
        # Captured HERE (the request thread): hedge legs run on pool
        # threads where the obs contextvar is not set, so the trace
        # object travels by closure and each leg records its own
        # remote:<peer> span (two legs = two spans, honestly).
        trace = obs_current()

        def call(target):
            """One request with health accounting — success AND transport
            failure are recorded HERE, whatever thread runs it, so a
            losing hedge leg (or an abandoned primary) still drives its
            peer's breaker even when its exception is never re-raised."""
            t0 = _time.monotonic()
            sp = (trace.span(f"remote:{target.id}", shards=len(node_shards))
                  if trace is not None else NOP_SPAN)
            call_kw = kw if trace is None else {**kw, "trace": sp}
            with sp:
                try:
                    res = self.client.query_node(
                        target, index, str(c), shards=node_shards,
                        remote=True, **call_kw,
                    )[0]
                except ClientError as e:
                    if _is_node_failure(e):
                        health.record_failure(target.id)
                    raise
            health.record_success(target.id, _time.monotonic() - t0)
            return res

        from .server.client import ClientError

        if self._pool is None or not health.hedge_enabled():
            return call(node)
        hedge_node = self._hedge_replica(index, node, node_shards)
        if hedge_node is None:
            return call(node)
        from concurrent.futures import (
            FIRST_COMPLETED, TimeoutError as FuturesTimeout, wait,
        )

        primary = self._pool.submit(call, node)
        try:
            # A fast primary failure raises here and takes the normal
            # replica-retry classification path.
            return primary.result(timeout=health.hedge_delay(node.id))
        except FuturesTimeout:
            pass
        if not health.allow_hedge():
            return primary.result()
        hedge = self._pool.submit(call, hedge_node)
        futures = {primary, hedge}
        errors = {}
        while futures:
            done, futures = wait(futures, return_when=FIRST_COMPLETED)
            for fut in done:
                err = fut.exception()
                if err is None:
                    if fut is hedge:
                        health.note_hedge_won()
                    return fut.result()
                errors[fut] = err
        # Both legs failed: surface the PRIMARY's error so the caller's
        # retry classification re-maps the shards it actually assigned
        # (the hedge leg's failure was already recorded by call()).
        raise errors.get(primary) or errors[hedge]

    def _hedge_replica(self, index: str, primary, node_shards):
        """A routable peer (breaker closed, not self, not the primary)
        that owns EVERY shard in the batch, or None. Shard batches group
        by owner, so replicas usually align; when they don't, hedging is
        skipped rather than splitting the batch."""
        health = self.health
        common = None
        for shard in node_shards:
            ids = {n.id for n in self.cluster.shard_nodes(index, shard)}
            common = ids if common is None else common & ids
            if not common or common == {primary.id}:
                return None
        for nid in sorted(common):
            if nid in (primary.id, self.node.id) or health.is_down(nid):
                continue
            n = self.cluster.node_by_id(nid)
            if n is not None:
                return n
        return None

    # ------------------------------------------------------------- bitmaps

    def _execute_bitmap_call(self, index: str, c: Call, shards: List[int], opt: ExecOptions) -> Row:
        def map_fn(shard):
            return self._execute_bitmap_call_shard(index, c, shard)

        def reduce_fn(prev, v):
            prev.merge(v)
            return prev

        row = self._batched_or_map_reduce(
            index, c, shards, opt, "bitmap", map_fn, reduce_fn
        )
        if row is None:
            row = Row()

        if c.name == "Row" and not opt.exclude_row_attrs:
            idx = self.holder.index(index)
            if idx is not None:
                field_name = c.field_arg()
                fld = idx.field(field_name)
                if fld is not None:
                    row_id, ok = c.uint_arg(field_name)
                    if ok:
                        row.attrs = fld.row_attr_store.attrs(row_id)
        if opt.exclude_columns:
            row.segments = {}
        return row

    def _execute_bitmap_call_shard(self, index: str, c: Call, shard: int) -> Row:
        if c.name == "Row":
            return self._execute_row_shard(index, c, shard)
        if c.name == "Difference":
            return self._execute_nary_shard(index, c, shard, "difference")
        if c.name == "Intersect":
            return self._execute_nary_shard(index, c, shard, "intersect")
        if c.name == "Union":
            return self._execute_nary_shard(index, c, shard, "union")
        if c.name == "Xor":
            return self._execute_nary_shard(index, c, shard, "xor")
        if c.name == "Range":
            return self._execute_range_shard(index, c, shard)
        raise QueryError(f"unknown call: {c.name}")

    def _fragment(self, index: str, field: str, view: str, shard: int):
        """Read-path fragment lookup. A quarantined fragment (corrupt file
        moved aside at open, repair pending) is returned as-is — its
        storage is empty, so reads degrade to empty instead of erroring —
        but the touch is counted so operators can see degraded results."""
        frag = self.holder.fragment(index, field, view, shard)
        if frag is not None and frag.quarantined:
            self.quarantined_reads += 1
        return frag

    def _execute_row_shard(self, index: str, c: Call, shard: int) -> Row:
        field_name = c.field_arg()
        fld = self.holder.field(index, field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise QueryError("Row() must specify row")
        frag = self._fragment(index, field_name, VIEW_STANDARD, shard)
        if frag is None:
            return Row()
        return frag.row(row_id)

    def _execute_nary_shard(self, index: str, c: Call, shard: int, op: str) -> Row:
        if not c.children and op in ("difference", "intersect"):
            raise QueryError(f"empty {c.name} query is currently not supported")
        rows = [self._execute_bitmap_call_shard(index, ch, shard) for ch in c.children]
        if not rows:
            return Row()
        out = rows[0]
        for r in rows[1:]:
            out = getattr(out, op)(r)
        return out

    def _execute_range_shard(self, index: str, c: Call, shard: int) -> Row:
        if c.has_condition_arg():
            return self._execute_bsi_range_shard(index, c, shard)

        field_name = c.field_arg()
        fld = self.holder.field(index, field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise QueryError("Range() must specify row")
        start = c.args.get("_start")
        end = c.args.get("_end")
        if not isinstance(start, str) or not isinstance(end, str):
            raise QueryError("Range() start/end time required")
        start_t, end_t = parse_timestamp(start), parse_timestamp(end)
        q = fld.time_quantum()
        if not q:
            return Row()
        row = Row()
        for view_name in views_by_time_range(VIEW_STANDARD, start_t, end_t, q):
            frag = self._fragment(index, field_name, view_name, shard)
            if frag is not None:
                row.merge(frag.row(row_id))
        return row

    def _execute_bsi_range_shard(self, index: str, c: Call, shard: int) -> Row:
        if len(c.args) == 0:
            raise QueryError("Range(): condition required")
        if len(c.args) > 1:
            raise QueryError("Range(): too many arguments")
        (field_name, cond), = c.args.items()
        if not isinstance(cond, Condition):
            raise QueryError(f"Range(): expected condition argument, got {cond!r}")
        fld = self.holder.field(index, field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        bsig = fld.bsi_group(field_name)
        if bsig is None:
            raise BSIGroupNotFoundError(field_name)
        frag = self._fragment(index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, shard)

        if cond.op == NEQ and cond.value is None:  # != null
            return frag.not_null(bsig.bit_depth()) if frag else Row()

        if cond.op == BETWEEN:
            predicates = cond.int_slice_value()
            if len(predicates) != 2:
                raise QueryError("Range(): BETWEEN condition requires exactly two integer values")
            lo, hi, out_of_range = bsig.base_value_between(*predicates)
            if out_of_range or frag is None:
                return Row()
            if predicates[0] <= bsig.min and predicates[1] >= bsig.max:
                return frag.not_null(bsig.bit_depth())
            return frag.range_between(bsig.bit_depth(), lo, hi)

        if not isinstance(cond.value, int) or isinstance(cond.value, bool):
            raise QueryError("Range(): conditions only support integer values")
        value = cond.value
        base, out_of_range = bsig.base_value(cond.op, value)
        if out_of_range and cond.op != NEQ:
            return Row()
        if frag is None:
            return Row()
        # Full-range LT/GT collapse to not-null (executor.go:938-948).
        if (
            (cond.op == LT and value > bsig.max)
            or (cond.op == LTE and value >= bsig.max)
            or (cond.op == GT and value < bsig.min)
            or (cond.op == GTE and value <= bsig.min)
        ):
            return frag.not_null(bsig.bit_depth())
        if out_of_range and cond.op == NEQ:
            return frag.not_null(bsig.bit_depth())
        return frag.range_op(cond.op, bsig.bit_depth(), base)

    # --------------------------------------------------------------- count

    def _execute_count(self, index: str, c: Call, shards: List[int], opt: ExecOptions) -> int:
        if len(c.children) == 0:
            raise QueryError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise QueryError("Count() only accepts a single bitmap input")
        child = c.children[0]

        if self._collective_ok(index, shards, opt):
            supported = self.engine.supports(child, index)
            if supported:
                from .parallel.collective import CollectiveUnavailable

                try:
                    if self.batcher is not None and supported is not True:
                        # Batched collective launch: concurrent queries of
                        # one canonical signature coalesce into ONE
                        # barrier + ONE seq slot + ONE SPMD entry
                        # (sched/batcher.py collective_count). The group
                        # key is the SAME canonical sig the descriptor
                        # carries — one helper, so they cannot drift.
                        comp, _ = supported
                        sig = self.collective._sig_tuple(comp)
                        result = self.batcher.collective_count(
                            self.collective, index, child, sig,
                            deadline=opt.deadline)
                    else:
                        result = int(self.collective.count(index, child))
                    self._count_stat("CollectiveCount")
                    return result
                except CollectiveUnavailable as e:
                    self._collective_fallback(e)

        def map_fn(shard):
            return self._execute_bitmap_call_shard(index, child, shard).count()

        result = self._batched_or_map_reduce(
            index, c, shards, opt, "count", map_fn, lambda a, b: a + b, child=child
        )
        return int(result or 0)

    def _batched_or_map_reduce(self, index, c, shards, opt, kind, map_fn, reduce_fn, child=None):
        """Run locally-owned shards as ONE sharded device program when the
        call tree compiles onto the fast path; remote/unsupported shards use
        the reference-style per-shard map/reduce.

        The device-fault ladder (docs/fault-tolerance.md) sits here: the
        engine's breaker state routes a quarantined SIGNATURE to the
        per-shard XLA walk and an open PLANE to host execution before any
        device work is attempted, and a dispatch that fails mid-request
        falls one rung down for exactly that batch instead of surfacing a
        500 — the breakers make the routing sticky for the next query."""
        target = child if child is not None else c
        supported = self.engine.supports(target, index) if shards else False
        if not supported:
            return self._map_reduce(index, shards, c, opt, map_fn, reduce_fn)
        # supports(call, index) returns the compiled (comp, expr) pair,
        # so the gate and the execution share one AST walk on the
        # hottest serving path (True means a patched/syntactic gate:
        # let the engine compile internally).
        compiled = None if supported is True else supported
        health_sig = compiled[0].plan.sig_tuple if compiled else None
        route = self.engine.device_health.plan(health_sig)
        if route == "shard":
            # Per-signature quarantine: THIS structure keeps failing on
            # the fused path; everything else stays on the device. The
            # half-open probe re-admits it via plan() after backoff.
            self._count_stat("DeviceSigQuarantined")
            inner_map = map_fn

            def map_fn(shard):
                # The trace must show WHICH rung served a degraded query.
                with obs_span("device.dispatch", rung="shard", shard=shard):
                    return inner_map(shard)

            return self._map_reduce(index, shards, c, opt, map_fn, reduce_fn)
        if route == "host":
            # Plane breaker open: the device is sick — no dispatches at
            # all. Counts answer compressed-domain from the host ladder;
            # trees the host evaluator can't express (BSI) take the
            # per-shard walk.
            self._count_stat("DeviceHostRouted")
            if kind == "count" and self.engine.host_supports(target):

                def host_runner(local_shards):
                    if opt.deadline is not None:
                        opt.deadline.check("host execution")
                    with obs_span("device.dispatch", rung="host",
                                  shards=len(local_shards)):
                        return self.engine.host_count(
                            index, target, local_shards, comp_expr=compiled)

                return self._fan_out(
                    index, shards, c, opt, host_runner, reduce_fn)
            return self._map_reduce(index, shards, c, opt, map_fn, reduce_fn)

        def fallback(local_shards):
            # One rung down for THIS batch: the breaker state decides
            # where the NEXT query routes; this query still answers.
            if kind == "count" and self.engine.host_supports(target):
                with obs_span("device.dispatch", rung="host",
                              shards=len(local_shards)):
                    return self.engine.host_count(
                        index, target, local_shards, comp_expr=compiled)
            result = None
            with obs_span("device.dispatch", rung="shard",
                          shards=len(local_shards)):
                for s in local_shards:
                    v = map_fn(s)
                    result = v if result is None else reduce_fn(result, v)
            return result

        def local_runner(local_shards):
            if opt.deadline is not None:
                # "Aborts before the next device dispatch": the gate
                # sits exactly at the engine-launch boundary.
                opt.deadline.check("device dispatch")
            try:
                with obs_span("device.dispatch", rung="device",
                              shards=len(local_shards)) as sp:
                    if sp is not NOP_SPAN and health_sig is not None:
                        sp.tag(sig=str(health_sig))
                    if kind == "count":
                        if self.batcher is not None:
                            return self.batcher.count(
                                index, target, local_shards,
                                comp_expr=compiled, deadline=opt.deadline)
                        return self.engine.count(
                            index, target, local_shards, comp_expr=compiled)
                    if self.batcher is not None:
                        # Generalized micro-batching: bitmap dispatches
                        # coalesce with same-canonical-signature peers
                        # into one fused bitmap_batch launch, exactly
                        # like Counts (docs/query-compiler.md).
                        return self.batcher.bitmap(
                            index, target, local_shards,
                            comp_expr=compiled, deadline=opt.deadline)
                    return self.engine.bitmap(
                        index, target, local_shards, comp_expr=compiled)
            except DeviceDispatchError as e:
                self._count_stat("DeviceLadderFallback")
                self.logger.error(
                    "device dispatch failed (%s), serving %s from the "
                    "fallback rung: %s", e.kind, kind, e)
                return fallback(local_shards)

        return self._fan_out(index, shards, c, opt, local_runner, reduce_fn)

    # --------------------------------------------------------- sum/min/max

    def _execute_val_count(self, index: str, c: Call, shards: List[int], opt: ExecOptions, kind: str) -> ValCount:
        if not c.args.get("field"):
            raise QueryError(f"{c.name}(): field required")
        if len(c.children) > 1:
            raise QueryError(f"{c.name}() only accepts a single bitmap input")

        def map_fn(shard):
            return self._execute_val_count_shard(index, c, shard, kind)

        def reduce_fn(prev, v):
            if kind == "sum":
                return prev.add(v)
            if kind == "min":
                return prev.smaller(v)
            return prev.larger(v)

        field_name = c.args.get("field")
        fld = self.holder.field(index, field_name)
        bsig = fld.bsi_group(field_name) if fld else None
        filter_call = c.children[0] if c.children else None

        if (
            bsig is not None
            and (filter_call is None or self.engine.supports(filter_call, index))
            and self._collective_ok(index, shards, opt)
        ):
            from .parallel.collective import CollectiveUnavailable

            try:
                result = self._collective_val_count(
                    index, field_name, bsig, kind, filter_call
                )
                self._count_stat("CollectiveValCount")
                return result
            except CollectiveUnavailable as e:
                self._collective_fallback(e)

        local_runner = None
        if bsig is not None and (
            filter_call is None or self.engine.supports(filter_call, index)
        ) and self.engine.device_health.plan(None) == "device":
            # Batched path: one device program per node covering all its
            # shards (replaces the per-shard ValCount merge loop). An
            # open plane breaker short-circuits to the per-shard walk
            # BEFORE any dispatch — BSI's bit-sliced kernels have no host
            # twin, so rung 1 is its whole degraded ladder, and paying a
            # failing dispatch (or a watchdog stall) per query on a known-
            # sick device would defeat the breaker.
            depth = bsig.bit_depth()

            def local_runner(local_shards):
                try:
                    with obs_span("device.dispatch", rung="device",
                                  shards=len(local_shards)):
                        out = self.engine.bsi_val_count(
                            index, field_name, kind, depth, local_shards,
                            filter_call
                        )
                except DeviceDispatchError as e:
                    # Ladder rung for BSI: the bit-sliced scan is device
                    # code with no host twin, so the fallback is the
                    # reference per-shard merge for this batch (the
                    # breaker reroutes subsequent queries).
                    self._count_stat("DeviceLadderFallback")
                    self.logger.error(
                        "device BSI dispatch failed (%s), per-shard "
                        "fallback: %s", e.kind, e)
                    result = None
                    with obs_span("device.dispatch", rung="shard",
                                  shards=len(local_shards)):
                        for s in local_shards:
                            v = map_fn(s)
                            result = (v if result is None
                                      else reduce_fn(result, v))
                    return result
                return self._compose_bsi_result(bsig, kind, out)

        if local_runner is not None:
            result = self._fan_out(index, shards, c, opt, local_runner, reduce_fn) or ValCount()
        else:
            result = self._map_reduce(index, shards, c, opt, map_fn, reduce_fn) or ValCount()
        if result.count == 0:
            return ValCount()
        return result

    def _collective_val_count(self, index: str, field_name: str, bsig, kind: str,
                              filter_call) -> ValCount:
        """BSI Sum/Min/Max as ONE SPMD program over the global mesh — the
        cluster-wide replacement for the per-node ValCount merge loop."""
        out = self.collective.bsi_val_count(
            index, field_name, kind, bsig.bit_depth(), filter_call
        )
        return self._compose_bsi_result(bsig, kind, out)

    @staticmethod
    def _compose_bsi_result(bsig, kind: str, out) -> ValCount:
        """ValCount from a bsi_val_count result — ONE implementation of the
        offset/weight math shared by the local-engine and collective
        providers so the two paths cannot silently diverge."""
        depth = bsig.bit_depth()
        if kind == "sum":
            counts = out
            vcount = int(counts[depth])
            if vcount == 0:
                return ValCount()
            vsum = sum((1 << i) * int(counts[i]) for i in range(depth))
            return ValCount(vsum + vcount * bsig.min, vcount)
        bits, count = out
        if count == 0:
            return ValCount()
        from .ops.bitplane import compose_bits

        return ValCount(compose_bits(bits) + bsig.min, count)

    def _execute_val_count_shard(self, index: str, c: Call, shard: int, kind: str) -> ValCount:
        filter_row = None
        if len(c.children) == 1:
            filter_row = self._execute_bitmap_call_shard(index, c.children[0], shard)
        field_name = c.args.get("field")
        fld = self.holder.field(index, field_name)
        if fld is None:
            return ValCount()
        bsig = fld.bsi_group(field_name)
        if bsig is None:
            return ValCount()
        frag = self._fragment(index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, shard)
        if frag is None:
            return ValCount()
        if kind == "sum":
            vsum, vcount = frag.sum(filter_row, bsig.bit_depth())
            return ValCount(val=vsum + vcount * bsig.min, count=vcount)
        if kind == "min":
            v, cnt = frag.min(filter_row, bsig.bit_depth())
        else:
            v, cnt = frag.max(filter_row, bsig.bit_depth())
        return ValCount(val=v + bsig.min if cnt else 0, count=cnt)

    # ----------------------------------------------------------------- TopN

    def _check_chunk_deadline(self, deadline, where: str) -> None:
        """Deadline re-check BETWEEN device-dispatch chunks and after
        gathers: the scheduler gates the budget before a dispatch, but a
        multi-chunk TopN would otherwise finish dead work after the
        budget expires mid-flight. The counter separates 'expired between
        chunks' (work was abandoned early, the good case) from the
        admission-time expiries the scheduler already counts."""
        if deadline is None:
            return
        if deadline.expired():
            self._count_stat("DeadlineMidQuery")
        deadline.check(where)

    def _topn_counts_laddered(self, index, field, ids, local_shards,
                              src_call, need_rc):
        """engine.topn_shard_counts under the device-fault ladder: an
        open plane breaker (or a dispatch failure mid-request) answers
        the same contract from host planes + numpy popcounts instead of
        erroring (docs/fault-tolerance.md). When the src tree has no
        host twin (BSI Range), a DeviceDispatchError propagates — the
        batched local_runners catch it and take the per-shard rung."""
        eng = self.engine
        host_ok = src_call is None or eng.host_supports(src_call)
        if eng.device_health.plan(None) == "device":
            try:
                with obs_span("device.dispatch", rung="device",
                              shards=len(local_shards)):
                    return eng.topn_shard_counts(
                        index, field, ids, local_shards, src_call,
                        need_row_counts=need_rc)
            except DeviceDispatchError as e:
                if not host_ok:
                    raise
                self._count_stat("DeviceLadderFallback")
                self.logger.error(
                    "device TopN dispatch failed (%s), host fallback: %s",
                    e.kind, e)
        elif not host_ok:
            raise DeviceDispatchError(
                "runtime", None,
                "device plane degraded and TopN src is not host-executable")
        else:
            self._count_stat("DeviceHostRouted")
        with obs_span("device.dispatch", rung="host",
                      shards=len(local_shards)):
            return eng.host_topn_shard_counts(
                index, field, ids, local_shards, src_call,
                need_row_counts=need_rc)

    def _execute_topn(self, index: str, c: Call, shards: List[int], opt: ExecOptions) -> List[Pair]:
        ids_arg = self._uint_slice_arg(c, "ids")
        n, _ = c.uint_arg("n")

        pairs = self._execute_topn_shards(index, c, shards, opt)
        if not pairs or ids_arg or opt.remote:
            return pairs

        # Phase 2: refetch full counts for the merged candidate ids
        # (executor.go:524-560). Re-check the budget first: phase 1's
        # gathers may have consumed it, and phase 2 is a full second
        # fan-out of dead work if so.
        self._check_chunk_deadline(opt.deadline, "between TopN phases")
        other = Call(c.name, dict(c.args), list(c.children))
        other.args["ids"] = sorted({p.id for p in pairs})
        trimmed = self._execute_topn_shards(index, other, shards, opt)
        if n and len(trimmed) > n:
            trimmed = trimmed[:n]
        return trimmed

    def _execute_topn_shards(self, index: str, c: Call, shards: List[int], opt: ExecOptions) -> List[Pair]:
        def map_fn(shard):
            return self._execute_topn_shard(index, c, shard)

        local_runner = None
        ids = self._uint_slice_arg(c, "ids")
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            raise QueryError("Tanimoto Threshold is from 1 to 100 only")
        src_call = c.children[0] if c.children else None

        if (
            ids
            and not c.args.get("attrName")
            and not tanimoto
            and max(c.uint_arg("threshold")[0], DEFAULT_MIN_THRESHOLD) <= 1
            and (src_call is None or self.engine.supports(src_call, index))
            and self._collective_ok(index, shards, opt)
        ):
            # Collective phase-2: global candidate counts in one SPMD
            # program per chunk instead of an HTTP fan-out per node.
            # Restricted to threshold<=1 (per-shard MinThreshold semantics
            # need per-shard counts, fragment.go:899-990).
            from .parallel.collective import CollectiveUnavailable

            field_name = c.args.get("_field") or DEFAULT_FIELD
            try:
                pairs: List[Pair] = []
                CHUNK = _topn_chunk(len(shards))  # bounds the (R, S, W) global stack
                for i in range(0, len(ids), CHUNK):
                    if i:
                        self._check_chunk_deadline(
                            opt.deadline, "between collective TopN chunks")
                    chunk = ids[i : i + CHUNK]
                    counts = self.collective.topn_counts(
                        index, field_name, chunk, src_call
                    )
                    pairs.extend(
                        Pair(id=r, count=int(cnt))
                        for r, cnt in zip(chunk, counts)
                        if cnt > 0
                    )
                self._count_stat("CollectiveTopN")
                return sort_pairs(pairs)
            except CollectiveUnavailable as e:
                self._collective_fallback(e)
        if (
            ids
            and src_call is not None  # without src the host rank cache has
            and self.engine.supports(src_call, index)  # exact counts; device adds RTT
        ):
            # Batched phase-2: all candidate counts across all local shards
            # in one device program, preserving per-shard MinThreshold,
            # tanimoto (fragment.go:899-990, 1008-1027 — the coefficient is
            # a pure function of the (row, inter, src) counts the program
            # already produces), and attr-filter semantics (a host-side
            # per-row check against the field's row attr store,
            # fragment.go:922-934 — filtered rows never join the program).
            field_name = c.args.get("_field") or DEFAULT_FIELD
            thr = max(c.uint_arg("threshold")[0], DEFAULT_MIN_THRESHOLD)
            attr_name = c.args.get("attrName", "")
            attr_values = set(c.args.get("attrValues") or [])

            def local_runner(local_shards):
                import math

                run_ids = ids
                if attr_name and attr_values:
                    from .core.fragment import Fragment

                    fld = self.holder.field(index, field_name)
                    store = fld.row_attr_store if fld else None
                    run_ids = [
                        r for r in ids
                        if Fragment.row_attrs_match(store, r, attr_name, attr_values)
                    ]
                    if not run_ids:
                        return []
                # Row (cache) counts only gate tanimoto and thresholds > 1:
                # at thr<=1 the count>0 check below subsumes them, so the
                # common phase-2 skips the candidate-plane popcount pass
                # entirely (engine.topn_shard_counts need_row_counts).
                need_rc = bool(tanimoto) or thr > 1
                row_counts, inter, src_counts = self._topn_counts_laddered(
                    index, field_name, run_ids, local_shards, src_call,
                    need_rc,
                )
                pairs: Dict[int, int] = {}
                for ri, row_id in enumerate(run_ids):
                    for si in range(len(local_shards)):
                        # inter is never None here: this branch requires a
                        # supported src_call.
                        count = int(inter[ri, si])
                        cnt = int(row_counts[ri, si]) if need_rc else count
                        if cnt <= 0 or count == 0:
                            continue
                        if tanimoto:
                            tan = math.ceil(
                                count * 100.0 / (cnt + int(src_counts[si]) - count)
                            )
                            if tan <= tanimoto:
                                continue
                        elif cnt < thr or count < thr:
                            continue
                        pairs[row_id] = pairs.get(row_id, 0) + count
                return [Pair(id=r, count=n) for r, n in pairs.items()]

        elif (
            src_call is not None
            and not ids
            and self.engine.supports(src_call, index)
        ):
            # Batched phase-1: each shard's candidate list comes from its
            # host rank cache (cheap), but the src intersections for the
            # UNION of candidates across all local shards run as ONE device
            # program — the per-fragment fallback pays a device round trip
            # per plane chunk per shard (seconds through a remote runtime).
            # Heap semantics stay exact: Fragment.top replays them from the
            # precomputed per-shard counts (fragment.go:899-990). Tanimoto
            # (the ChEMBL workload, docs/examples.md:321-328) and attr
            # filters ride this path too: the coefficient needs only the
            # per-shard src popcount the same program produces, and attr
            # filtering is a host-side candidate check.
            field_name = c.args.get("_field") or DEFAULT_FIELD
            n_arg, _ = c.uint_arg("n")
            thr = max(c.uint_arg("threshold")[0], DEFAULT_MIN_THRESHOLD)
            topn_opt = TopOptions(
                n=n_arg,
                min_threshold=thr,
                filter_name=c.args.get("attrName", ""),
                filter_values=c.args.get("attrValues") or [],
                tanimoto_threshold=tanimoto,
            )

            def local_runner(local_shards):
                frags = []
                union: List[int] = []
                seen = set()
                for s in local_shards:
                    frag = self._fragment(index, field_name, VIEW_STANDARD, s)
                    if frag is None:
                        continue
                    cands = frag.top_candidates(topn_opt)
                    frags.append((frag, cands))
                    for r, _ in cands:
                        if r not in seen:
                            seen.add(r)
                            union.append(r)
                if not frags or not union:
                    return []
                shard_list = [f.shard for f, _ in frags]
                inter_by_shard: Dict[int, Dict[int, int]] = {
                    s: {} for s in shard_list
                }
                src_count_by_shard: Dict[int, int] = {}
                CHUNK = _topn_chunk(len(shard_list))  # bounds the gather working set
                for i in range(0, len(union), CHUNK):
                    if i:
                        # Between chunks AND after the previous chunk's
                        # gather: a budget that died mid-TopN stops here
                        # (503) instead of finishing dead device work.
                        self._check_chunk_deadline(
                            opt.deadline, "between TopN chunks")
                    chunk = union[i : i + CHUNK]
                    # Ranking uses the cache counts already attached to the
                    # candidates; the device program only computes the src
                    # intersections (need_row_counts=False).
                    _, inter, src_counts = self._topn_counts_laddered(
                        index, field_name, chunk, shard_list, src_call,
                        False,
                    )
                    for si, s in enumerate(shard_list):
                        src_count_by_shard[s] = int(src_counts[si])
                    for ri, r in enumerate(chunk):
                        for si, s in enumerate(shard_list):
                            inter_by_shard[s][r] = int(inter[ri, si])
                out: List[Pair] = []
                for frag, cands in frags:
                    counts = {
                        r: inter_by_shard[frag.shard].get(r, 0) for r, _ in cands
                    }
                    out.extend(frag.top(
                        topn_opt, inter_counts=counts,
                        src_count=src_count_by_shard[frag.shard],
                    ))
                return add_pairs([], out)

        if local_runner is not None:
            # Last rung for a batch neither the device nor the host
            # evaluator could serve (e.g. degraded plane + BSI src): the
            # reference per-shard TopN walk, same one _map_reduce runs.
            batched_runner = local_runner

            def guarded_runner(local_shards):
                try:
                    return batched_runner(local_shards)
                except DeviceDispatchError as e:
                    self._count_stat("DeviceLadderFallback")
                    self.logger.error(
                        "batched TopN unavailable (%s), per-shard rung: %s",
                        e.kind, e)
                    out = []
                    for s in local_shards:
                        out = add_pairs(out, map_fn(s))
                    return out

            result = self._fan_out(
                index, shards, c, opt, guarded_runner, add_pairs) or []
        else:
            result = self._map_reduce(index, shards, c, opt, map_fn, add_pairs) or []
        return sort_pairs(result)

    def _execute_topn_shard(self, index: str, c: Call, shard: int) -> List[Pair]:
        field_name = c.args.get("_field") or DEFAULT_FIELD
        n, _ = c.uint_arg("n")
        attr_name = c.args.get("attrName", "")
        row_ids = self._uint_slice_arg(c, "ids")
        min_threshold, _ = c.uint_arg("threshold")
        attr_values = c.args.get("attrValues") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            raise QueryError("Tanimoto Threshold is from 1 to 100 only")

        src = None
        if len(c.children) == 1:
            src = self._execute_bitmap_call_shard(index, c.children[0], shard)
        elif len(c.children) > 1:
            raise QueryError("TopN() can only have one input bitmap")

        frag = self._fragment(index, field_name, VIEW_STANDARD, shard)
        if frag is None:
            return []
        return frag.top(
            TopOptions(
                n=n,
                src=src,
                row_ids=row_ids,
                min_threshold=min_threshold or DEFAULT_MIN_THRESHOLD,
                filter_name=attr_name,
                filter_values=attr_values,
                tanimoto_threshold=tanimoto,
            )
        )

    @staticmethod
    def _uint_slice_arg(c: Call, key: str) -> List[int]:
        v = c.args.get(key)
        if v is None:
            return []
        if not isinstance(v, list):
            raise QueryError(f"invalid call.Args[{key}]: {v!r}")
        return [int(x) for x in v]

    # --------------------------------------------------------------- writes

    def _forward_tolerant(self, node, send, errors, note_app_error,
                          what: str = "", hint=None):
        """THE per-target write-tolerance step (one implementation for
        the single-shard and the group fan-outs): breaker short-circuit
        (don't pay a connect timeout per write; an elapsed backoff makes
        this forward the half-open probe), transport-vs-4xx
        classification — a 4xx means the replica is alive and rejected
        the write, which is transport-level SUCCESS for the breaker (a
        half-open probe must re-close, not wedge) but is handed to
        `note_app_error` so the caller surfaces the divergence only
        after every other owner got its forward — and health recording.
        Returns the forward's result on success, None otherwise (errors
        are appended, never raised).

        `hint` (hinted handoff, cluster/hints.py) is a callable(node) ->
        bool that appends this write's captured op batch to the peer's
        durable hint log; it runs when the forward is skipped at the
        breaker or fails at the transport, so a dead replica costs an
        O(batch) disk append — never a connect timeout — and the missed
        write replays when the peer returns. While a peer has UNDELIVERED
        hints, later writes append behind them even though the breaker
        would admit a send: per-peer FIFO keeps replay order identical to
        coordinator apply order, so a drain can never resurrect a bit
        that a post-recovery write already cleared. A hinted forward
        still counts as NOT applied for write-consistency accounting."""
        from .server.client import ClientError

        if hint is not None and self.hints is not None \
                and self.hints.pending(node.id):
            if hint(node):
                self._count_stat("WriteForwardHinted")
                errors.append(
                    f"{node.id}{what}: hinted (queued behind pending "
                    "handoff)")
                return None
            # Hint append refused (byte budget / disk fault): fall through
            # to the direct forward — applying out of order beats dropping
            # the write, and anti-entropy owns the reconciliation either
            # way (the refused append flagged the shard for priority sync).
        if not self.health.allow_request(node.id):
            self._count_stat("WriteForwardSkipped")
            if hint is not None and hint(node):
                self._count_stat("WriteForwardHinted")
                errors.append(
                    f"{node.id}{what}: unavailable (breaker open; hinted)")
            else:
                errors.append(f"{node.id}{what}: unavailable (breaker open)")
            return None
        try:
            res = send(node)
        except ClientError as e:
            if not _is_node_failure(e):
                self.health.record_success(node.id)
                note_app_error(e)
                errors.append(f"{node.id}: {e}")
                return None
            self.health.record_failure(node.id)
            self._count_stat("WriteForwardFailed")
            if hint is not None and hint(node):
                self._count_stat("WriteForwardHinted")
            errors.append(f"{node.id}: {e}")
            return None
        self.health.record_success(node.id)
        return res if res is not None else True

    def _write_required(self, n_owners: int) -> int:
        """Owners that must APPLY before a write acks ([replication]
        write-consistency): 1 without config (the reference behavior)."""
        cfg = self.replication_config
        return 1 if cfg is None else cfg.required_owners(n_owners)

    def _write_level(self) -> str:
        cfg = self.replication_config
        return "one" if cfg is None else cfg.write_consistency

    def tolerant_owner_fanout(self, index: str, shard: int, remote: bool,
                              local_fn, forward_fn, on_forward_ok=None,
                              hint=None):
        """THE write-tolerance policy, shared by PQL writes and bulk
        imports (executor.go:1109): apply locally FIRST (arming the
        caller's hint capture with this write's op bytes), forward to
        every other owner, hint-or-skip dead owners (hinted handoff
        replays the miss when the peer returns; anti-entropy remains the
        backstop), finish the whole loop before surfacing a deterministic
        4xx rejection (so one lagging replica cannot cause extra
        divergence on the others), then gate the ack on the configured
        write-consistency level: a write that applied on fewer owners
        than `one|quorum|all` requires surfaces as a typed retryable 503
        (errors.WriteConsistencyError) AFTER hints were enqueued for the
        missed owners — the applied copies stand, there is no rollback
        (docs/durability.md "Write-path consistency").

        Live-rebalance cutovers surface here as ShardMovedError (the
        local fragment froze) or a 409 from a frozen remote owner: the
        write re-routes on refreshed placement — re-applying to an owner
        that already took it is an idempotent set/clear — and keeps
        retrying up to `cutover_wait` while the commit broadcast lands,
        so a write racing the cutover follows the shard to its new owner
        instead of failing. Past the cap it surfaces clean (retryable)."""
        from .errors import ShardMovedError, WriteConsistencyError

        deadline = _time.monotonic() + (0.0 if remote else
                                        max(self.cutover_wait, 0.0))
        while True:
            try:
                applied, total, errors = self._owner_fanout_once(
                    index, shard, remote, local_fn, forward_fn,
                    on_forward_ok, hint)
            except PilosaError as e:
                mid_cutover = isinstance(e, ShardMovedError) or (
                    getattr(e, "status", 0) == 409)
                if not mid_cutover or _time.monotonic() >= deadline:
                    raise
                if self.holder.stats is not None:
                    self.holder.stats.count("CutoverWriteWait", 1)
                _time.sleep(0.02)
                continue
            if remote:
                # Forwarded leg: the COORDINATOR owns level accounting
                # (our `applied` counts the forwarder's owners as
                # fictitious applies).
                return
            required = self._write_required(total)
            if applied < required:
                self._count_stat("WriteConsistencyUnmet")
                raise WriteConsistencyError(
                    f"applied on {applied}/{total} owners of {index}/"
                    f"shard {shard}, level {self._write_level()!r} "
                    f"requires {required}: " + "; ".join(errors),
                    level=self._write_level(), required=required,
                    applied=applied,
                )
            return

    def _owner_fanout_once(self, index, shard, remote, local_fn, forward_fn,
                           on_forward_ok, hint=None):
        """One fan-out pass; returns (applied, n_owners, errors)."""
        applied = 0
        errors = []
        app_error = [None]

        def note(e):
            app_error[0] = app_error[0] or e

        owners = self.cluster.shard_nodes(index, shard)
        if remote and not any(n.id == self.node.id for n in owners):
            # A forwarded write for a shard this node no longer serves
            # (the sender routed under a pre-cutover placement). The old
            # behavior — count every non-self owner as applied-by-
            # forwarder and ack — SILENTLY DROPPED the write: zero
            # fragments were touched. Raise instead (HTTP 409) so the
            # sender re-routes to the shard's current owner.
            from .errors import ShardMovedError

            raise ShardMovedError(
                f"{index}/shard {shard} is not served by this node")
        # Local apply first (stable otherwise): the caller's hint capture
        # is filled by the local apply, and a forward can miss — and need
        # those bytes — at ANY position in the owner walk. Replicas have
        # no ordering contract among themselves, so the reorder is free.
        for node in sorted(owners, key=lambda n: n.id != self.node.id):
            if node.id == self.node.id:
                local_fn()
                applied += 1
                continue
            if remote:
                applied += 1  # forwarding node already counted the write
                continue
            res = self._forward_tolerant(node, forward_fn, errors, note,
                                         hint=hint)
            if res is None:
                continue
            applied += 1
            if on_forward_ok is not None:
                on_forward_ok(res if res is not True else None)
        if app_error[0] is not None:
            raise app_error[0]
        return applied, len(owners), errors

    def tolerant_group_fanout(self, index: str, shards, remote: bool,
                              apply_local, send_remote,
                              workers: int = 1) -> None:
        """Bulk-import fan-out for MANY shard batches at once: the same
        write-tolerance policy as tolerant_owner_fanout (dead replicas
        skipped + marked, deterministic rejections surfaced only after
        every batch got its chance, failure only when a shard reached NO
        owner), but parallel — local applies run across the worker pool
        and remote forwards are batched PER PEER: one task per node
        streams that node's shard batches sequentially over its
        keep-alive connection while different nodes (and local applies)
        proceed concurrently. `workers` caps how much of the shared pool
        one import may occupy, so a huge load can't starve query fan-out
        of threads. apply_local(shard) / send_remote(node, shard).

        Hinted handoff + consistency: local applies run under hint
        capture (core/fragment.py), and the local wave completes BEFORE
        any remote forward is attempted — a forward that then misses
        enqueues the shard's captured op batch for the dead peer (a shard
        with no local replica degrades to a sync-priority marker). After
        the loop, the same [replication] write-consistency gate as the
        single-shard fan-out applies PER SHARD: any shard under its level
        raises a typed retryable 503 (hints already enqueued, no
        rollback)."""
        import threading

        from .core.fragment import capture_hint_ops

        # Placement resolved up front: one routing decision per import.
        plan = {int(s): self.cluster.shard_nodes(index, int(s)) for s in shards}
        if remote:
            from .errors import ShardMovedError

            for shard, owners in plan.items():
                if not any(n.id == self.node.id for n in owners):
                    # Same silent-drop hazard as the single-shard fanout:
                    # a forwarded batch for a migrated-away shard must
                    # 409 so the sender re-routes, not ack into the void.
                    raise ShardMovedError(
                        f"{index}/shard {shard} is not served by this node")
        applied = {s: 0 for s in plan}
        errors: List[str] = []
        app_error: List[Optional[Exception]] = [None]
        captured: Dict[int, list] = {}  # shard -> [(frag, op_bytes)]
        mu = threading.Lock()

        local_shards: List[int] = []
        node_work: Dict[str, tuple] = {}  # node.id -> (node, [shards])
        for shard, owners in plan.items():
            for node in owners:
                if node.id == self.node.id:
                    local_shards.append(shard)
                elif remote:
                    applied[shard] += 1  # forwarding node counted the write
                else:
                    node_work.setdefault(node.id, (node, []))[1].append(shard)

        def run_local(shard):
            rec: list = []
            try:
                with capture_hint_ops(rec):
                    apply_local(shard)
            except Exception as e:
                # Local failures are deterministic (validation, storage
                # fault): surface after the loop like a replica's 4xx, so
                # one bad batch can't abort the others mid-flight.
                with mu:
                    app_error[0] = app_error[0] or e
                    errors.append(f"local/shard {shard}: {e}")
                return
            with mu:
                captured[shard] = rec
                applied[shard] += 1

        def note_app_error(e):
            with mu:
                app_error[0] = app_error[0] or e

        def hint_for(shard):
            def hint(node):
                if self.hints is None:
                    return False
                with mu:
                    rec = captured.get(shard)
                return self.hints.add(node.id, index, shard, rec)
            return hint

        def run_node(node, shard_list):
            # The per-target tolerance step is _forward_tolerant — the
            # SAME implementation tolerant_owner_fanout uses, so the two
            # fan-outs cannot drift apart on breaker/4xx/hint semantics.
            for shard in shard_list:
                local_errs: List[str] = []
                res = self._forward_tolerant(
                    node, lambda n, s=shard: send_remote(n, s),
                    local_errs, note_app_error, what=f"/shard {shard}",
                    hint=hint_for(shard))
                with mu:
                    errors.extend(local_errs)
                    if res is not None:
                        applied[shard] += 1

        # Two waves — all local applies, THEN remote forwards: a forward
        # can only hint op bytes its shard's local apply has already
        # captured. Locals still parallelize among themselves and per-peer
        # streams still overlap each other; only the local->remote overlap
        # is given up, and that was already bounded by `workers` waves.
        for tasks in ([(run_local, (s,)) for s in local_shards],
                      [(run_node, nw) for nw in node_work.values()]):
            if self._pool is None or workers <= 1 or len(tasks) <= 1:
                for fn, args in tasks:
                    fn(*args)
            else:
                # Bounded waves rather than one submit-all: `workers` caps
                # this import's occupancy of the shared pool.
                cap = max(1, workers)
                for i in range(0, len(tasks), cap):
                    futs = [self._pool.submit(fn, *args)
                            for fn, args in tasks[i:i + cap]]
                    for f in futs:
                        f.result()  # worker exceptions captured inside

        if app_error[0] is not None:
            raise app_error[0]
        if remote:
            # Forwarded leg: the coordinator owns level accounting.
            return
        from .errors import WriteConsistencyError

        under = sorted(
            s for s, n in applied.items()
            if n < self._write_required(len(plan[s])))
        if under:
            self._count_stat("WriteConsistencyUnmet")
            raise WriteConsistencyError(
                f"import applied under level {self._write_level()!r} on "
                f"{index}/shards {under}: " + "; ".join(errors),
                level=self._write_level(),
            )

    def _for_shard_owners(self, index: str, c: Call, shard: int, opt: ExecOptions, local_fn):
        """Apply a PQL write locally and forward to other owners — the
        shared tolerant fan-out with query_node as the transport. The
        local apply runs under a hint capture (core/fragment.py), so a
        missed forward hands the peer's hint log the exact WAL op bytes
        this write produced — every view the write touched (standard plus
        time-quantum views) rides along with no re-derivation."""
        from .core.fragment import capture_hint_ops

        out = {"ret": False}
        captured: list = []

        def local():
            captured.clear()  # cutover retries must not double the batch
            with capture_hint_ops(captured):
                if local_fn():
                    out["ret"] = True

        def forward(node):
            return self.client.query_node(node, index, str(c), remote=True)

        def note(res):
            if res and isinstance(res[0], bool):
                out["ret"] = out["ret"] or res[0]

        def hint(node):
            if self.hints is None:
                return False
            return self.hints.add(node.id, index, shard, captured)

        self.tolerant_owner_fanout(
            index, shard, opt.remote, local, forward, on_forward_ok=note,
            hint=hint,
        )
        return out["ret"]

    def _execute_set_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        field_name = c.field_arg()
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        fld = idx.field(field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise QueryError("Set() row argument required")
        col_id, ok = c.uint_arg("_col")
        if not ok:
            raise QueryError("Set() column argument required")
        timestamp = None
        ts = c.args.get("_timestamp")
        if isinstance(ts, str):
            timestamp = parse_timestamp(ts)
        shard = col_id // SHARD_WIDTH
        return self._for_shard_owners(
            index, c, shard, opt, lambda: fld.set_bit(row_id, col_id, timestamp)
        )

    def _execute_clear_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        field_name = c.field_arg()
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        fld = idx.field(field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise QueryError("Clear() row argument required")
        col_id, ok = c.uint_arg("_col")
        if not ok:
            raise QueryError("Clear() column argument required")
        shard = col_id // SHARD_WIDTH
        return self._for_shard_owners(
            index, c, shard, opt, lambda: fld.clear_bit(row_id, col_id)
        )

    def _execute_set_value(self, index: str, c: Call, opt: ExecOptions) -> None:
        col_id, ok = c.uint_arg("col")
        if not ok:
            # Message parity: executor_test.go:451-458.
            raise QueryError("SetValue() column field 'col' required")
        args = {k: v for k, v in c.args.items() if k != "col"}
        for name, value in args.items():
            fld = self.holder.field(index, name)
            if fld is None:
                raise FieldNotFoundError(name)
            if not isinstance(value, int) or isinstance(value, bool):
                # pilosa.go:42 ErrInvalidBSIGroupValueType.
                raise QueryError("invalid bsigroup value type")
            fld.set_value(col_id, value)
        self._forward_to_all(index, c, opt)

    def _execute_set_row_attrs(self, index: str, c: Call, opt: ExecOptions) -> None:
        field_name = c.args.get("_field")
        fld = self.holder.field(index, field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        row_id, ok = c.uint_arg("_row")
        if not ok:
            raise QueryError("SetRowAttrs() row argument required")
        attrs = {k: v for k, v in c.args.items() if k not in ("_field", "_row")}
        fld.row_attr_store.set_attrs(row_id, attrs)
        self._forward_to_all(index, c, opt)

    def _execute_set_column_attrs(self, index: str, c: Call, opt: ExecOptions) -> None:
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        col, ok = c.uint_arg("_col")
        if not ok:
            raise QueryError("SetColumnAttrs() col argument required")
        attrs = {k: v for k, v in c.args.items() if k not in ("_col", "field")}
        idx.column_attr_store.set_attrs(col, attrs)
        self._forward_to_all(index, c, opt)

    def _forward_to_all(self, index: str, c: Call, opt: ExecOptions) -> None:
        """Fan a write out to every node. The local apply already succeeded,
        so dead peers are marked unavailable and skipped rather than failing
        the request (anti-entropy converges them later); previously one dead
        peer made every attr/value write block on a client timeout and raise."""
        from .server.client import ClientError

        if opt.remote:
            return
        app_error = None
        for node in self.cluster.nodes:
            if node.id == self.node.id:
                continue
            if not self.health.allow_request(node.id):
                self._count_stat("WriteForwardSkipped")
                continue
            try:
                self.client.query_node(node, index, str(c), remote=True)
            except ClientError as e:
                if not _is_node_failure(e):
                    # Deterministic rejection by a live peer: transport
                    # success for the breaker; finish the fan-out (don't
                    # widen divergence), then surface it.
                    self.health.record_success(node.id)
                    app_error = app_error or e
                    continue
                self.health.record_failure(node.id)
                self._count_stat("WriteForwardFailed")
            else:
                self.health.record_success(node.id)
        if app_error is not None:
            raise app_error

    # ---------------------------------------------------------- translation

    def _translate_call(self, index: str, idx, c: Call) -> None:
        """Translate string keys to ids in-place (executor.go:1595-1659).

        Mirrors the reference's key selection exactly: Set/Clear/Row use the
        positional column arg and the field-named row arg; every other call
        uses literal 'col'/'row' args with the field taken from a 'field'
        arg — so e.g. SetValue(col=10, f="x") is NOT key-translated and
        falls through to the BSI type check (executor_test.go:461-466)."""
        store = self.translate_store
        if store is not None:
            if c.name in ("Set", "Clear", "Row"):
                col_key = "_col"
                # Reference ignores FieldArg errors here (fieldName, _ =
                # c.FieldArg()); a missing field is rejected at execution
                # time, not during translation.
                try:
                    field_name = c.field_arg()
                except QueryError:
                    field_name = None
                row_key = field_name
            else:
                col_key = "col"
                # callArgString semantics: a non-string `field` arg reads as
                # "" in the reference, so row translation is skipped and the
                # call is rejected later — not a FieldNotFoundError here.
                fv = c.args.get("field")
                field_name = fv if isinstance(fv, str) else None
                row_key = "row"

            col = c.args.get(col_key)
            if idx.keys():
                if col is not None and not isinstance(col, str):
                    raise QueryError(
                        "column value must be a string when index 'keys' option enabled"
                    )
                if isinstance(col, str) and col != "":
                    # Empty keys are not translated (callArgString != ""
                    # guard); the later uint-arg check rejects the call.
                    c.args[col_key] = store.translate_columns_to_uint64(index, [col])[0]
            elif isinstance(col, str):
                raise QueryError(
                    "string 'col' value not allowed unless index 'keys' option enabled"
                )

            if field_name:
                fld = idx.field(field_name)
                if fld is None:
                    raise FieldNotFoundError(field_name)
                row = c.args.get(row_key)
                if fld.keys():
                    if row is not None and not isinstance(row, str):
                        raise QueryError(
                            "row value must be a string when field 'keys' option enabled"
                        )
                    if isinstance(row, str) and row != "":
                        c.args[row_key] = store.translate_rows_to_uint64(
                            index, field_name, [row]
                        )[0]
                elif isinstance(row, str):
                    raise QueryError(
                        "string 'row' value not allowed unless field 'keys' option enabled"
                    )
        for child in c.children:
            self._translate_call(index, idx, child)

    def _translate_result(self, index: str, idx, c: Call, result):
        store = self.translate_store
        if store is None:
            return result
        if isinstance(result, Row) and idx.keys():
            result.keys = store.translate_columns_to_string(
                index, [int(x) for x in result.columns()]
            )
        if isinstance(result, list) and result and isinstance(result[0], Pair):
            field_name = c.args.get("_field")
            fld = idx.field(field_name) if field_name else None
            if fld is not None and fld.keys():
                result = [
                    Pair(id=p.id, count=p.count,
                         key=store.translate_row_to_string(index, field_name, p.id))
                    for p in result
                ]
        return result
