"""Ingest-path knobs: the [ingest] config section.

Same pattern as [storage]/StorageConfig and [scheduler]/SchedulerConfig —
the section IS the dataclass the layer it governs consumes (server/api.py's
parallel shard fan-out), so knob names and defaults have one source of
truth. stdlib-only so CLI startup stays light. See docs/ingest.md.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class IngestConfig:
    # Max shard batches of one import applied/forwarded concurrently
    # across the executor's worker pool (key-mode imports re-group by
    # shard; multi-node forwards batch per node). <= 1 keeps the serial
    # path. The pool itself is the executor's — this only caps how much
    # of it one import may occupy.
    import_workers: int = 8

    def validate(self) -> "IngestConfig":
        if self.import_workers < 1:
            raise ValueError("ingest.import-workers must be >= 1")
        return self
