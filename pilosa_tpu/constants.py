"""Global constants for pilosa_tpu.

Mirrors the reference's operational envelope (see
/root/reference/fragment.go:48,60,63 and field.go:38-41, cluster.go:40) but
re-expressed for a TPU bitplane layout: a shard is 2^20 columns wide and the
device-side unit of compute is a dense row bitplane of SHARD_WIDTH bits packed
into 32-bit lanes.
"""

import os

# Width of a single shard, in columns (reference: fragment.go:48 ShardWidth).
# Overridable for tests that want tiny device tensors.
SHARD_WIDTH_EXP = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH_EXP", "20"))
SHARD_WIDTH = 1 << SHARD_WIDTH_EXP

# Device bitplane packing: uint32 lanes (population_count-supported on TPU).
BITS_PER_WORD = 32
WORDS_PER_ROW = SHARD_WIDTH // BITS_PER_WORD

# TopN cache (reference: field.go:38-41).
DEFAULT_CACHE_SIZE = 50000
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_NONE = "none"

# Field types (reference: field.go:49-53).
FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"

DEFAULT_FIELD_TYPE = FIELD_TYPE_SET

# Snapshot after this many incremental ops (reference: fragment.go:63).
MAX_OP_N = 2000

# Merkle/anti-entropy hash block size, in rows (reference: fragment.go:60).
HASH_BLOCK_SIZE = 100

# Cluster partitioning (reference: cluster.go:40).
DEFAULT_PARTITION_N = 256

# Max writes allowed in a single /query request (reference: server/config.go:107).
MAX_WRITES_PER_REQUEST = 5000

# View names (reference: view.go:31-35).
VIEW_STANDARD = "standard"
VIEW_BSI_GROUP_PREFIX = "bsig_"

# Time quantum characters, in order (reference: time.go).
TIME_QUANTUM_CHARS = "YMDH"
