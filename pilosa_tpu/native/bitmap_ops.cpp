// Native host-side bitmap kernels (C ABI, loaded via ctypes).
//
// The TPU owns the query hot path (Pallas/XLA bitplane kernels); these are
// the host runtime's compiled kernels: bitplane packing for device upload,
// sorted-container set ops for the cold/roaring path, and popcounts — the
// CPU-fallback tier of the framework (the reference's equivalents are the
// roaring container routines, /root/reference/roaring/roaring.go:1836-3375).
//
// Build: make -C pilosa_tpu/native  (produces libbitmap_ops.so)

#include <cstdint>
#include <cstddef>
#include <cstring>

extern "C" {

// Set bit positions cols[0..n) (each < n_words*32) in a zeroed word buffer.
void pack_bits(const uint32_t* cols, size_t n, uint32_t* words) {
    for (size_t i = 0; i < n; i++) {
        uint32_t c = cols[i];
        words[c >> 5] |= (1u << (c & 31u));
    }
}

// Extract set bit positions from a bitplane; returns count written.
size_t unpack_bits(const uint32_t* words, size_t n_words, uint32_t* out) {
    size_t k = 0;
    for (size_t w = 0; w < n_words; w++) {
        uint32_t v = words[w];
        while (v) {
            uint32_t b = __builtin_ctz(v);
            out[k++] = (uint32_t)(w * 32 + b);
            v &= v - 1;
        }
    }
    return k;
}

uint64_t popcount_words(const uint32_t* words, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += __builtin_popcount(words[i]);
    return total;
}

uint64_t and_count_words(const uint32_t* a, const uint32_t* b, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += __builtin_popcount(a[i] & b[i]);
    return total;
}

// Sorted uint16 container ops (roaring array containers).

uint64_t intersection_count_u16(const uint16_t* a, size_t na,
                                const uint16_t* b, size_t nb) {
    size_t i = 0, j = 0;
    uint64_t n = 0;
    while (i < na && j < nb) {
        uint16_t x = a[i], y = b[j];
        n += (x == y);
        i += (x <= y);
        j += (y <= x);
    }
    return n;
}

size_t intersect_u16(const uint16_t* a, size_t na, const uint16_t* b, size_t nb,
                     uint16_t* out) {
    size_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        uint16_t x = a[i], y = b[j];
        if (x == y) out[k++] = x;
        i += (x <= y);
        j += (y <= x);
    }
    return k;
}

size_t union_u16(const uint16_t* a, size_t na, const uint16_t* b, size_t nb,
                 uint16_t* out) {
    size_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        uint16_t x = a[i], y = b[j];
        if (x < y)      { out[k++] = x; i++; }
        else if (y < x) { out[k++] = y; j++; }
        else            { out[k++] = x; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

size_t difference_u16(const uint16_t* a, size_t na, const uint16_t* b, size_t nb,
                      uint16_t* out) {
    size_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        uint16_t x = a[i], y = b[j];
        if (x < y)      { out[k++] = x; i++; }
        else if (y < x) { j++; }
        else            { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    return k;
}

size_t xor_u16(const uint16_t* a, size_t na, const uint16_t* b, size_t nb,
               uint16_t* out) {
    size_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        uint16_t x = a[i], y = b[j];
        if (x < y)      { out[k++] = x; i++; }
        else if (y < x) { out[k++] = y; j++; }
        else            { i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

}  // extern "C"
