"""ctypes bindings for the native host kernels (bitmap_ops.cpp).

Loads libbitmap_ops.so, building it with `make` on first use if the
toolchain is available. All entry points have numpy fallbacks — the
framework works without the native library, just slower on host paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbitmap_ops.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR], check=True, capture_output=True, timeout=120
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        lib.pack_bits.argtypes = [u32p, ctypes.c_size_t, u32p]
        lib.pack_bits.restype = None
        lib.unpack_bits.argtypes = [u32p, ctypes.c_size_t, u32p]
        lib.unpack_bits.restype = ctypes.c_size_t
        lib.popcount_words.argtypes = [u32p, ctypes.c_size_t]
        lib.popcount_words.restype = ctypes.c_uint64
        lib.and_count_words.argtypes = [u32p, u32p, ctypes.c_size_t]
        lib.and_count_words.restype = ctypes.c_uint64
        lib.intersection_count_u16.argtypes = [u16p, ctypes.c_size_t, u16p, ctypes.c_size_t]
        lib.intersection_count_u16.restype = ctypes.c_uint64
        for name in ("intersect_u16", "union_u16", "difference_u16", "xor_u16"):
            fn = getattr(lib, name)
            fn.argtypes = [u16p, ctypes.c_size_t, u16p, ctypes.c_size_t, u16p]
            fn.restype = ctypes.c_size_t
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


# ------------------------------------------------------------ typed wrappers


def pack_bits(cols: np.ndarray, n_words: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    cols = np.ascontiguousarray(cols, dtype=np.uint32)
    words = np.zeros(n_words, dtype=np.uint32)
    lib.pack_bits(cols, len(cols), words)
    return words


def unpack_bits(words: np.ndarray) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint32)
    total = int(lib.popcount_words(words, len(words)))
    out = np.empty(total, dtype=np.uint32)
    n = lib.unpack_bits(words, len(words), out)
    return out[:n].astype(np.uint64)


def and_count_words(a: np.ndarray, b: np.ndarray) -> Optional[int]:
    """popcount(a & b) over packed uint32 planes (the host hot loop)."""
    lib = load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.uint32)
    b = np.ascontiguousarray(b, dtype=np.uint32)
    return int(lib.and_count_words(a, b, min(len(a), len(b))))


def intersection_count_u16(a: np.ndarray, b: np.ndarray) -> Optional[int]:
    lib = load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.uint16)
    b = np.ascontiguousarray(b, dtype=np.uint16)
    return int(lib.intersection_count_u16(a, len(a), b, len(b)))


def _binop_u16(name: str, a: np.ndarray, b: np.ndarray, out_cap: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.uint16)
    b = np.ascontiguousarray(b, dtype=np.uint16)
    out = np.empty(out_cap, dtype=np.uint16)
    n = getattr(lib, name)(a, len(a), b, len(b), out)
    return out[:n]


def intersect_u16(a, b):
    return _binop_u16("intersect_u16", a, b, min(len(a), len(b)))


def union_u16(a, b):
    return _binop_u16("union_u16", a, b, len(a) + len(b))


def difference_u16(a, b):
    return _binop_u16("difference_u16", a, b, len(a))


def xor_u16(a, b):
    return _binop_u16("xor_u16", a, b, len(a) + len(b))
