"""Dense bitplane ops — the TPU compute core.

A *bitplane* is one fragment row's 2^20 column bits packed into uint32 lanes:
shape (WORDS_PER_ROW,) = (32768,), i.e. 256 sublanes x 128 lanes — a clean VPU
tile. Batches of rows stack to (R, WORDS_PER_ROW). This dense layout replaces
the reference's per-container array/bitmap/run polymorphism
(/root/reference/roaring/roaring.go:988-1061), which is branch-and-pointer
heavy and wrong for a vector unit; roaring survives only as the host/disk
format (storage/bitmap.py).

Everything here is jit-compatible and branch-free: data-dependent choices are
jnp.where on scalar predicates so a whole PQL call tree can be fused into one
XLA program. Counts use lax.population_count on uint32 lanes.

BSI algorithms are the bit-sliced routines of /root/reference/fragment.go:
565-837 (sum/min/max/rangeEQ/NEQ/LT/GT/Between), re-derived for bitplanes.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..constants import BITS_PER_WORD, SHARD_WIDTH

# ------------------------------------------------------------- host packing


def pack_bits(cols: np.ndarray, width: int = SHARD_WIDTH) -> np.ndarray:
    """Pack sorted column ids (< width) into a uint32 bitplane (host).

    Uses the native C++ kernel when built (np.bitwise_or.at is an order of
    magnitude slower); numpy fallback otherwise.
    """
    n_words = width // BITS_PER_WORD
    if len(cols):
        from .. import native

        packed = native.pack_bits(np.asarray(cols, dtype=np.uint32), n_words)
        if packed is not None:
            return packed
    words = np.zeros(n_words, dtype=np.uint32)
    if len(cols):
        cols = np.asarray(cols, dtype=np.uint32)
        np.bitwise_or.at(words, cols >> 5, np.uint32(1) << (cols & np.uint32(31)))
    return words


def unpack_bits(plane: np.ndarray) -> np.ndarray:
    """Bitplane -> ascending uint64 column ids (numpy, host)."""
    plane = np.ascontiguousarray(np.asarray(plane, dtype=np.uint32))
    from .. import native

    if native.available():
        out = native.unpack_bits(plane)
        if out is not None:
            return out
    bits = np.unpackbits(plane.view(np.uint8), bitorder="little")
    return np.flatnonzero(bits).astype(np.uint64)


# ------------------------------------------------------------- basic algebra


def p_and(a, b):
    return jnp.bitwise_and(a, b)


def p_or(a, b):
    return jnp.bitwise_or(a, b)


def p_andnot(a, b):
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def p_xor(a, b):
    return jnp.bitwise_xor(a, b)


def popcount(plane) -> jnp.ndarray:
    """Total set bits. Sums over the trailing word axis; keeps leading axes.

    Per-shard counts fit int32 (<= 2^20 per row; a (R, W) batch sums per-row).
    """
    c = jax.lax.population_count(plane).astype(jnp.int32)
    return jnp.sum(c, axis=-1)


def intersection_count(a, b) -> jnp.ndarray:
    """popcount(a & b) without materializing the intersection."""
    return popcount(jnp.bitwise_and(a, b))


def row_counts(planes, filter_plane=None) -> jnp.ndarray:
    """Per-row counts of a (R, W) stack, optionally ANDed with a (W,) filter.

    This is the TopN inner loop (reference fragment.go:870-1058): all candidate
    rows are counted in one batched popcount instead of a per-row heap walk.
    """
    if filter_plane is not None:
        planes = jnp.bitwise_and(planes, filter_plane[None, :])
    return popcount(planes)


# ----------------------------------------------------------------- BSI ops


def bsi_plane_counts(planes, filter_plane=None) -> jnp.ndarray:
    """Per-plane popcounts for BSI sum (reference fragment.go:565-600).

    planes: (bit_depth + 1, W) — planes[i] is value-bit i, planes[bit_depth]
    is the not-null row. Returns (bit_depth + 1,) int32 counts; the weighted
    sum(2^i * counts[i]) is composed on host in Python ints to avoid overflow.
    """
    return row_counts(planes, filter_plane)


def bsi_min(planes, bit_depth: int, filter_plane=None):
    """Min over a BSI group (reference fragment.go:603-637).

    Returns (bits, count): bits is (bit_depth,) int32 0/1 — bit i of the min —
    and count is how many columns hold that min. Branch-free: each step keeps
    `consider` = columns still able to be minimal.
    """
    consider = planes[bit_depth]
    if filter_plane is not None:
        consider = jnp.bitwise_and(consider, filter_plane)
    bits = []
    for i in range(bit_depth - 1, -1, -1):
        x = p_andnot(consider, planes[i])
        nonzero = popcount(x) > 0
        bits.append(jnp.where(nonzero, 0, 1).astype(jnp.int32))
        consider = jnp.where(nonzero, x, consider)
    bits = jnp.stack(bits[::-1]) if bits else jnp.zeros((0,), jnp.int32)
    return bits, popcount(consider)


def bsi_max(planes, bit_depth: int, filter_plane=None):
    """Max over a BSI group (reference fragment.go:640-657)."""
    consider = planes[bit_depth]
    if filter_plane is not None:
        consider = jnp.bitwise_and(consider, filter_plane)
    bits = []
    for i in range(bit_depth - 1, -1, -1):
        x = jnp.bitwise_and(planes[i], consider)
        nonzero = popcount(x) > 0
        bits.append(jnp.where(nonzero, 1, 0).astype(jnp.int32))
        consider = jnp.where(nonzero, x, consider)
    bits = jnp.stack(bits[::-1]) if bits else jnp.zeros((0,), jnp.int32)
    return bits, popcount(consider)


def bsi_range_eq(planes, bit_depth: int, predicate: int):
    """Columns whose value == predicate (reference fragment.go:683-699)."""
    b = planes[bit_depth]
    for i in range(bit_depth - 1, -1, -1):
        if (predicate >> i) & 1:
            b = jnp.bitwise_and(b, planes[i])
        else:
            b = p_andnot(b, planes[i])
    return b


def bsi_range_neq(planes, bit_depth: int, predicate: int):
    """not-null minus EQ (reference fragment.go:701-714)."""
    return p_andnot(planes[bit_depth], bsi_range_eq(planes, bit_depth, predicate))


def bsi_range_lt(planes, bit_depth: int, predicate: int, allow_equality: bool):
    """Columns whose value < (or <=) predicate (reference fragment.go:716-762)."""
    zero = jnp.zeros_like(planes[bit_depth])
    keep = zero
    b = planes[bit_depth]
    leading_zeros = True
    for i in range(bit_depth - 1, -1, -1):
        row = planes[i]
        bit = (predicate >> i) & 1
        if leading_zeros:
            if bit == 0:
                b = p_andnot(b, row)
                continue
            leading_zeros = False
        if i == 0 and not allow_equality:
            if bit == 0:
                return keep
            return p_andnot(b, p_andnot(row, keep))
        if bit == 0:
            b = p_andnot(b, p_andnot(row, keep))
            continue
        if i > 0:
            keep = jnp.bitwise_or(keep, p_andnot(b, row))
    return b


def bsi_range_gt(planes, bit_depth: int, predicate: int, allow_equality: bool):
    """Columns whose value > (or >=) predicate (reference fragment.go:764-800)."""
    zero = jnp.zeros_like(planes[bit_depth])
    keep = zero
    b = planes[bit_depth]
    for i in range(bit_depth - 1, -1, -1):
        row = planes[i]
        bit = (predicate >> i) & 1
        if i == 0 and not allow_equality:
            if bit == 1:
                return keep
            return p_andnot(b, p_andnot(p_andnot(b, row), keep))
        if bit == 1:
            b = p_andnot(b, p_andnot(p_andnot(b, row), keep))
            continue
        if i > 0:
            keep = jnp.bitwise_or(keep, jnp.bitwise_and(b, row))
    return b


def bsi_range_between(planes, bit_depth: int, pmin: int, pmax: int):
    """Columns with pmin <= value <= pmax (reference fragment.go:812-851)."""
    zero = jnp.zeros_like(planes[bit_depth])
    b = planes[bit_depth]
    keep1 = zero  # GTE side
    keep2 = zero  # LTE side
    for i in range(bit_depth - 1, -1, -1):
        row = planes[i]
        bit1 = (pmin >> i) & 1
        bit2 = (pmax >> i) & 1
        if bit1 == 1:
            b = p_andnot(b, p_andnot(p_andnot(b, row), keep1))
        elif i > 0:
            keep1 = jnp.bitwise_or(keep1, jnp.bitwise_and(b, row))
        if bit2 == 0:
            b = p_andnot(b, p_andnot(row, keep2))
        elif i > 0:
            keep2 = jnp.bitwise_or(keep2, p_andnot(b, row))
    return b


# ----------------------------------------------------- jitted entry points

# Small stable jitted wrappers for direct (non-fused) use. The executor
# compiles whole query trees instead; these serve tests and simple paths.

and_count = jax.jit(intersection_count)
count = jax.jit(popcount)
topn_counts = jax.jit(row_counts)


def compose_bits(bits: np.ndarray) -> int:
    """(bit_depth,) 0/1 vector -> python int value (host, overflow-safe)."""
    return sum((1 << i) for i, b in enumerate(np.asarray(bits)) if b)
