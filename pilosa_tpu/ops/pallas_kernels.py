"""Pallas TPU kernels for the bitmap hot loops.

These are the compiled-kernel tier of the framework — the TPU-native
replacement for the reference's 16 specialized roaring container routines
(/root/reference/roaring/roaring.go:1836-3375). Instead of per-container
array/bitmap/run branches, every op is a grid of VMEM-tiled fused
bitwise+popcount passes over dense uint32 bitplanes:

- fused_intersection_count: popcount(a & b) without materializing a & b in
  HBM (the reference's intersectionCount* family).
- fused_nary_count: popcount over an elementwise tree (and/or/andnot/xor)
  of N planes in one pass — a whole PQL call tree per tile.
- topn_filter_counts: per-row popcount(row & filter) over a stacked row
  tensor (the TopN inner loop, fragment.go:870-1058).

On non-TPU backends (CPU tests) the kernels run in Pallas interpret mode;
`use_pallas()` picks real kernels on TPU. XLA's fusion of the pure-jnp
versions (ops/bitplane.py) is already near-optimal for these elementwise
reductions, so the Pallas path exists to (a) pin the tiling (avoid HBM
round-trips between ops on multi-MiB planes) and (b) serve as the template
for fused multi-op query kernels where XLA's scheduling is not guaranteed.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Words processed per grid step. 8 sublane-rows x 128 lanes x 32 words = a
# (8, 128)-shaped uint32 tile block; BLOCK words = 64 KiB in VMEM per input.
BLOCK = 16384


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _interpret() -> bool:
    return not _on_tpu()


def _pad_to_block(x: jnp.ndarray, block: int) -> jnp.ndarray:
    n = x.shape[-1]
    rem = n % block
    if rem:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, block - rem)]
        x = jnp.pad(x, pad)
    return x


# ------------------------------------------------- fused intersection count


def _count_kernel(a_ref, b_ref, out_ref):
    """One tile: per-lane popcount partials of a & b, accumulated across the
    grid into an (8, 128) VMEM tile (scalar stores to VMEM don't lower on
    TPU; the final scalar reduce happens outside the kernel)."""
    i = pl.program_id(0)
    masked = jnp.bitwise_and(a_ref[:], b_ref[:])
    pc = jax.lax.population_count(masked).astype(jnp.int32)
    partial = jnp.sum(pc.reshape(-1, 8, 128), axis=0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += partial


@jax.jit
def fused_intersection_count(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """popcount(a & b) over flat uint32 planes, fused in VMEM."""
    a = _pad_to_block(a.reshape(-1), BLOCK).reshape(-1, 128)
    b = _pad_to_block(b.reshape(-1), BLOCK).reshape(-1, 128)
    rows_per_block = BLOCK // 128
    grid = (a.shape[0] // rows_per_block,)
    out = pl.pallas_call(
        _count_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows_per_block, 128), lambda i: (i, 0)),
            pl.BlockSpec((rows_per_block, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        interpret=_interpret(),
    )(a, b)
    return jnp.sum(out)


# ------------------------------------------------------- fused n-ary count

# Op codes for the expression tape: (op, lhs_slot, rhs_slot, out_slot).
OP_AND, OP_OR, OP_ANDNOT, OP_XOR = 0, 1, 2, 3
_OPS = {
    OP_AND: jnp.bitwise_and,
    OP_OR: jnp.bitwise_or,
    OP_ANDNOT: lambda x, y: jnp.bitwise_and(x, jnp.bitwise_not(y)),
    OP_XOR: jnp.bitwise_xor,
}


def _nary_count_kernel(tape, n_leaves, *refs):
    """Evaluate a static op tape over leaf tiles, then popcount."""
    *leaf_refs, out_ref = refs
    i = pl.program_id(0)
    slots = [r[:] for r in leaf_refs]
    for op, lhs, rhs in tape:
        slots.append(_OPS[op](slots[lhs], slots[rhs]))
    pc = jax.lax.population_count(slots[-1]).astype(jnp.int32)
    partial = jnp.sum(pc.reshape(-1, 8, 128), axis=0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += partial


@functools.partial(jax.jit, static_argnums=(0,))
def fused_nary_count(tape: tuple, *planes: jnp.ndarray) -> jnp.ndarray:
    """popcount of an expression tree over N planes in ONE VMEM pass.

    `tape` is a tuple of (op, lhs_slot, rhs_slot) ops; slots 0..N-1 are the
    input planes, each op appends a slot, the last slot is counted. The
    whole PQL call tree runs per-tile without HBM round-trips.
    """
    n = len(planes)
    padded = [_pad_to_block(p.reshape(-1), BLOCK).reshape(-1, 128) for p in planes]
    rows_per_block = BLOCK // 128
    grid = (padded[0].shape[0] // rows_per_block,)
    kernel = functools.partial(_nary_count_kernel, tape, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows_per_block, 128), lambda i: (i, 0)) for _ in range(n)
        ],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        interpret=_interpret(),
    )(*padded)
    return jnp.sum(out)


# ------------------------------------------- batched gather + expr + count


# Per-leaf VMEM bytes for one gather block. One grid step holds l leaf
# blocks, double-buffered by the pipeline; keep l*2 blocks well under
# v5e's VMEM so the compiler never spills.
_GATHER_VMEM_BUDGET = 32 << 20


def batched_gather_expr_count(stacked, idxs, expr):
    """Per-query fused gather+expr+popcount: (Q,) int32.

    `stacked` is the resident (U, S, W) uint32 leaf stack, `idxs` is a tuple
    of L (Q,) int32 leaf-slot vectors (one per leaf position of the
    compiled expression), `expr` an elementwise jnp function over L planes
    (a PQL set-op tree). For query q the kernel computes
    popcount(expr(stacked[idxs[0][q]], ..., stacked[idxs[L-1][q]])) summed
    over shards and words.

    The slot vectors are scalar-prefetched so the BlockSpec index maps DMA
    exactly each query's leaf blocks from HBM — the (Q, S, W) gathered
    intermediate the XLA fallback materializes
    (parallel/engine.py:_count_batch_setops) never exists here, which is
    why this kernel beats XLA at HBM-resident sizes: the fallback's gather
    copy multiplies the memory traffic. One grid step covers a whole
    (S, W) leaf plane — a single large contiguous DMA per leaf — unless
    that would blow the VMEM budget, in which case the W axis is chunked.
    Caller is responsible for sharding (single-device stacks only; the
    multi-device mesh path uses the XLA fallback, whose NamedShardings XLA
    partitions).
    """
    u, s, w = stacked.shape
    l = len(idxs)
    q = idxs[0].shape[0]
    assert w % 128 == 0, w
    # Largest W chunk (a multiple of 128 dividing W) whose l
    # double-buffered (S, wc) leaf blocks fit the budget.
    wc = w
    while l * 2 * s * wc * 4 > _GATHER_VMEM_BUDGET and wc % 256 == 0:
        wc //= 2
    n_wb = w // wc

    def kernel(*refs):
        leaf_refs = refs[l:-1]
        out_ref = refs[-1]
        bi = pl.program_id(1)
        planes = tuple(r[0] for r in leaf_refs)  # (s, wc)
        pc = jax.lax.population_count(expr(planes)).astype(jnp.int32)
        pc = pc.reshape(-1, 128)
        if pc.shape[0] % 8:  # tiny test shapes; no-op at real plane widths
            pc = jnp.pad(pc, ((0, 8 - pc.shape[0] % 8), (0, 0)))
        partial = jnp.sum(pc.reshape(-1, 8, 128), axis=0)

        @pl.when(bi == 0)
        def _():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        out_ref[0] += partial

    def leaf_map(j):
        return lambda qi, bi, *idx_refs: (idx_refs[j][qi], 0, bi)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=l,
        grid=(q, n_wb),
        in_specs=[pl.BlockSpec((1, s, wc), leaf_map(j)) for j in range(l)],
        out_specs=pl.BlockSpec((1, 8, 128), lambda qi, bi, *idx_refs: (qi, 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((q, 8, 128), jnp.int32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(*[ix.astype(jnp.int32) for ix in idxs], *([stacked] * l))
    return jnp.sum(out, axis=(1, 2))


# ------------------------------------------------------- TopN row counting


def _topn_kernel(rows_ref, filt_ref, out_ref):
    i = pl.program_id(0)  # word-block index
    masked = jnp.bitwise_and(rows_ref[:], filt_ref[:])
    pc = jax.lax.population_count(masked).astype(jnp.int32)
    partial = jnp.sum(pc, axis=1)  # (R, 128) per-lane partials

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += partial


@jax.jit
def topn_filter_counts(rows: jnp.ndarray, filt: jnp.ndarray) -> jnp.ndarray:
    """Per-row popcount(row & filter): rows (R, W), filter (W,) -> (R,)."""
    r = rows.shape[0]
    rows2 = _pad_to_block(rows, BLOCK)
    filt2 = _pad_to_block(filt.reshape(-1), BLOCK)
    w = rows2.shape[-1]
    rows3 = rows2.reshape(r, w // 128, 128)
    filt3 = filt2.reshape(1, w // 128, 128)
    rows_per_block = BLOCK // 128
    grid = (w // BLOCK,)
    out = pl.pallas_call(
        _topn_kernel,
        out_shape=jax.ShapeDtypeStruct((r, 128), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r, rows_per_block, 128), lambda i: (0, i, 0)),
            pl.BlockSpec((1, rows_per_block, 128), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((r, 128), lambda i: (0, 0)),
        interpret=_interpret(),
    )(rows3, filt3)
    return jnp.sum(out, axis=1)
