"""Pallas TPU kernel tier: the batched gather+expr+popcount hot loop.

This is the compiled-kernel tier of the framework — the TPU-native
replacement for the reference's specialized roaring container routines
(/root/reference/roaring/roaring.go:1836-3375). It deliberately contains
ONE kernel. Dispatch-amortized measurements on a v5 lite chip (loops
inside a single compiled program, RTT subtracted) showed that for pure
elementwise bitwise+popcount reductions XLA's own fusion already runs at
89-97% of HBM bandwidth — a hand-written Pallas pipeline can at best tie,
so earlier fused-elementwise kernels (intersection count, n-ary op tapes,
TopN filter counts) were removed as negative value; ops/bitplane.py's
jnp formulations are the shipped implementation for those.

Where Pallas genuinely wins is the shape XLA handles badly: the batched
per-query GATHER. XLA materializes gathered (Q, S, W) intermediates
(~3x the necessary HBM traffic, measured 224 GB/s of 819 peak);
batched_gather_expr_count DMAs exactly each query's leaf planes via
scalar-prefetched block indices and streams at ~95% of peak.

On non-TPU backends (CPU tests) the kernel runs in Pallas interpret mode;
on TPU the engine gates it in for single-device meshes
(parallel/engine.py:_use_gather_kernel).

BENCH_r03 measured this tier at ~0.7x of the plain-XLA fused path on the
only full TPU capture; the kernel was rewritten around in-kernel
popcount accumulation (VMEM scratch across the W loop, one output write
per query) and k-ary operand evaluation of the canonical plan's
flattened trees. The keep-vs-delete decision rule — beat the XLA
formulation in the next hardware capture (BENCH_r06's pallas_vs_xla) or
be deleted — is recorded in docs/query-compiler.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except (RuntimeError, IndexError):
        # RuntimeError: backend failed to initialize (no TPU runtime);
        # IndexError: zero devices. Both mean "interpret mode" — anything
        # else (a typo here, a jax API break) should surface loudly.
        return False


def _interpret() -> bool:
    return not _on_tpu()


# Per-leaf VMEM bytes for one gather block. One grid step holds l leaf
# blocks, double-buffered by the pipeline; keep l*2 blocks well under
# v5e's VMEM so the compiler never spills.
_GATHER_VMEM_BUDGET = 32 << 20


def batched_gather_expr_count(stacked, idxs, expr):
    """Per-query fused gather+expr+popcount: (Q,) int32.

    `stacked` is the resident (U, S, W) uint32 leaf stack, `idxs` is a tuple
    of L (Q,) int32 leaf-slot vectors (one per leaf position of the
    compiled expression), `expr` an elementwise jnp function over L planes
    (a canonical PQL set-op tree, docs/query-compiler.md). For query q the
    kernel computes
    popcount(expr(stacked[idxs[0][q]], ..., stacked[idxs[L-1][q]])) summed
    over shards and words.

    Two in-kernel tricks close BENCH_r03's gap against plain XLA:

    - **k-ary operand evaluation** (the arXiv:1103.2409 idea applied at
      plane level): the plan compiler flattens associative chains, so
      `expr` reduces ALL L operand planes of a node in one pass over the
      gathered blocks — a k-wide Intersect is k-1 ANDs on VMEM-resident
      data inside one grid step, never a pairwise tree of separate
      kernels with materialized intermediates.
    - **in-kernel popcount accumulation** (the accumulator discipline of
      arXiv:1611.07612's vectorized popcounts): per-block popcount
      partials accumulate in a VMEM scratch accumulator across the whole
      W loop, and the HBM-backed output block is written ONCE per query
      at the last block — the previous formulation read-modified-wrote
      the output block every W chunk.

    The slot vectors are scalar-prefetched so the BlockSpec index maps DMA
    exactly each query's leaf blocks from HBM — the (Q, S, W) gathered
    intermediate the XLA fallback materializes
    (parallel/engine.py:_count_batch_setops) never exists here, which is
    why this kernel beats XLA at HBM-resident sizes: the fallback's gather
    copy multiplies the memory traffic. One grid step covers a whole
    (S, W) leaf plane — a single large contiguous DMA per leaf — unless
    that would blow the VMEM budget, in which case the W axis is chunked.
    The kernel operates on ONE device's arrays: multi-device callers run
    it per device under shard_map on each local (U, S/d, W) shard-block
    and psum the per-query partials (parallel/engine.py
    _count_batch_setops).
    """
    u, s, w = stacked.shape
    l = len(idxs)
    q = idxs[0].shape[0]
    assert w % 128 == 0, w
    # Largest W chunk (a multiple of 128 dividing W) whose l
    # double-buffered (S, wc) leaf blocks fit the budget.
    wc = w
    while l * 2 * s * wc * 4 > _GATHER_VMEM_BUDGET and wc % 256 == 0:
        wc //= 2
    n_wb = w // wc

    def kernel(*refs):
        leaf_refs = refs[l:-2]
        out_ref = refs[-2]
        acc_ref = refs[-1]  # VMEM scratch accumulator, (8, 128) int32
        bi = pl.program_id(1)
        planes = tuple(r[0] for r in leaf_refs)  # (s, wc)
        pc = jax.lax.population_count(expr(planes)).astype(jnp.int32)
        pc = pc.reshape(-1, 128)
        if pc.shape[0] % 8:  # tiny test shapes; no-op at real plane widths
            pc = jnp.pad(pc, ((0, 8 - pc.shape[0] % 8), (0, 0)))
        partial = jnp.sum(pc.reshape(-1, 8, 128), axis=0)

        @pl.when(bi == 0)
        def _():
            acc_ref[...] = partial

        @pl.when(bi != 0)
        def _():
            acc_ref[...] += partial

        @pl.when(bi == n_wb - 1)
        def _():
            out_ref[0] = acc_ref[...]

    def leaf_map(j):
        return lambda qi, bi, *idx_refs: (idx_refs[j][qi], 0, bi)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=l,
        grid=(q, n_wb),
        in_specs=[pl.BlockSpec((1, s, wc), leaf_map(j)) for j in range(l)],
        out_specs=pl.BlockSpec((1, 8, 128), lambda qi, bi, *idx_refs: (qi, 0, 0)),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.int32)],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((q, 8, 128), jnp.int32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(*[ix.astype(jnp.int32) for ix in idxs], *([stacked] * l))
    return jnp.sum(out, axis=(1, 2))
