"""Sharded query engine: one XLA program per query shape over all shards.

This replaces the reference's goroutine-per-shard map loop
(executor.go:1558-1593) for local shards. A PQL bitmap call tree is
compiled once per *structure* into a jitted function over a stacked leaf
tensor of shape (L, S, W) — L leaf rows, S shards sharded over the device
mesh, W bitplane words. XLA fuses the whole tree into one fused
elementwise+popcount kernel per device and inserts ICI collectives for the
scalar reductions. Leaf planes are cached on device between queries and
invalidated by fragment generation counters.

Supported fast-path calls: Row / Intersect / Union / Difference / Xor /
Range(BSI) compositions, Count(...) and per-row TopN candidate counting.
Everything else falls back to the executor's per-shard path.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import failpoints
from ..constants import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD, WORDS_PER_ROW
from ..obs import NOP_SPAN, span as obs_span
from ..core.row import Row
from ..errors import QueryError
from ..ops import bitplane as bp
from ..plan.signature import (
    CompiledPlan, Leaf, cached_plan, resolve_time_range as
    _resolve_time_range,
)
from ..pql.ast import Call
from . import EngineConfig
from .device_health import (
    COMPILE, DeviceDispatchError, DeviceDispatchTimeout, DevicePlaneHealth,
    OOM, classify_device_error,
)
from .mesh import SHARD_AXIS, default_mesh, pad_shards, shard_sharding

def _pop_elems(a: np.ndarray) -> np.ndarray:
    """Elementwise popcounts of a uint32 array for the host execution
    ladder, returned over the uint16 view (same leading shape, last axis
    doubled) so callers sum over the trailing axis/axes for plane
    popcounts. np.bitwise_count is used unconditionally, matching the
    storage/wire layers (storage/bitmap.py, server/wire.py)."""
    return np.bitwise_count(a.view(np.uint16))


def _lower_ir(ir: tuple) -> Callable:
    """Canonical plan IR (plan/signature.py) -> jnp closure over the
    (L, S, W) leaf tuple. The IR is already canonicalized (commutative
    operands sorted, associative chains flattened to k-ary nodes), so
    the lowered program reduces all k operands of a node in one chained
    pass — XLA fuses the whole thing into a single elementwise kernel —
    and a Difference pays ONE complement for its whole subtracting set
    (head AND NOT(OR(tail))) instead of one per operand."""
    kind = ir[0]
    if kind == "leaf":
        i = ir[1]
        return lambda leaves: leaves[i]
    if kind in ("Intersect", "Union", "Xor"):
        subs = [_lower_ir(ch) for ch in ir[1]]
        op = {
            "Intersect": jnp.bitwise_and,
            "Union": jnp.bitwise_or,
            "Xor": jnp.bitwise_xor,
        }[kind]

        def fn(leaves, subs=subs, op=op):
            out = subs[0](leaves)
            for s in subs[1:]:
                out = op(out, s(leaves))
            return out

        return fn
    if kind == "Difference":
        head = _lower_ir(ir[1])
        tails = [_lower_ir(ch) for ch in ir[2]]
        if not tails:
            return head

        def fn(leaves, head=head, tails=tails):
            mask = tails[0](leaves)
            for t in tails[1:]:
                mask = jnp.bitwise_or(mask, t(leaves))
            return jnp.bitwise_and(head(leaves), jnp.bitwise_not(mask))

        return fn
    if kind == "timerange":
        idxs = ir[1]

        def fn(leaves, idxs=idxs):
            out = leaves[idxs[0]]
            for i in idxs[1:]:
                out = jnp.bitwise_or(out, leaves[i])
            return out

        return fn
    if kind == "zero":
        i = ir[1]
        return lambda leaves: jnp.zeros_like(leaves[i])
    if kind == "notnull":
        i = ir[1]
        return lambda leaves: leaves[i]
    if kind == "between":
        idxs, depth, lo, hi = ir[1], ir[2], ir[3], ir[4]
        return lambda leaves: bp.bsi_range_between(
            jnp.stack([leaves[i] for i in idxs]), depth, lo, hi)
    if kind == "cmp":
        _, op, idxs, depth, base = ir

        def fn(leaves, op=op, idxs=idxs, depth=depth, base=base):
            planes = jnp.stack([leaves[i] for i in idxs])
            if op == "eq":
                return bp.bsi_range_eq(planes, depth, base)
            if op == "neq":
                return bp.bsi_range_neq(planes, depth, base)
            if op in ("lt", "lte"):
                return bp.bsi_range_lt(planes, depth, base, op == "lte")
            return bp.bsi_range_gt(planes, depth, base, op == "gte")

        return fn
    raise QueryError(f"unknown plan IR node: {kind!r}")


def _plan_expr(plan: CompiledPlan) -> Callable:
    """Lowered closure for a plan, cached on the plan object (plans are
    themselves cached on the Call tree, so a query's expression lowers
    once per epoch, not once per dispatch site). Benign publication
    race: concurrent lowerings produce equivalent closures."""
    expr = plan.expr
    if expr is None:
        expr = plan.expr = _lower_ir(plan.ir)
    return expr


class _Compiler:
    """Facade over the canonical plan compiler (plan/signature.py),
    keeping the historical (comp, expr) surface: `comp.signature` (the
    single-entry canonical-IR list), `comp.leaves` (canonical slot
    order), `comp.plan`. Query structures that differ only by
    commutative operand order or associative nesting now produce the
    SAME signature and leaf binding, so they share one compiled program,
    one memo space, one micro-batcher group, and one device breaker."""

    def __init__(self, holder, index: str, field_cache: Optional[Dict] = None,
                 plan_cache: bool = True):
        self.holder = holder
        self.index = index
        self.leaves: List[Leaf] = []
        self.signature: List = []
        self.plan: Optional[CompiledPlan] = None
        # Shared across one batch's compilers: a 1024-query batch would
        # otherwise repeat the same holder field-existence lookups per call.
        self._field_cache = field_cache
        self._plan_cache = plan_cache

    def compile(self, c: Call) -> Callable:
        plan = cached_plan(self.holder, self.index, c,
                           field_cache=self._field_cache,
                           enabled=self._plan_cache)
        self.plan = plan
        self.leaves = plan.leaves
        self.signature = plan.signature
        return _plan_expr(plan)


class ShardedQueryEngine:
    def __init__(self, holder, mesh=None, config: Optional[EngineConfig] = None,
                 tier_config=None, traffic_fn=None, resilience_config=None):
        self.holder = holder
        if mesh is None:
            # [engine] mesh-devices: a positive N pins the engine to the
            # first N local devices (see EngineConfig for the concurrent-
            # all-reduce rationale); 0 = all local devices.
            md = int(getattr(config, "mesh_devices", 0) or 0) if config \
                else int(os.environ.get("PILOSA_TPU_ENGINE_MESH_DEVICES",
                                        "0"))
            if md > 0:
                import jax as _jax

                mesh = default_mesh(_jax.local_devices()[:md])
            else:
                mesh = default_mesh()
        self.mesh = mesh
        if config is None:
            # No resolved config (library/test/bench use): honor the env
            # spellings directly. When a Config DID resolve these knobs,
            # flags > env > TOML precedence already happened there —
            # re-reading env here would let a stray export silently beat
            # an explicit --engine-* flag.
            config = EngineConfig(
                delta_max_fraction=float(os.environ.get(
                    "PILOSA_TPU_ENGINE_DELTA_MAX_FRACTION",
                    EngineConfig.delta_max_fraction)),
                gather_workers=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_GATHER_WORKERS",
                    EngineConfig.gather_workers)),
                leaf_cache_bytes=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_LEAF_CACHE_BYTES", 0)),
                stack_cache_bytes=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_STACK_CACHE_BYTES", 0)),
                memo_entries=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_MEMO_ENTRIES", 0)),
                aux_memo_entries=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_AUX_MEMO_ENTRIES", 0)),
                dispatch_watchdog=float(os.environ.get(
                    "PILOSA_TPU_ENGINE_DISPATCH_WATCHDOG",
                    EngineConfig.dispatch_watchdog)),
                cold_host_count=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_COLD_HOST_COUNT",
                    EngineConfig.cold_host_count)),
                plan_cache=int(os.environ.get(
                    "PILOSA_TPU_ENGINE_PLAN_CACHE",
                    EngineConfig.plan_cache)),
            )
        if tier_config is None:
            # Same env-only fallback for the [tier] section.
            from ..tier import TierConfig

            tier_config = TierConfig.from_env()
        # Delta-refresh budget: a stale resident tensor is refreshed by a
        # scattered (indices, values) upload only while the changed 32-bit
        # words stay under this fraction of the tensor; 0 disables deltas.
        self._delta_max_fraction = float(config.delta_max_fraction)
        # Device-plane fault state (device_health.py): every dispatch
        # reports its outcome here, and the executor consults plan()
        # before routing work at the device. The watchdog bounds how long
        # a dispatch may block a serving thread (0 = off).
        self.device_health = DevicePlaneHealth(resilience_config)
        self._watchdog_s = float(getattr(config, "dispatch_watchdog", 0.0))
        # Watchdogged dispatches run on their own small pool, NOT the
        # gather pool: an abandoned (wedged) dispatch parks its worker
        # until the runtime answers, and parking gather workers would
        # starve the host gathers the fallback ladder itself serves
        # from. `_watchdog_inflight` counts submitted-but-unfinished
        # dispatches (incremented at submit, decremented by a done
        # callback); at the pool bound, further dispatches run INLINE
        # unwatchdogged — slower to detect a wedge, but never a deadlock
        # and never a queued task misread as a device timeout.
        self._watchdog_pool = None
        self._watchdog_inflight = 0
        self._cold_host = bool(int(getattr(config, "cold_host_count", 1)))
        # On-Call canonical-plan caching (plan/signature.py cached_plan):
        # 0 recompiles at every dispatch site — the escape hatch if a
        # workload ever hits a stale-plan bug; the epoch token makes
        # that structurally unlikely.
        self._plan_cache_enabled = bool(int(getattr(config, "plan_cache", 1)))
        # Leaf sets already answered once by the cold-host path: the
        # second touch promotes normally so repeat traffic climbs back
        # into HBM instead of re-decoding per query. Bounded crudely —
        # losing the set only costs one extra host answer per leaf set.
        self._cold_seen: set = set()
        # Cold-gather host parallelism (per-shard container walks).
        gw = int(config.gather_workers)
        self._gather_workers = gw if gw > 0 else min(8, os.cpu_count() or 1)
        self._gather_pool = None  # lazy ThreadPoolExecutor
        # (index, leaf, shards) -> (generation fingerprint, sharded device array)
        self._leaf_cache: Dict[Tuple, Tuple[Tuple, jax.Array]] = {}
        self._leaf_bytes = 0
        # (index, leaves, shards, U) -> (fingerprint, stacked (U, S, W) array)
        self._stack_cache: Dict[Tuple, Tuple[Tuple, jax.Array]] = {}
        self._stack_bytes = 0
        # Device-cache budgets (bytes, LRU-evicted). The stacked tensors
        # duplicate the per-leaf planes they're built from, so both caches
        # need a byte bound, not an entry bound — one TopN candidate list
        # can be 1000x the size of a 2-leaf count stack. Defaults are
        # sized for a serving chip (v5e: 16 GiB HBM): a 256-candidate x
        # 8-shard TopN stack alone is ~268 MiB, so sub-GiB budgets thrash
        # on every ranked-cache TopN.
        on_accel = self.mesh.devices.flat[0].platform in ("tpu", "axon")
        default_budget = (3 << 30) if on_accel else (1 << 29)
        if tier_config.hbm_bytes > 0:
            # [tier] hbm-bytes is the COMBINED device-cache budget, split
            # evenly; an explicit [engine] budget or legacy env var for
            # one cache still wins for that cache.
            default_budget = max(1, int(tier_config.hbm_bytes) // 2)

        def budget(env_name: str, cfg_val: int, default: int) -> int:
            v = os.environ.get(env_name)
            if v is not None:
                return int(v)
            return int(cfg_val) if cfg_val > 0 else default

        self._leaf_budget = budget(
            "PILOSA_LEAF_CACHE_BYTES", config.leaf_cache_bytes, default_budget)
        self._stack_budget = budget(
            "PILOSA_STACK_CACHE_BYTES", config.stack_cache_bytes,
            default_budget)
        self._stack_jit: Optional[Callable] = None
        self._count_fns: Dict[Tuple, Callable] = {}
        self._bitmap_fns: Dict[Tuple, Callable] = {}
        # Compiled-program caches are LRU-bounded by entry count: each entry
        # pins an XLA executable, and a long-lived server seeing varied query
        # shapes would otherwise accumulate them without bound.
        self._fn_budget = int(os.environ.get("PILOSA_FN_CACHE_ENTRIES", 256))
        # key -> (Event, builder thread); see _gate.
        self._building: Dict[Tuple, Tuple] = {}
        # The server handles requests on ThreadingHTTPServer threads, so
        # every cache (LRU touch included) mutates under concurrency. One
        # lock guards dict + byte-counter state; device work (gather,
        # device_put, jit) happens outside it.
        self._lock = threading.RLock()
        # Host-side hot-query result memo: (index, structure signature,
        # leaves, shards) -> (generation fingerprint, count). A repeat query
        # whose fragments haven't changed skips the device round trip
        # entirely — O(dict lookup + generation check) instead of O(RTT),
        # which on a remote-runtime link is ~70ms -> ~50us. Invalidated by
        # the same per-fragment generation counters as the leaf cache.
        self._memo: Dict[Tuple, Tuple[Tuple, int]] = {}
        self._memo_budget = budget(
            "PILOSA_MEMO_ENTRIES", config.memo_entries, 8192)
        # Composite-result memo (TopN per-shard matrices, BSI val counts):
        # a repeat TopN pays zero device round trips — phase-1 AND the
        # phase-2 refetch hit here. Bounded by entries (values are small
        # (R,S) host arrays); shares the memo hit/miss counters.
        self._aux_memo: Dict[Tuple, Tuple[Tuple, object]] = {}
        self._aux_budget = budget(
            "PILOSA_AUX_MEMO_ENTRIES", config.aux_memo_entries, 512)
        # Effective cache bounds after env > config > tier > default
        # resolution, surfaced verbatim in /debug/vars (engine_budgets) so
        # a deployment can SEE what its knobs resolved to.
        self.budgets = {
            "leaf_cache_bytes": self._leaf_budget,
            "stack_cache_bytes": self._stack_budget,
            "memo_entries": self._memo_budget,
            "aux_memo_entries": self._aux_budget,
            "fn_cache_entries": self._fn_budget,
        }
        # Observable cache behavior (hit rate / eviction pressure) for
        # /debug/vars and the HBM-budget bench stanza.
        self.counters = {
            "leaf_hits": 0, "leaf_misses": 0, "leaf_evictions": 0,
            "stack_hits": 0, "stack_misses": 0, "stack_evictions": 0,
            "memo_hits": 0, "memo_misses": 0,
            # Compiled-program (XLA executable) cache traffic: the proof
            # that canonicalized query shapes SHARE programs is
            # fn_cache_hits climbing while fn_cache_builds stays flat
            # across commutative/associative respellings of one tree.
            "fn_cache_hits": 0, "fn_cache_builds": 0,
            # Device-program launches (memo hits dispatch nothing). The
            # scheduler's coalescing proof is dispatches/query < 1, so the
            # counters must distinguish a launch from an answered query.
            "count_dispatches": 0, "bitmap_dispatches": 0,
            # Delta-refresh accounting: delta hits refreshed a stale
            # resident tensor with a scattered update (delta_bytes of
            # host->device traffic) instead of a full host walk + re-upload
            # (full_refresh_bytes counts those). The bench MIXED stanza's
            # win condition is delta_bytes << full_refresh_bytes at equal
            # correctness under mixed read/write traffic.
            "leaf_delta_hits": 0, "stack_delta_hits": 0,
            "delta_bytes": 0, "full_refresh_bytes": 0,
            # Tiered-storage accounting: an HBM miss answered by
            # decompressing a demoted plane from the host/disk tier
            # (leaf_tier_hits) instead of a cold container walk
            # (leaf_misses). Memo/aux evictions close the observability
            # gap the leaf/stack caches never had.
            "leaf_tier_hits": 0, "tier_promote_bytes": 0,
            "memo_evictions": 0, "aux_evictions": 0,
            # _byte_cache_put's explicit oversized-entry policy: an entry
            # bigger than its whole budget is admitted ALONE (everything
            # else evicts) and counted here — rejecting it would make the
            # largest plane permanently uncacheable (regather per query),
            # strictly worse than holding it.
            "oversized_admits": 0,
            # Background tier-hook failures (promotion gather / demotion
            # capture) and fast-path compile-gate refusals: each swallows
            # the exception by design (the caller has a correct fallback),
            # so the COUNT is the only externally visible trace.
            "tier_promote_errors": 0, "tier_demote_errors": 0,
            "compile_gate_refusals": 0,
            # Device-fault ladder accounting (docs/fault-tolerance.md):
            # host_counts/host_topn are queries answered entirely on the
            # host (degraded ladder), host_cold_counts the healthy
            # compressed-domain path for one-off queries on demoted
            # planes; oom_backpressure counts budget shrinks, oom_retries
            # dispatches that succeeded after one, oom_batch_splits
            # reduced-batch retries, watchdog_timeouts dispatches the
            # watchdog abandoned, device_dispatch_errors every classified
            # dispatch failure (per-kind detail in device_plane).
            "host_counts": 0, "host_topn": 0, "host_cold_counts": 0,
            "oom_backpressure": 0, "oom_retries": 0, "oom_batch_splits": 0,
            "watchdog_timeouts": 0, "device_dispatch_errors": 0,
        }
        # Tier manager (tier/manager.py): owns the host-RAM + disk tiers
        # below the device caches. Leaf evictions demote through it and
        # cold gathers probe it before paying the container walk.
        self.tier = None
        if tier_config.enabled():
            from ..tier.manager import TierManager

            self.tier = TierManager(
                self.holder, tier_config, traffic_fn=traffic_fn)
            self.tier.bind(
                promote_fn=self._tier_promote_key,
                headroom_fn=self._hbm_headroom,
                resident_fn=self._tier_resident,
            )

    def stack_generation(self, index: str) -> int:
        """O(1) write epoch of an index's resident leaf stacks (bumped by
        every fragment mutation, core/fragment.py WriteEpoch). The micro-
        batcher keys coalescing groups on it so one fused launch never
        mixes queries that straddle a visible write."""
        idx = self.holder.index(index)
        return -1 if idx is None else idx.write_epoch.value

    def _count_dispatch(self) -> None:
        with self._lock:
            self.counters["count_dispatches"] += 1

    def snapshot(self) -> dict:
        """Wholesale counter export for /debug/vars (the `engine_cache`
        group). Every key in self.counters is observable through here —
        pilint R4 relies on that, so new counters need no wiring."""
        with self._lock:
            return dict(self.counters)

    def close(self) -> None:
        """Release host-side serving resources (the cold-gather thread
        pool — its workers are non-daemon, so an embedder that opens and
        closes executors repeatedly would otherwise leak them). The tier
        manager stops FIRST so its prefetch thread can't race the pool
        shutdown with a promotion."""
        if self.tier is not None:
            self.tier.close()
        with self._lock:
            pool, self._gather_pool = self._gather_pool, None
            wpool, self._watchdog_pool = self._watchdog_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if wpool is not None:
            wpool.shutdown(wait=False)

    # ----------------------------------------------------- tier integration
    #
    # The leaf cache is the TOP tier of the three-tier plane hierarchy
    # (docs/tiered-storage.md): evicted planes demote into the manager's
    # compressed host tier instead of vanishing, cold gathers probe the
    # manager before paying the container walk, and the manager's prefetch
    # thread re-promotes demoted planes of hot indexes through the hooks
    # below. All three hooks are engine-lock-cheap; the manager never
    # calls them while holding its own lock with ours taken.

    def _tier_promote_key(self, key) -> bool:
        """Prefetch hook: make `key` HBM-resident via the normal gather
        path (which consumes the tier entry and installs the plane)."""
        index, leaf, shards = key
        try:
            self._gather_leaf(index, leaf, shards)
            return True
        except Exception:
            with self._lock:
                self.counters["tier_promote_errors"] += 1
            return False

    def _hbm_headroom(self) -> int:
        with self._lock:
            return self._leaf_budget - self._leaf_bytes

    def _tier_resident(self, key) -> bool:
        with self._lock:
            return key in self._leaf_cache

    def _demote_keys(self, keys) -> None:
        """Demote freshly-evicted leaf planes into the host tier. Runs
        OUTSIDE the engine lock (demotion takes fragment mutexes and
        serializes containers — far too heavy for the cache lock)."""
        if not keys or self.tier is None:
            return
        for key in keys:
            try:
                self.tier.demote(key)
            except Exception:
                # The evicted plane simply stays cold (next read regathers
                # from the fragments); the count is the trace.
                with self._lock:
                    self.counters["tier_demote_errors"] += 1

    # ------------------------------------------------------------ caches
    #
    # All device caches (compiled programs, leaf planes, stacked tensors)
    # are mutated from concurrent ThreadingHTTPServer threads. `self._lock`
    # guards dict + byte-counter state; `_gate` /
    # `_release` dedupe expensive cold builds (XLA trace/compile, host
    # gathers, device_put) so N concurrent misses on a key do the work
    # once instead of N times (compile stampede).

    def _gate(self, key, probe: Callable):
        """Return probe()'s non-None value, or None once the caller holds
        the build gate for `key` — the caller then MUST publish a value and
        `_release(key)`, even on failure (_release runs in the builder's
        finally). Waiters re-probe when the builder releases. Ownership is
        stolen ONLY if the builder thread is no longer alive (interpreter
        teardown — finally makes a leaked gate otherwise impossible):
        stealing on a mere timeout would re-run 20-40s TPU compiles once
        per waiter during a cold-start stampede."""
        waited = 0
        while True:
            val = probe()
            if val is not None:
                return val
            with self._lock:
                entry = self._building.get(key)
                if entry is None:
                    self._building[key] = (
                        threading.Event(), threading.current_thread())
                    return None
                ev, builder = entry
            if ev.wait(timeout=10.0):
                continue
            waited += 1
            # Liveness escape hatch for a WEDGED (alive) builder — e.g. a
            # device call stuck on a dead tunnel: complain at 1 minute,
            # steal at 5 (a redundant compile is the least of the problems
            # then). A dead builder (interpreter teardown) steals at once.
            if waited == 6:
                self.counters["gate_stalls"] = \
                    self.counters.get("gate_stalls", 0) + 1
            if not builder.is_alive() or waited >= 30:
                with self._lock:
                    if self._building.get(key) is entry:
                        self._building[key] = (
                            threading.Event(), threading.current_thread())
                        return None

    def _release(self, key) -> None:
        with self._lock:
            entry = self._building.pop(key, None)
        if entry is not None:
            entry[0].set()

    def _fn_probe(self, cache: Dict[Tuple, Callable], sig: Tuple) -> Optional[Callable]:
        with self._lock:
            fn = cache.get(sig)
            if fn is not None:
                cache[sig] = cache.pop(sig)  # LRU touch
                self.counters["fn_cache_hits"] += 1
            return fn

    def _fn_build(self, cache: Dict[Tuple, Callable], sig: Tuple,
                  build: Callable[[], Callable],
                  health_sig: Optional[Tuple] = None) -> Callable:
        """Get-or-build a compiled program, stampede-gated and LRU-bounded.

        A build failure is a DEVICE fault, not a query error: it is
        classified `compile`, recorded into the device breakers under the
        caller's structure signature (a shape whose program cannot build
        will fail every time — quarantining it to the per-shard path is
        exactly the breaker's job), and re-raised typed so the executor's
        ladder catches it. The `device-compile` failpoint makes the path
        deterministically testable; it fires only on a real cache miss,
        like a real compile failure would."""
        fn = self._gate(sig, lambda: self._fn_probe(cache, sig))
        if fn is not None:
            return fn
        try:
            try:
                failpoints.fire("device-compile")
                fn = build()
                # Counted AFTER a successful build: a failing compile
                # (breaker path) must not inflate the one-build-per-
                # canonical-shape proof counter.
                with self._lock:
                    self.counters["fn_cache_builds"] += 1
            except Exception as e:
                with self._lock:
                    self.counters["device_dispatch_errors"] += 1
                self.device_health.record_failure(health_sig, COMPILE)
                raise DeviceDispatchError(
                    COMPILE, health_sig,
                    f"device program build failed: {e}") from e
            with self._lock:
                cache[sig] = fn
                while len(cache) > self._fn_budget:
                    cache.pop(next(iter(cache)))
        finally:
            self._release(sig)
        return fn

    # ------------------------------------------------------ dispatch guard
    #
    # Every device dispatch runs through _device_call: the `device-
    # dispatch` failpoint fires at exactly this boundary, the optional
    # watchdog bounds how long the serving thread blocks, failures are
    # classified (device_health.classify_device_error) and recorded into
    # the per-signature + plane breakers, and an HBM OOM gets
    # backpressure (shrink budgets, demote through the tier manager) plus
    # ONE same-size retry before the typed error escapes to the
    # executor's ladder. Gather-stage transfers use the lighter
    # _oom_guard: same backpressure, but non-OOM errors propagate raw
    # (a gather bug must not masquerade as a dispatch fault).

    _WATCHDOG_WORKERS = 4

    def _watchdog_done(self, _fut) -> None:
        with self._lock:
            self._watchdog_inflight -= 1

    def _watchdogged(self, fn: Callable, fire: bool = True):
        def run():
            if fire:
                failpoints.fire("device-dispatch")
            return fn()

        if self._watchdog_s <= 0:
            return run()
        with self._lock:
            if self._watchdog_inflight >= self._WATCHDOG_WORKERS:
                # Every watchdog slot is occupied (normally: parked on
                # wedged dispatches). Dispatch inline unwatchdogged —
                # the breaker still routes around repeated failures; we
                # just can't bound this one call's latency. Submitting
                # instead would queue the task and misread queue delay
                # as a device timeout.
                inline = True
            else:
                if self._watchdog_pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._watchdog_pool = ThreadPoolExecutor(
                        max_workers=self._WATCHDOG_WORKERS,
                        thread_name_prefix="pilosa-dispatch",
                    )
                self._watchdog_inflight += 1
                inline = False
                pool = self._watchdog_pool
        if inline:
            return run()
        from concurrent.futures import TimeoutError as FutTimeout

        fut = pool.submit(run)
        # Fires when the task finishes, is cancelled, or (wedged case)
        # whenever the runtime finally answers — inflight stays elevated
        # exactly while a worker is actually occupied.
        fut.add_done_callback(self._watchdog_done)
        try:
            return fut.result(timeout=self._watchdog_s)
        except FutTimeout:
            if fut.cancel():
                # Never started: the timeout measured pool queueing, not
                # the device. Not a fault — dispatch inline.
                return run()
            # Started and wedged: the task cannot be killed — it keeps
            # its worker parked until the runtime answers. The watchdog
            # frees the SERVING thread; the breaker stops new work from
            # piling onto a wedged device.
            with self._lock:
                self.counters["watchdog_timeouts"] += 1
            raise DeviceDispatchTimeout(
                f"device dispatch exceeded the {self._watchdog_s:.3f}s "
                "watchdog")

    def _device_call(self, health_sig: Optional[Tuple], fn: Callable,
                     fire: bool = True):
        """Run one device dispatch under the fault ladder; returns fn()'s
        value. On failure: classify, record into the breakers, re-raise
        as DeviceDispatchError (the executor's catch point). OOM gets
        backpressure + one retry first — a transient allocation failure
        must never reach a client."""
        try:
            result = self._watchdogged(fn, fire=fire)
        except Exception as e:
            with self._lock:
                self.counters["device_dispatch_errors"] += 1
            kind = classify_device_error(e)
            if kind == OOM:
                self._oom_backpressure()
                try:
                    result = self._watchdogged(fn, fire=fire)
                except Exception as e2:
                    kind2 = classify_device_error(e2)
                    self.device_health.record_failure(health_sig, kind2)
                    raise DeviceDispatchError(
                        kind2, health_sig, str(e2)) from e2
                with self._lock:
                    self.counters["oom_retries"] += 1
                self.device_health.record_success(health_sig)
                return result
            self.device_health.record_failure(health_sig, kind)
            raise DeviceDispatchError(kind, health_sig, str(e)) from e
        self.device_health.record_success(health_sig)
        return result

    def _oom_guard(self, health_sig: Optional[Tuple], fn: Callable):
        """Gather-stage transfer guard (device_put, restack): an HBM OOM
        gets backpressure + one retry; any other failure is a DEVICE
        fault at transfer time (dead tunnel erroring in device_put) — it
        is classified, recorded into the breakers, and re-raised typed so
        the executor's ladder catches it. Without that, a device that
        dies at the transfer stage would 500 every query forever with the
        plane breaker still CLOSED."""
        try:
            return fn()
        except Exception as e:
            with self._lock:
                self.counters["device_dispatch_errors"] += 1
            kind = classify_device_error(e)
            if kind != OOM:
                self.device_health.record_failure(health_sig, kind)
                raise DeviceDispatchError(kind, health_sig, str(e)) from e
            self._oom_backpressure()
            try:
                return fn()
            except Exception as e2:
                kind = classify_device_error(e2)
                self.device_health.record_failure(health_sig, kind)
                raise DeviceDispatchError(
                    kind, health_sig, str(e2)) from e2

    def _oom_backpressure(self) -> None:
        """HBM pressure response: halve the effective leaf/stack budgets
        (floored at 1 MiB), evict down to them, and demote the evicted
        planes through the tier manager — free real HBM before the retry
        instead of bouncing RESOURCE_EXHAUSTED to the client. The shrink
        is sticky (the budget stays down for the process lifetime): an
        OOM means the configured budget overcommitted this chip."""
        evicted: List = []
        with self._lock:
            self.counters["oom_backpressure"] += 1
            floor = 1 << 20
            self._leaf_budget = max(self._leaf_budget // 2, floor)
            self._stack_budget = max(self._stack_budget // 2, floor)
            self.budgets["leaf_cache_bytes"] = self._leaf_budget
            self.budgets["stack_cache_bytes"] = self._stack_budget
            while self._leaf_bytes > self._leaf_budget and self._leaf_cache:
                key = next(iter(self._leaf_cache))
                self._leaf_bytes -= self._leaf_cache.pop(key)[1].nbytes
                self.counters["leaf_evictions"] += 1
                evicted.append(key)
            while self._stack_bytes > self._stack_budget and self._stack_cache:
                key = next(iter(self._stack_cache))
                self._stack_bytes -= self._stack_cache.pop(key)[1].nbytes
                self.counters["stack_evictions"] += 1
        self._demote_keys(evicted)

    def _byte_cache_put(self, cache: Dict, key, entry: Tuple, budget: int,
                        used: int, evict_counter: str = "",
                        evicted: Optional[List] = None) -> int:
        """Insert (fingerprint, array) at MRU and evict LRU entries past the
        byte budget; returns the updated used-bytes counter. Caller holds
        self._lock.

        Oversized-entry policy (explicit, tested): an entry whose payload
        exceeds the WHOLE budget is admitted alone — every other entry
        evicts and the insert is counted in `oversized_admits`. The
        alternative (reject-and-count) would make the largest plane
        permanently uncacheable and re-gathered per query, strictly worse
        than briefly over-committing; `used` stays exact either way so the
        next insert immediately evicts back under budget.

        `evicted` (when a list) collects the evicted KEYS so the caller
        can demote those planes into the tier manager after releasing the
        lock — eviction is demotion, not loss (docs/tiered-storage.md)."""
        prev = cache.pop(key, None)
        if prev is not None:
            used -= prev[1].nbytes
        used += entry[1].nbytes
        cache[key] = entry
        if entry[1].nbytes > budget:
            self.counters["oversized_admits"] += 1
        while used > budget and len(cache) > 1:
            old_key = next(iter(cache))
            if old_key == key:
                break
            used -= cache.pop(old_key)[1].nbytes
            if evict_counter:
                self.counters[evict_counter] += 1
            if evicted is not None:
                evicted.append(old_key)
        return used

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    # --------------------------------------------------------- leaf tensors

    def _fingerprint(self, index: str, leaf: Leaf, shards: Tuple[int, ...]) -> Tuple:
        """Per-shard (incarnation, generation) pairs for one leaf — the
        staleness key for every device cache (no device work, just holder
        lookups). The incarnation half makes a RECREATED fragment (deleted
        index re-made under the same name, generation counter reset) never
        compare equal to a stale entry, even if its fresh counter climbs
        back to the cached value."""
        return tuple(
            -1 if f is None else (f.incarnation, f.generation)
            for f in (
                self.holder.fragment(index, leaf.field, leaf.view, s)
                for s in shards
            )
        )

    def _gather_leaf(self, index: str, leaf: Leaf, shards: Tuple[int, ...]) -> jax.Array:
        """(S_padded, W) uint32, sharded over the mesh's shard axis."""
        s_padded = pad_shards(len(shards), self.n_devices)
        key = (index, leaf, shards)
        frags = [
            self.holder.fragment(index, leaf.field, leaf.view, s) for s in shards
        ]
        fingerprint = tuple(
            -1 if f is None else (f.incarnation, f.generation) for f in frags)

        def probe():
            with self._lock:
                cached = self._leaf_cache.get(key)
                if cached is not None and cached[0] == fingerprint:
                    self._leaf_cache[key] = self._leaf_cache.pop(key)  # LRU touch
                    self.counters["leaf_hits"] += 1
                    hit = cached[1]
                else:
                    return None
            if self.tier is not None and self.tier.has_prefetched():
                self.tier.note_hbm_hit(key)
            return hit

        arr = self._gate(("leaf", key), probe)
        if arr is not None:
            return arr
        evicted: List = []
        # The gather stage is where a slow query's time hides: the trace
        # span tags WHICH refresh path ran (delta scatter vs compressed-
        # tier promote vs cold container walk) so /debug/traces answers
        # "why was this gather 30 ms" without correlating counters.
        with obs_span("gather") as sp:
            try:
                # Stale resident entry: try the delta path first — upload
                # only the words the writes changed instead of re-walking
                # every shard's containers and re-shipping the whole plane.
                with self._lock:
                    stale = self._leaf_cache.get(key)
                if stale is not None:
                    arr = self._leaf_delta(key, leaf.row, stale, frags,
                                           fingerprint, evicted)
                    if arr is not None:
                        sp.tag(kind="delta")
                        return arr
                # Demoted plane? Decode the compressed host/disk-tier image
                # (journal deltas folded) instead of walking every shard's
                # live containers.
                buf = None
                if self.tier is not None:
                    buf = self.tier.promote(key, frags, fingerprint, s_padded)
                tier_hit = buf is not None
                if buf is None:
                    buf = self._host_gather(frags, leaf.row, s_padded)
                if sp is not NOP_SPAN:
                    sp.tag(kind="tier-promote" if tier_hit else "cold",
                           bytes=int(buf.nbytes))
                arr = self._oom_guard(None, lambda: jax.device_put(
                    buf, shard_sharding(self.mesh, 2)))
                with self._lock:
                    if tier_hit:
                        self.counters["leaf_tier_hits"] += 1
                        self.counters["tier_promote_bytes"] += buf.nbytes
                    else:
                        self.counters["leaf_misses"] += 1
                        self.counters["full_refresh_bytes"] += buf.nbytes
                    self._leaf_bytes = self._byte_cache_put(
                        self._leaf_cache, key, (fingerprint, arr),
                        self._leaf_budget, self._leaf_bytes, "leaf_evictions",
                        evicted,
                    )
            finally:
                self._release(("leaf", key))
                # Evicted planes demote off-lock whichever path installed
                # the fresh entry (full gather, tier promote, or delta
                # refresh).
                self._demote_keys(evicted)
        return arr

    # ------------------------------------------------------- cold gather

    def _pool(self):
        with self._lock:
            if self._gather_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._gather_pool = ThreadPoolExecutor(
                    max_workers=self._gather_workers,
                    thread_name_prefix="pilosa-gather",
                )
            return self._gather_pool

    def _host_gather(self, frags, row: int, s_padded: int) -> np.ndarray:
        """Cold-path host assembly of an (S_padded, W) plane buffer. The
        per-shard container walks are independent pure reads (fragment
        reads are lock-free by design), so they thread-pool; the device
        transfer of leaf k overlaps leaf k+1's walk for free via jax async
        dispatch, so no cross-leaf pipeline is needed on top."""
        buf = np.zeros((s_padded, WORDS_PER_ROW), dtype=np.uint32)
        live = [(i, f) for i, f in enumerate(frags) if f is not None]
        if len(live) > 1 and self._gather_workers > 1:
            def fill(item):
                i, frag = item
                buf[i] = frag.plane_np(row)

            list(self._pool().map(fill, live))
        else:
            for i, frag in live:
                buf[i] = frag.plane_np(row)
        return buf

    # ------------------------------------------------------ delta refresh
    #
    # A write to a resident fragment bumps its generation; without deltas
    # the next query pays a full host container walk over EVERY shard of
    # the leaf plus a full (S, W) re-upload and a restack of every (U, S,
    # W) stack containing it — O(plane) work for a 1-bit write. The dirty-
    # word journal (core/fragment.py) lets stale members report exactly
    # which 64-bit words changed; while the total stays under
    # delta_max_fraction of the tensor, the refresh is a small (indices,
    # values) device_put + one jitted scatter into the cached array.
    #
    # The scatter is functional (.at[].set builds a new on-device array),
    # NOT buffer-donating: concurrent readers that probed before the write
    # may still be dispatching programs against the old buffer, and
    # donation would invalidate it under them. The on-device copy is HBM-
    # bandwidth cheap; the win is eliminating the host walk and the
    # host->device plane transfer.

    def _collect_updates(self, members, size: int):
        """Shared delta collector for the leaf and stack paths (one body so
        the guards/budget/incarnation logic cannot diverge between them).

        `members`: iterable of (coords, frag, row, old_fp, new_fp) per
        STALE cache member — coords are the member's leading indices in the
        cached tensor ((shard,) for a leaf, (u, shard) for a stack), fps
        are -1 or (incarnation, generation) pairs. `size` is the cached
        tensor's element count (the delta budget base).

        Returns None when only a full regather is safe (missing fragment,
        fragment recreated since the fp was read, journal can't answer,
        budget exceeded), else a list of (coords, col32 indices, uint32
        values) triples — possibly empty, meaning the generation churn came
        from rows outside the cache and zero bytes need to move."""
        out = []
        n32 = 0
        for coords, frag, row, old_fp, new_fp in members:
            if frag is None or old_fp == -1 or new_fp == -1:
                return None
            if old_fp[0] != new_fp[0] or frag.incarnation != new_fp[0]:
                # Different incarnation: the journal's generations are not
                # comparable across it (and the frag we just looked up may
                # itself be newer than the fingerprint we read).
                return None
            w = frag.dirty_words_since(row, old_fp[1])
            if w is None:
                return None
            if not len(w):
                continue
            n32 += 2 * len(w)
            if n32 > self._delta_max_fraction * size:
                return None
            cols, vals = self._updates32(w, frag.row_words64(row, w))
            out.append((coords, cols, vals))
        return out

    @staticmethod
    def _updates32(w64: np.ndarray, v64: np.ndarray):
        """Expand 64-bit dirty words into the (col32 indices, uint32
        values) pairs of the device plane layout. The interleave matches
        plane_np's `.view(np.uint32)` on the same host, so the scattered
        words are byte-identical to a regathered plane."""
        cols = np.empty(2 * len(w64), dtype=np.int32)
        cols[0::2] = w64 * 2
        cols[1::2] = w64 * 2 + 1
        return cols, v64.view(np.uint32)

    @staticmethod
    def _pad_updates(arrays):
        """Pad parallel index/value arrays to a pow2 length by repeating
        entry 0 (a duplicate scatter of the SAME value is deterministic),
        so varying delta sizes reuse a handful of compiled programs."""
        n = len(arrays[0])
        npad = 1 << (n - 1).bit_length()
        if npad == n:
            return arrays
        return [np.concatenate([a, np.repeat(a[:1], npad - n)]) for a in arrays]

    def _leaf_delta(self, key, row: int, stale, frags, fingerprint,
                    evicted: Optional[List] = None):
        """Refresh a stale cached (S, W) leaf; None = caller must
        full-regather. `evicted` collects evicted keys for demotion."""
        old_fp, arr = stale
        if self._delta_max_fraction <= 0 or len(old_fp) != len(fingerprint):
            return None
        updates = self._collect_updates(
            (((i,), frag, row, old_fp[i], fingerprint[i])
             for i, frag in enumerate(frags)
             if old_fp[i] != fingerprint[i]),
            arr.size,
        )
        if updates is None:
            return None
        if not updates:
            # Nothing in THIS row changed: republish the same device array
            # under the fresh fingerprint (zero bytes moved).
            new_arr, moved = arr, 0
        else:
            rows, cols, vals = self._pad_updates([
                np.concatenate([np.full(len(c), co[0], np.int32)
                                for co, c, _ in updates]),
                np.concatenate([c for _, c, _ in updates]),
                np.concatenate([v for _, _, v in updates]),
            ])
            sig = ("leaf_delta", arr.shape, len(rows))
            fn = self._fn_build(self._count_fns, sig, lambda: jax.jit(
                lambda a, r, c, v: a.at[r, c].set(v),
                out_shardings=shard_sharding(self.mesh, 2),
            ))
            new_arr = fn(arr, rows, cols, vals)
            moved = int(rows.nbytes + cols.nbytes + vals.nbytes)
        with self._lock:
            self.counters["leaf_delta_hits"] += 1
            self.counters["delta_bytes"] += moved
            self._leaf_bytes = self._byte_cache_put(
                self._leaf_cache, key, (fingerprint, new_arr),
                self._leaf_budget, self._leaf_bytes, "leaf_evictions",
                evicted,
            )
        return new_arr

    def _stack_delta(self, key, index: str, leaves, shards, stale, fp):
        """Refresh a stale (U, S, W) stack with one scattered update — no
        host walk, no member re-gather, no restack. None = full rebuild."""
        old_fp, arr = stale
        if self._delta_max_fraction <= 0 or len(old_fp) != len(fp):
            return None
        if any(len(o) != len(n) for o, n in zip(old_fp, fp)):
            return None

        def members():
            for u, leaf in enumerate(leaves):
                if old_fp[u] == fp[u]:
                    continue
                for i, s in enumerate(shards):
                    if old_fp[u][i] == fp[u][i]:
                        continue
                    frag = self.holder.fragment(index, leaf.field, leaf.view, s)
                    yield (u, i), frag, leaf.row, old_fp[u][i], fp[u][i]

        updates = self._collect_updates(members(), arr.size)
        if updates is None:
            return None
        # pow2 padding rows duplicate leaf 0; today no compiled program
        # reads them, but the full-rebuild invariant is pad == leaf 0's
        # CURRENT plane, so replicate leaf-0 updates onto every pad row
        # rather than trusting a comment to keep them unread forever.
        leaf0 = [(co, c, v) for co, c, v in updates if co[0] == 0]
        for pad_u in range(len(leaves), arr.shape[0]):
            updates.extend(((pad_u, co[1]), c, v) for co, c, v in leaf0)
        if not updates:
            new_arr, moved = arr, 0
        else:
            us, rows, cols, vals = self._pad_updates([
                np.concatenate([np.full(len(c), co[0], np.int32)
                                for co, c, _ in updates]),
                np.concatenate([np.full(len(c), co[1], np.int32)
                                for co, c, _ in updates]),
                np.concatenate([c for _, c, _ in updates]),
                np.concatenate([v for _, _, v in updates]),
            ])
            sig = ("stack_delta", arr.shape, len(us))
            fn = self._fn_build(self._count_fns, sig, lambda: jax.jit(
                lambda a, u, r, c, v: a.at[u, r, c].set(v),
                out_shardings=shard_sharding(self.mesh, 3, axis=1),
            ))
            new_arr = fn(arr, us, rows, cols, vals)
            moved = int(us.nbytes + rows.nbytes + cols.nbytes + vals.nbytes)
        with self._lock:
            self.counters["stack_delta_hits"] += 1
            self.counters["delta_bytes"] += moved
            self._stack_bytes = self._byte_cache_put(
                self._stack_cache, key, (fp, new_arr),
                self._stack_budget, self._stack_bytes, "stack_evictions",
            )
        return new_arr

    def _leaf_tensor(self, index: str, leaves: List[Leaf], shards: Tuple[int, ...]):
        """Tuple of per-leaf (S, W) sharded arrays. Passed as a pytree into
        jitted query fns so each input keeps its NamedSharding (stacking
        outside jit would re-lay-out the data)."""
        return tuple(self._gather_leaf(index, leaf, shards) for leaf in leaves)

    def _stacked_leaf_tensor(
        self, index: str, leaves: List[Leaf], shards: Tuple[int, ...],
        pad_pow2: bool = False,
    ) -> jax.Array:
        """One resident (U, S, W) device tensor for a leaf list, rebuilt only
        when a member fragment's generation changes.

        Serving latency for batched queries is dominated by per-call host
        work, not device FLOPs: passing one argument per leaf (dozens of
        arrays) and restacking them inside the program costs far more than
        the popcounts. Keeping the stack resident shrinks every query
        dispatch to (stacked tensor, small index vectors). `pad_pow2` pads
        the leading axis with duplicate rows so nearby leaf-set sizes reuse
        one compiled program."""
        fp = tuple(self._fingerprint(index, leaf, shards) for leaf in leaves)
        n = len(leaves)
        np2 = (1 << (n - 1).bit_length()) if (pad_pow2 and n) else n
        key = (index, tuple(leaves), shards, np2)

        def probe():
            with self._lock:
                cached = self._stack_cache.get(key)
                if cached is not None and cached[0] == fp:
                    self._stack_cache[key] = self._stack_cache.pop(key)  # LRU touch
                    self.counters["stack_hits"] += 1
                    return cached[1]
            return None

        stacked = self._gate(("stack", key), probe)
        if stacked is not None:
            return stacked
        try:
            # Stale resident stack: one scattered update beats regathering
            # every member and restacking the whole (U, S, W) tensor.
            with self._lock:
                stale = self._stack_cache.get(key)
            if stale is not None:
                with obs_span("gather", kind="stack-delta") as sp:
                    stacked = self._stack_delta(
                        key, index, leaves, shards, stale, fp)
                    if sp is not NOP_SPAN:
                        sp.tag(applied=stacked is not None)
                if stacked is not None:
                    return stacked
            # Stale or missing: gather member planes (leaf-cache hits are
            # cheap; on a fresh stack hit above no gather happens at all).
            arrs = [self._gather_leaf(index, leaf, shards) for leaf in leaves]
            arrs = arrs + [arrs[0]] * (np2 - n)
            with self._lock:
                if self._stack_jit is None:
                    self._stack_jit = jax.jit(
                        lambda xs: jnp.stack(xs),
                        out_shardings=shard_sharding(self.mesh, 3, axis=1),
                    )
                stack_jit = self._stack_jit
            stacked = self._oom_guard(None, lambda: stack_jit(tuple(arrs)))
            with self._lock:
                self.counters["stack_misses"] += 1
                self._stack_bytes = self._byte_cache_put(
                    self._stack_cache, key, (fp, stacked),
                    self._stack_budget, self._stack_bytes, "stack_evictions",
                )
        finally:
            self._release(("stack", key))
        return stacked

    # ----------------------------------------------------------- query memo

    def _epoch_token(self, index: str):
        """(incarnation, value) of the index's write epoch, or -1 when the
        index doesn't exist. A bare value would let a recreated index whose
        fresh epoch climbs back to a stored entry's number alias the OLD
        index's memoized count; the incarnation pair can't collide."""
        idx = self.holder.index(index)
        if idx is None:
            return -1
        ep = idx.write_epoch
        return (ep.incarnation, ep.value)

    def memo_probe(self, index: str, comp: "_Compiler",
                   shards: Tuple[int, ...]):
        """(memoized count or None, store token) for an already-compiled
        call. A hit is host-only work (dict lookup + generation check).

        The token freezes the generation fingerprint AT PROBE TIME — i.e.
        before the query executes. memo_store(token) must use it, not a
        fresh fingerprint: a write landing during the device round trip
        bumps generations, and stamping the post-write generation onto the
        pre-write count would serve stale results forever. With the probe-
        time fingerprint the entry just misses on the next probe (the safe
        direction, matching the leaf cache's fp-before-read ordering)."""
        key = (index, comp.plan.sig_tuple, tuple(comp.leaves), shards)
        # O(1) staleness fast path: when the index's write epoch hasn't
        # moved since the entry was stored, NOTHING in the index changed,
        # so the O(U x S) per-fragment fingerprint walk below is pure
        # overhead — on a quiet index a hot repeat query probes in one
        # attribute read + dict lookup. Epoch is read BEFORE the walk /
        # execution (probe-time discipline, see below), so a concurrent
        # write can only make the stored epoch conservatively old.
        epoch = self._epoch_token(index)
        with self._lock:
            ent = self._memo.get(key)
            if ent is not None and epoch != -1 and ent[1] == epoch:
                self._memo[key] = self._memo.pop(key)  # LRU touch
                self.counters["memo_hits"] += 1
                return ent[2], (key, ent[0], epoch)
        fp = tuple(self._fingerprint(index, leaf, shards) for leaf in comp.leaves)
        token = (key, fp, epoch)
        with self._lock:
            ent = self._memo.get(key)
            if ent is not None and ent[0] == fp:
                # Epoch moved (a write elsewhere in the index) but these
                # leaves didn't: refresh the stored epoch so the next
                # probe is O(1) again.
                self._memo.pop(key)
                self._memo[key] = (fp, epoch, ent[2])
                self.counters["memo_hits"] += 1
                return ent[2], token
            self.counters["memo_misses"] += 1
        return None, token

    def memo_store(self, token, count: int) -> None:
        key, fp, epoch = token
        with self._lock:
            self._memo.pop(key, None)
            self._memo[key] = (fp, epoch, count)
            while len(self._memo) > self._memo_budget:
                self._memo.pop(next(iter(self._memo)))
                self.counters["memo_evictions"] += 1

    def _aux_probe(self, key, fp):
        """Generation-checked memo for composite results (TopN count
        matrices, BSI val-count outputs). Same probe-time-fingerprint
        discipline as memo_probe; values are small host arrays."""
        with self._lock:
            ent = self._aux_memo.get(key)
            if ent is not None and ent[0] == fp:
                self._aux_memo[key] = self._aux_memo.pop(key)  # LRU touch
                self.counters["memo_hits"] += 1
                return ent[1]
            self.counters["memo_misses"] += 1
        return None

    def _aux_store(self, key, fp, value) -> None:
        with self._lock:
            self._aux_memo.pop(key, None)
            self._aux_memo[key] = (fp, value)
            while len(self._aux_memo) > self._aux_budget:
                self._aux_memo.pop(next(iter(self._aux_memo)))
                self.counters["aux_evictions"] += 1

    # ------------------------------------------------------ host execution
    #
    # The bottom rung of the degraded ladder (docs/fault-tolerance.md) and
    # ROADMAP's compressed-domain cold path, one implementation: evaluate
    # a set-op call tree entirely on the host — planes come from the
    # host-tier compressed roaring bytes (decode_plane_words + journal
    # fold, via TierManager.promote) when the plane is demoted, or a live
    # container walk otherwise, and popcounts are one vectorized numpy
    # pass. Bit-exact vs the device path by construction: the promotion
    # logic is the same one the device gather consumes, and a popcount is
    # a popcount. No device work whatsoever, so a dead/demoted device
    # plane can still answer Count/TopN correctly.

    def host_supports(self, call: Call) -> bool:
        """True when `call` is answerable by the host evaluator: Row /
        Intersect / Union / Difference / Xor trees and time-quantum
        Ranges. BSI Ranges refuse (the bit-sliced kernels are device
        code); the executor's ladder uses the per-shard walk for those."""
        if call.name == "Row":
            return True
        if call.name in ("Intersect", "Union", "Difference", "Xor"):
            return bool(call.children) and all(
                self.host_supports(ch) for ch in call.children)
        if call.name == "Range" and not call.has_condition_arg():
            return True
        return False

    def _host_plane(self, index: str, leaf: Leaf, shards: Tuple[int, ...],
                    cache: Optional[Dict] = None) -> np.ndarray:
        """(len(shards), W) uint32 plane for one leaf, host memory only:
        tier promotion (compressed decode + journal fold) when demoted,
        live container walk otherwise. `cache` dedupes leaves within one
        query tree."""
        key = (index, leaf, shards)
        if cache is not None and key in cache:
            return cache[key]
        frags = [
            self.holder.fragment(index, leaf.field, leaf.view, s)
            for s in shards
        ]
        fp = tuple(
            -1 if f is None else (f.incarnation, f.generation) for f in frags)
        buf = None
        if self.tier is not None:
            buf = self.tier.promote(key, frags, fp, len(shards))
        if buf is None:
            buf = self._host_gather(frags, leaf.row, len(shards))
        if cache is not None:
            cache[key] = buf
        return buf

    def _host_eval(self, index: str, call: Call, shards: Tuple[int, ...],
                   cache: Dict) -> np.ndarray:
        """Evaluate a host-supported call tree to its (S, W) plane."""
        if call.name == "Row":
            field_name = call.field_arg()
            row_id, ok = call.uint_arg(field_name)
            if not ok:
                raise QueryError("Row() must specify row")
            return self._host_plane(
                index, Leaf(field_name, VIEW_STANDARD, row_id), shards, cache)
        if call.name in ("Intersect", "Union", "Difference", "Xor"):
            if not call.children:
                raise QueryError(
                    f"empty {call.name} query is currently not supported")
            out = self._host_eval(index, call.children[0], shards, cache)
            op = {
                "Intersect": np.bitwise_and,
                "Union": np.bitwise_or,
                "Xor": np.bitwise_xor,
            }.get(call.name)
            for ch in call.children[1:]:
                rhs = self._host_eval(index, ch, shards, cache)
                if op is None:  # Difference
                    out = np.bitwise_and(out, np.bitwise_not(rhs))
                else:
                    out = op(out, rhs)
            return out
        if call.name == "Range" and not call.has_condition_arg():
            return self._host_time_range(index, call, shards, cache)
        raise QueryError(f"not host-executable: {call.name}")

    def _host_time_range(self, index: str, c: Call, shards: Tuple[int, ...],
                         cache: Dict) -> np.ndarray:
        """Time-quantum Range as a host union over present time views —
        the SHARED _resolve_time_range pruning, so the host answer
        matches the compiled program bit for bit. (This path is reached
        only after the compiled twin accepted the call, so the empty /
        too-many-views refusals don't re-apply here: zeros for empty is
        exactly the fallback's semantics.)"""
        field_name, row_id, views = _resolve_time_range(
            self.holder, index, c)
        out = None
        for v in views:
            p = self._host_plane(
                index, Leaf(field_name, v, row_id), shards, cache)
            out = p if out is None else np.bitwise_or(out, p)
        if out is None:
            out = np.zeros((len(shards), WORDS_PER_ROW), dtype=np.uint32)
        return out

    def host_count(self, index: str, call: Call, shards: Sequence[int],
                   comp_expr=None) -> int:
        """Count(call) answered entirely from host memory — the degraded
        ladder's bottom rung. Shares the generation-checked result memo
        with the device path (the answer is bit-exact, so a host-computed
        entry is as good as a device-computed one)."""
        shards = tuple(shards)
        comp = None
        if comp_expr is not None and comp_expr is not True:
            comp = comp_expr[0]
        if comp is None:
            comp, _ = self._compile(index, call)
        hit, token = self.memo_probe(index, comp, shards)
        if hit is not None:
            return hit
        plane = self._host_eval(index, call, shards, {})
        result = int(_pop_elems(plane).sum())
        with self._lock:
            self.counters["host_counts"] += 1
        self.memo_store(token, result)
        return result

    def host_topn_shard_counts(
        self, index: str, field: str, row_ids: Sequence[int],
        shards: Sequence[int], src_call: Optional[Call] = None,
        need_row_counts: bool = True,
    ):
        """topn_shard_counts with the same result contract, computed from
        host planes with numpy popcounts — the TopN rung of the ladder.
        Unmemoized: this is the degraded path, correctness over speed."""
        shards = tuple(shards)
        req = np.asarray(row_ids, dtype=np.int64)
        canon = np.unique(req)
        sel = np.searchsorted(canon, req)
        cache: Dict = {}
        if len(canon):
            planes = np.stack([
                self._host_plane(
                    index, Leaf(field, VIEW_STANDARD, int(r)), shards, cache)
                for r in canon
            ])  # (R, S, W)
        else:
            planes = np.zeros((0, len(shards), WORDS_PER_ROW), np.uint32)
        row_counts = None
        if need_row_counts:
            row_counts = _pop_elems(planes).sum(axis=2, dtype=np.int64)
        inter = src_counts = None
        if src_call is not None:
            src = self._host_eval(index, src_call, shards, cache)  # (S, W)
            src_counts = _pop_elems(src).sum(axis=1, dtype=np.int64)
            masked = np.bitwise_and(planes, src[None, :, :])
            inter = _pop_elems(masked).sum(axis=2, dtype=np.int64)
        with self._lock:
            self.counters["host_topn"] += 1
        return (
            row_counts[sel] if row_counts is not None else None,
            inter[sel] if inter is not None else None,
            src_counts,
        )

    def _cold_host_candidate(self, index: str, call: Call, comp: "_Compiler",
                             shards: Tuple[int, ...]) -> bool:
        """True when this Count should be answered compressed-domain: the
        tree is host-expressible, every leaf is demoted (none resident in
        HBM, all present in the tier), and this exact leaf set has not
        been host-answered before — the second touch promotes normally so
        hot planes climb back into HBM instead of re-decoding forever."""
        if not self._cold_host or self.tier is None or not comp.leaves:
            return False
        if not self.host_supports(call):
            return False
        keys = [(index, leaf, shards) for leaf in comp.leaves]
        kset = (index, tuple(comp.leaves), shards)
        with self._lock:
            if kset in self._cold_seen:
                return False
            if any(k in self._leaf_cache for k in keys):
                return False
        if not all(self.tier.has(k) for k in keys):
            return False
        with self._lock:
            if len(self._cold_seen) >= 4096:
                self._cold_seen.clear()
            self._cold_seen.add(kset)
        return True

    # -------------------------------------------------------------- queries

    def _compile(self, index: str, call: Call, field_cache: Optional[Dict] = None):
        comp = _Compiler(self.holder, index, field_cache=field_cache,
                         plan_cache=self._plan_cache_enabled)
        expr = comp.compile(call)
        return comp, expr

    def count(self, index: str, call: Call, shards: Sequence[int],
              comp_expr=None) -> int:
        """Count(<bitmap call>) over all shards in one device program."""
        shards = tuple(shards)
        comp, expr = comp_expr if comp_expr is not None else self._compile(index, call)
        hit, token = self.memo_probe(index, comp, shards)
        if hit is not None:
            return hit
        if self._cold_host_candidate(index, call, comp, shards):
            # Compressed-domain cold path: every leaf is demoted and this
            # leaf set is a first touch — one numpy popcount over the
            # host-tier bytes beats decode + device_put for a plane
            # nobody re-reads. A repeat promotes normally.
            plane = self._host_eval(index, call, shards, {})
            result = int(_pop_elems(plane).sum())
            with self._lock:
                self.counters["host_cold_counts"] += 1
            self.memo_store(token, result)
            return result
        hsig = comp.plan.sig_tuple
        sig = ("count", hsig, len(shards))

        def build():
            @jax.jit
            def fn(leaves):
                plane = expr(leaves)
                # XLA turns the full-tensor sum over the sharded axis into
                # per-device partial popcounts + an ICI all-reduce.
                return jnp.sum(jax.lax.population_count(plane).astype(jnp.int32))

            return fn

        fn = self._fn_build(self._count_fns, sig, build, health_sig=hsig)
        leaves = self._leaf_tensor(index, comp.leaves, shards)
        self._count_dispatch()
        result = int(self._device_call(hsig, lambda: int(fn(leaves))))
        self.memo_store(token, result)
        return result

    def count_async(self, index: str, call: Call, shards: Sequence[int],
                    comp_expr=None):
        """Like count() but returns the unmaterialized device scalar, so
        callers can pipeline many queries before blocking (dispatch latency
        through the host<->device link dominates single-query serving).
        `comp_expr` lets callers that already compiled the call skip the
        second AST walk."""
        shards = tuple(shards)
        comp, expr = comp_expr if comp_expr is not None else self._compile(index, call)
        hsig = comp.plan.sig_tuple
        sig = ("count", hsig, len(shards))

        def build():
            @jax.jit
            def fn(leaves):
                plane = expr(leaves)
                return jnp.sum(jax.lax.population_count(plane).astype(jnp.int32))

            return fn

        fn = self._fn_build(self._count_fns, sig, build, health_sig=hsig)
        leaves = self._leaf_tensor(index, comp.leaves, shards)
        self._count_dispatch()
        return self._device_call(hsig, lambda: fn(leaves))

    def count_batch(self, index: str, calls: Sequence[Call], shards: Sequence[int],
                    comps=None) -> np.ndarray:
        """Count Q structurally-identical queries in ONE device program.

        Every bitplane op is elementwise, so the compiled expression applies
        unchanged to each query's leaf set; XLA fuses the whole batch and the
        host pays one dispatch + one transfer for Q results. This is the
        throughput-serving path (amortizes host<->device latency that caps
        per-call serving at ~1/RTT). Queries answered by the result memo
        skip the device entirely; only misses ride the batched program.
        `comps` skips recompiling already-compiled calls (aligned 1:1 with
        `calls` — the micro-batcher compiled each query at enqueue)."""
        shards = tuple(shards)
        if comps is None:
            fcache: Dict = {}
            comps = [self._compile(index, c, field_cache=fcache) for c in calls]
        out = np.empty(len(calls), dtype=np.int64)
        miss = []
        tokens = {}
        for i, (comp, _) in enumerate(comps):
            hit, tokens[i] = self.memo_probe(index, comp, shards)
            if hit is None:
                miss.append(i)
            else:
                out[i] = hit
        if miss:
            def run(sub):
                arr = self.count_batch_async(
                    index, [calls[i] for i in sub], shards,
                    comps=[comps[i] for i in sub],
                )
                # Materialize INSIDE the guard: with jax's async dispatch
                # a real device fault surfaces here, not at the enqueue
                # the dispatch guard already wrapped — unguarded, it
                # would escape as a raw XlaRuntimeError that bypasses
                # classification, the breakers, and the ladder entirely.
                # fire=False: the dispatch already paid the failpoint.
                return self._device_call(
                    tuple(comps[sub[0]][0].signature),
                    lambda: np.asarray(arr)[: len(sub)], fire=False)

            try:
                res = run(miss)
            except DeviceDispatchError as e:
                # Reduced-batch retry: the full-size dispatch already got
                # backpressure + one same-size retry inside _device_call;
                # a batch that STILL OOMs re-dispatches as two halves
                # (half the stacked working set each) before the error is
                # allowed to reach a client.
                if e.kind != OOM or len(miss) < 2:
                    raise
                with self._lock:
                    self.counters["oom_batch_splits"] += 1
                h = len(miss) // 2
                res = np.concatenate([run(miss[:h]), run(miss[h:])])
            for j, i in enumerate(miss):
                out[i] = int(res[j])
                self.memo_store(tokens[i], int(res[j]))
        return out

    def count_batch_async(self, index: str, calls: Sequence[Call],
                          shards: Sequence[int], comps=None) -> jax.Array:
        """count_batch without blocking on the result: returns the device
        array (length ≥ len(calls); first len(calls) entries valid). Lets a
        serving loop keep several batches in flight so device work and
        host<->device transfer overlap instead of serializing on each
        batch's round trip. `comps` skips recompiling already-compiled
        calls (must align 1:1 with `calls`)."""
        shards = tuple(shards)
        if comps is None:
            fcache: Dict = {}
            comps = [self._compile(index, c, field_cache=fcache) for c in calls]
        # List comparison (not per-call tuple()): this runs once per query
        # on the serving hot path.
        sig0_list = comps[0][0].signature
        for comp, _ in comps[1:]:
            if comp.signature != sig0_list:
                raise QueryError("count_batch requires structurally identical queries")
        sig0 = comps[0][0].plan.sig_tuple

        # Set-op trees (Row/Intersect/Union/Difference/Xor) are elementwise,
        # so the whole batch vectorizes: dedupe the batch's leaf rows into one
        # stacked (U, S, W) tensor and gather each query's leaves with a (Q,)
        # index per leaf position. One small take+logic+popcount program, one
        # dispatch, one (Q,) transfer — and because the row choice is an
        # *input* (not baked into the trace), every batch of the same shape
        # reuses the compiled program. The canonical plan carries the gate
        # (setops_only) precomputed.
        if comps[0][0].plan is not None and comps[0][0].plan.setops_only:
            return self._count_batch_setops(index, comps, shards, len(calls))

        sig = ("count_batch", sig0, len(shards), len(calls))

        def build():
            exprs = [e for _, e in comps]

            @jax.jit
            def fn(leavess):
                outs = []
                for lv, e in zip(leavess, exprs):
                    plane = e(lv)
                    outs.append(jnp.sum(jax.lax.population_count(plane).astype(jnp.int32)))
                return jnp.stack(outs)

            return fn

        fn = self._fn_build(self._count_fns, sig, build, health_sig=sig0)
        leavess = tuple(
            self._leaf_tensor(index, comp.leaves, shards) for comp, _ in comps
        )
        self._count_dispatch()
        return self._device_call(sig0, lambda: fn(leavess))

    @staticmethod
    def _batch_slot_gather(comps, q: int):
        """THE batch-assembly prologue shared by the fused batched count
        and bitmap programs: leaf-slot dict, per-leaf-position (Q,) slot
        vectors, within-batch dedup — structurally identical queries over
        the same leaf slots compute ONCE and fan back out via `inverse`
        (real serving mixes repeat hot queries heavily, zipf) — and
        power-of-two padding so varying batch sizes hit a handful of
        compiled programs. One implementation so the two batched paths
        cannot drift on dedup/pad semantics. Returns
        (slots, idxs, inverse, q_deduped, qp)."""
        slots: Dict[Leaf, int] = {}
        for comp, _ in comps:
            for leaf in comp.leaves:
                slots.setdefault(leaf, len(slots))
        n_pos = len(comps[0][0].leaves)
        idxs = tuple(
            np.array([slots[comp.leaves[j]] for comp, _ in comps],
                     dtype=np.int32)
            for j in range(n_pos)
        )
        inverse = None
        if q > 1:
            mat = np.stack(idxs)  # (L, Q)
            uniq, inv = np.unique(mat, axis=1, return_inverse=True)
            if uniq.shape[1] < q:
                idxs = tuple(np.ascontiguousarray(row) for row in uniq)
                inverse = inv.reshape(-1).astype(np.int32)
                q = uniq.shape[1]
        qp = 1 << (q - 1).bit_length()
        if qp != q:
            idxs = tuple(
                np.concatenate([ix, np.full(qp - q, ix[-1], np.int32)])
                for ix in idxs)
        return slots, idxs, inverse, q, qp

    def _count_batch_setops(self, index: str, comps, shards: Tuple[int, ...],
                            q: int) -> jax.Array:
        """Returns the unmaterialized (Qp,) device counts, Qp ≥ q."""
        slots, idxs, inverse, q, qp = self._batch_slot_gather(comps, q)
        stacked = self._stacked_leaf_tensor(index, list(slots), shards,
                                            pad_pow2=True)
        up = stacked.shape[0]

        # The memoized expansion rides inside the same program (a take on
        # the (Qp,) counts): a separate jnp.take would be a second dispatch
        # — a second full round trip per batch on a remote-runtime link.
        invp = 0
        inv_in = None
        if inverse is not None:
            invp = 1 << (len(inverse) - 1).bit_length()
            inv_in = np.concatenate(
                [inverse, np.zeros(invp - len(inverse), np.int32)]
            )

        # sig0 is row-independent for set-op trees (Row entries carry leaf
        # positions, not row ids), so one compiled program serves any rows.
        sig = ("count_batch_setops", comps[0][0].plan.sig_tuple,
               len(shards), qp, up, invp)
        def build():
            expr = comps[0][1]
            if self._use_gather_kernel():
                from ..ops import pallas_kernels as pk

                if self.n_devices == 1:
                    def counts_of(stacked, idxs):
                        return pk.batched_gather_expr_count(stacked, idxs, expr)
                else:
                    # Multi-device: the kernel runs per device on its local
                    # (U, S/d, W) shard-block under shard_map; per-query
                    # partial counts reduce with one psum over the shard
                    # axis (ICI). This keeps the no-materialization win on
                    # every chip — the XLA fallback's gather copies cost 3x
                    # the HBM traffic per device.
                    try:
                        from jax import shard_map
                    except ImportError:  # older jax
                        from jax.experimental.shard_map import shard_map
                    from jax.sharding import PartitionSpec as P

                    def local(stacked_blk, *ix):
                        c = pk.batched_gather_expr_count(stacked_blk, ix, expr)
                        return jax.lax.psum(c, SHARD_AXIS)

                    specs = (P(None, SHARD_AXIS, None),) + (P(),) * len(idxs)
                    # check_vma/check_rep off (name depends on jax version):
                    # pallas_call inside shard_map cannot express output
                    # variance, and the psum makes the result replicated by
                    # construction.
                    for knob in ("check_vma", "check_rep"):
                        try:
                            smap = shard_map(
                                local, mesh=self.mesh, in_specs=specs,
                                out_specs=P(), **{knob: False},
                            )
                            break
                        except TypeError:
                            continue

                    def counts_of(stacked, idxs):
                        return smap(stacked, *idxs)
            else:
                # XLA fallback: materializes the (Q, S, W) gathers but
                # partitions cleanly over a multi-device mesh.
                def counts_of(stacked, idxs):
                    leaves = tuple(stacked[ix] for ix in idxs)  # each (Q, S, W)
                    plane = expr(leaves)
                    return jnp.sum(
                        jax.lax.population_count(plane).astype(jnp.int32),
                        axis=(1, 2),
                    )

            if invp:
                @jax.jit
                def fn(stacked, idxs, inv):
                    return jnp.take(counts_of(stacked, idxs), inv)
            else:
                @jax.jit
                def fn(stacked, idxs):
                    return counts_of(stacked, idxs)
            return fn

        hsig = comps[0][0].plan.sig_tuple
        fn = self._fn_build(self._count_fns, sig, build, health_sig=hsig)
        self._count_dispatch()
        if inv_in is not None:
            return self._device_call(hsig, lambda: fn(stacked, idxs, inv_in))
        return self._device_call(hsig, lambda: fn(stacked, idxs))

    def _use_gather_kernel(self) -> bool:
        """Fused Pallas gather kernel on TPU (any mesh size: multi-device
        runs the kernel per device under shard_map with a psum reduce).
        PILOSA_PALLAS_BATCH forces it on (tests use interpret mode) or
        off (XLA gather fallback)."""
        env = os.environ.get("PILOSA_PALLAS_BATCH")
        if env is not None:
            v = env.strip().lower()
            if v in ("1", "true", "yes", "on"):
                return True
            if v in ("", "0", "false", "no", "off"):
                return False
            # Unrecognized value: fall through to the default gates.
        from ..ops import pallas_kernels as pk

        return pk._on_tpu() and WORDS_PER_ROW % 128 == 0

    def bitmap(self, index: str, call: Call, shards: Sequence[int],
               comp_expr=None) -> Row:
        """Evaluate a bitmap call over all shards; returns a Row whose
        segments stay on device (one (W,) plane per shard)."""
        shards = tuple(shards)
        comp, expr = comp_expr if comp_expr is not None else self._compile(index, call)
        hsig = comp.plan.sig_tuple
        sig = ("bitmap", hsig, len(shards))
        fn = self._fn_build(self._bitmap_fns, sig, lambda: jax.jit(expr),
                            health_sig=hsig)
        leaves = self._leaf_tensor(index, comp.leaves, shards)
        with self._lock:
            self.counters["bitmap_dispatches"] += 1
        # block_until_ready inside the guard: the Row keeps its segments
        # on device (no host transfer), but forcing completion here makes
        # an async device fault surface where it is classified and
        # recorded instead of deep inside a later Row operation.
        planes = self._device_call(
            hsig, lambda: fn(leaves).block_until_ready())  # (S_padded, W)
        return Row({shard: planes[i] for i, shard in enumerate(shards)})

    def bitmap_batch(self, index: str, calls: Sequence[Call],
                     shards: Sequence[int], comps=None) -> List[Row]:
        """Evaluate Q same-signature bitmap call trees in ONE device
        program — the micro-batcher's generalized launch for bitmap
        (Row/set-op tree) dispatches, mirroring count_batch. The batch
        vectorizes exactly like _count_batch_setops: dedupe the batch's
        leaf rows into one stacked (U, S, W) tensor and gather each
        query's leaves with a (Q,) slot vector per leaf position, so one
        take+logic program produces all Q result planes and every batch
        of the same canonical shape reuses the compiled program. Trees
        outside the slot-gather shapes (BSI, time ranges) serve per-call
        — identical to the unbatched path."""
        shards = tuple(shards)
        if comps is None:
            fcache: Dict = {}
            comps = [self._compile(index, c, field_cache=fcache) for c in calls]
        plan0 = comps[0][0].plan
        if len(calls) == 1 or plan0 is None or not plan0.setops_only:
            return [self.bitmap(index, c, shards, comp_expr=ce)
                    for c, ce in zip(calls, comps)]
        sig0_list = comps[0][0].signature
        for comp, _ in comps[1:]:
            if comp.signature != sig0_list:
                raise QueryError(
                    "bitmap_batch requires structurally identical queries")
        # Shared prologue with the count path: slot vectors, within-batch
        # dedup (identical queries compute ONE plane; their Rows share
        # the immutable device array), power-of-two padding.
        n_calls = len(calls)
        slots, idxs, inverse, _, qp = self._batch_slot_gather(comps, n_calls)
        stacked = self._stacked_leaf_tensor(index, list(slots), shards,
                                            pad_pow2=True)
        up = stacked.shape[0]
        hsig = comps[0][0].plan.sig_tuple
        sig = ("bitmap_batch", hsig, len(shards), qp, up)
        expr = comps[0][1]

        def build():
            @jax.jit
            def fn(stacked, idxs):
                leaves = tuple(stacked[ix] for ix in idxs)  # each (Qp, S, W)
                return expr(leaves)

            return fn

        fn = self._fn_build(self._bitmap_fns, sig, build, health_sig=hsig)
        with self._lock:
            self.counters["bitmap_dispatches"] += 1
        # block_until_ready inside the guard, like bitmap(): an async
        # device fault must classify here, not inside a later Row op.
        planes = self._device_call(
            hsig,
            lambda: fn(stacked, idxs).block_until_ready())  # (Qp, Sp, W)
        return [
            Row({shard: planes[qi if inverse is None else int(inverse[qi]), i]
                 for i, shard in enumerate(shards)})
            for qi in range(n_calls)
        ]

    def topn_shard_counts(
        self, index: str, field: str, row_ids: Sequence[int],
        shards: Sequence[int], src_call: Optional[Call] = None,
        need_row_counts: bool = True,
    ):
        """Per-(row, shard) count matrices in one device program.

        Returns (row_counts, inter_counts, src_counts): the first two are
        (R, S) int arrays, src_counts is (S,) — popcount of the src bitmap
        per shard, which the tanimoto coefficient needs
        (fragment.go:1008-1027). inter_counts/src_counts are None without a
        src call. Per-shard granularity preserves the reference's per-shard
        MinThreshold semantics (fragment.go:899-990) while batching all
        popcounts.

        `need_row_counts=False` skips the candidate-plane popcount pass and
        returns None row_counts: the executor's TopN phase-1 ranks with
        cache counts and phase-2 at threshold<=1 needs only intersections,
        so the common TopN query never pays for the (R, S, W) popcount —
        only the fused AND+popcount program over the resident stack.
        """
        shards = tuple(shards)
        # Canonical (sorted, deduped) row order: the stacked tensor and the
        # result memo are keyed on it, so TopN phase-1 (first-seen candidate
        # order) and the phase-2 refetch (sorted ids) share one device
        # tensor and one memo entry instead of duplicating both.
        req = np.asarray(row_ids, dtype=np.int64)
        canon = np.unique(req)
        sel = np.searchsorted(canon, req)  # canonical -> requested order
        canon_rows = [int(r) for r in canon]
        s_real = len(shards)
        leaves = [Leaf(field, VIEW_STANDARD, r) for r in canon_rows]
        src_sig = None
        comp = expr = None
        if src_call is not None:
            comp, expr = self._compile(index, src_call)
            src_sig = tuple(comp.signature)
        mkey = ("topn_shard", index, field, tuple(canon_rows), shards,
                src_sig, tuple(comp.leaves) if comp else None,
                need_row_counts)
        fp = tuple(self._fingerprint(index, leaf, shards) for leaf in leaves)
        if comp is not None:
            fp = fp + tuple(
                self._fingerprint(index, leaf, shards) for leaf in comp.leaves
            )

        def answer(value):
            row_counts, inter, src_counts = value
            return (
                row_counts[sel] if row_counts is not None else None,
                inter[sel] if inter is not None else None,
                src_counts,
            )

        hit = self._aux_probe(mkey, fp)
        if hit is not None:
            return answer(hit)

        # The candidate-plane popcounts (row_counts) are INDEPENDENT of the
        # src call, so they memoize under their own key: a TopN stream with
        # a varying filter (each query a new src row — the ChEMBL serving
        # shape) pays for the (R, S, W) popcount pass at most once, and
        # every subsequent query runs only the fused AND+popcount program
        # below. Without this split each new src re-read the full candidate
        # stack twice (r04: topn_qps 2.69 vs sum_qps 199 at the same shape).
        # pad_pow2: phase-2 candidate counts vary per query (each query's
        # winner set differs), so the row axis pads to a power of two to
        # keep the compiled-program population at a handful of sizes.
        rows_tensor = self._stacked_leaf_tensor(index, leaves, shards,
                                                pad_pow2=True)  # (Rp, S, W)
        r_real = len(canon_rows)
        row_counts = None
        if need_row_counts:
            # Probe-time fingerprint discipline (see memo_probe): fp was
            # computed BEFORE the gather above; its first len(leaves)
            # entries are exactly the candidate-row fingerprints.
            rows_fp = fp[: len(leaves)]
            rkey = ("topn_rows", index, field, tuple(canon_rows), shards)
            row_counts = self._aux_probe(rkey, rows_fp)
            if row_counts is None:
                sig = ("topn_shard", len(shards), rows_tensor.shape[0])

                def build():
                    @jax.jit
                    def fn(stacked):
                        return jnp.sum(
                            jax.lax.population_count(stacked).astype(jnp.int32), axis=2
                        )

                    return fn

                fn = self._fn_build(self._count_fns, sig, build)
                row_counts = self._device_call(
                    None,
                    lambda: np.asarray(fn(rows_tensor))[:r_real, :s_real])
                self._aux_store(rkey, rows_fp, row_counts)

        if src_call is not None:
            src_leaves = self._leaf_tensor(index, comp.leaves, shards)
            sig = ("topn_shard_src", src_sig, len(shards), rows_tensor.shape[0])

            def build():
                @jax.jit
                def fn(stacked, src_lv):
                    src = expr(src_lv)
                    src_counts = jnp.sum(
                        jax.lax.population_count(src).astype(jnp.int32), axis=1
                    )
                    # AND+popcount+reduce fuses into one pass over the
                    # stack — the masked plane is never materialized.
                    masked = jnp.bitwise_and(stacked, src[None, :, :])
                    inter = jnp.sum(
                        jax.lax.population_count(masked).astype(jnp.int32), axis=2
                    )
                    return inter, src_counts

                return fn

            fn = self._fn_build(self._count_fns, sig, build)

            def run():
                inter, src_counts = fn(rows_tensor, src_leaves)
                return (np.asarray(inter)[:r_real, :s_real],
                        np.asarray(src_counts)[:s_real])

            inter, src_counts = self._device_call(None, run)
            value = (row_counts, inter, src_counts)
        else:
            value = (row_counts, None, None)
        self._aux_store(mkey, fp, value)
        return answer(value)

    def topn_counts(
        self, index: str, field: str, row_ids: Sequence[int],
        shards: Sequence[int], src_call: Optional[Call] = None,
    ) -> np.ndarray:
        """Total per-row counts across shards (optionally ∩ src bitmap) in
        one batched program — the distributed TopN inner loop. Canonical
        row ordering + the composite-result memo, as topn_shard_counts."""
        shards = tuple(shards)
        req = np.asarray(row_ids, dtype=np.int64)
        canon = np.unique(req)
        sel = np.searchsorted(canon, req)
        row_ids = [int(r) for r in canon]
        src_sig = None
        comp0 = expr0 = None
        if src_call is not None:
            comp0, expr0 = self._compile(index, src_call)
            src_sig = tuple(comp0.signature)
        mkey = ("topn_total", index, field, tuple(row_ids), shards, src_sig,
                tuple(comp0.leaves) if comp0 else None)
        leaves_fp = [Leaf(field, VIEW_STANDARD, r) for r in row_ids]
        fp = tuple(self._fingerprint(index, leaf, shards) for leaf in leaves_fp)
        if comp0 is not None:
            fp = fp + tuple(
                self._fingerprint(index, leaf, shards) for leaf in comp0.leaves
            )
        hit = self._aux_probe(mkey, fp)
        if hit is not None:
            return hit[sel]
        leaves = leaves_fp
        # pad_pow2: candidate-id counts vary per query; see topn_shard_counts.
        rows_tensor = self._stacked_leaf_tensor(index, leaves, shards,
                                                pad_pow2=True)  # (Rp, S, W)
        r_real = len(row_ids)
        if src_call is not None:
            comp, expr = comp0, expr0  # compiled once above for the memo key
            src_leaves = self._leaf_tensor(index, comp.leaves, shards)
            sig = ("topn_src", tuple(comp.signature), len(shards),
                   rows_tensor.shape[0])

            def build():
                @jax.jit
                def fn(stacked, src_lv):
                    src = expr(src_lv)  # (S, W)
                    masked = jnp.bitwise_and(stacked, src[None, :, :])
                    return jnp.sum(
                        jax.lax.population_count(masked).astype(jnp.int32), axis=(1, 2)
                    )

                return fn

            fn = self._fn_build(self._count_fns, sig, build)
            value = self._device_call(
                None, lambda: np.asarray(fn(rows_tensor, src_leaves))[:r_real])
            self._aux_store(mkey, fp, value)
            return value[sel]

        sig = ("topn", len(shards), rows_tensor.shape[0])

        def build():
            @jax.jit
            def fn(stacked):
                return jnp.sum(
                    jax.lax.population_count(stacked).astype(jnp.int32), axis=(1, 2)
                )

            return fn

        fn = self._fn_build(self._count_fns, sig, build)
        value = self._device_call(
            None, lambda: np.asarray(fn(rows_tensor))[:r_real])
        self._aux_store(mkey, fp, value)
        return value[sel]

    def bsi_val_count(
        self, index: str, field: str, kind: str, bit_depth: int,
        shards: Sequence[int], filter_call: Optional[Call] = None,
    ):
        """Batched BSI Sum/Min/Max across all shards in one device program.

        kind='sum' returns (depth+1,) per-plane global counts (host composes
        the weighted sum in Python ints). kind='min'/'max' returns
        (bits (depth,), count) — the bit-sliced scan of fragment.go:603-657
        run over the full sharded plane set, so cross-shard min/max needs no
        per-shard ValCount merge.
        """
        shards = tuple(shards)
        view = VIEW_BSI_GROUP_PREFIX + field
        leaves = [Leaf(field, view, i) for i in range(bit_depth + 1)]
        fsig = ()
        comp = expr = None
        if filter_call is not None:
            comp, expr = self._compile(index, filter_call)
            fsig = tuple(comp.signature)
        # Result memo: a repeat Sum/Min/Max over unchanged fragments is
        # host-only work (the val-count outputs are tiny).
        mkey = ("bsi", index, field, kind, bit_depth, shards, fsig,
                tuple(comp.leaves) if comp else None)
        fp = tuple(self._fingerprint(index, leaf, shards) for leaf in leaves)
        if comp is not None:
            fp = fp + tuple(
                self._fingerprint(index, leaf, shards) for leaf in comp.leaves
            )
        hit = self._aux_probe(mkey, fp)
        if hit is not None:
            return hit

        planes = self._stacked_leaf_tensor(index, leaves, shards)  # (D+1, S, W)
        filter_leaves = None
        if filter_call is not None:
            filter_leaves = self._leaf_tensor(index, comp.leaves, shards)
        sig = ("bsi", kind, bit_depth, len(shards), fsig)

        def build():
            def total(x):
                return jnp.sum(jax.lax.population_count(x).astype(jnp.int32))

            if kind == "sum":
                @jax.jit
                def fn(planes, flt):
                    stacked = planes  # (D+1, S, W)
                    if expr is not None:
                        stacked = jnp.bitwise_and(stacked, expr(flt)[None])
                    return jnp.sum(
                        jax.lax.population_count(stacked).astype(jnp.int32),
                        axis=(1, 2),
                    )
            else:
                maximize = kind == "max"

                @jax.jit
                def fn(planes, flt):
                    consider = planes[bit_depth]
                    if expr is not None:
                        consider = jnp.bitwise_and(consider, expr(flt))
                    bits = []
                    for i in range(bit_depth - 1, -1, -1):
                        if maximize:
                            x = jnp.bitwise_and(planes[i], consider)
                        else:
                            x = jnp.bitwise_and(consider, jnp.bitwise_not(planes[i]))
                        nonzero = total(x) > 0
                        bit = jnp.where(nonzero, 1, 0) if maximize else jnp.where(nonzero, 0, 1)
                        bits.append(bit.astype(jnp.int32))
                        consider = jnp.where(nonzero, x, consider)
                    bits = (
                        jnp.stack(bits[::-1]) if bits else jnp.zeros((0,), jnp.int32)
                    )
                    return bits, total(consider)

            return fn

        fn = self._fn_build(self._count_fns, sig, build)

        def run():
            # Materialization inside the guard (async-dispatch faults
            # surface here, not at the enqueue).
            out = fn(planes, filter_leaves)
            if kind == "sum":
                return np.asarray(out)
            bits, count = out
            return (np.asarray(bits), int(count))

        value = self._device_call(None, run)
        self._aux_store(mkey, fp, value)
        return value

    def supports(self, call: Call, index: Optional[str] = None):
        """Truthy if `call` compiles onto the fast path.

        With `index`, runs the REAL compiler (holder lookups, no device
        work) so the answer is exact — e.g. a time-quantum Range only
        compiles when the field actually has a quantum and the range
        covers views; the syntactic check alone would claim support and
        then diverge from the fallback's empty-Row semantics. The return
        value is then the compiled (comp, expr) pair, which callers pass
        to count()/bitmap() as comp_expr so the gate and the execution
        share ONE AST walk. Without `index` (callers that don't know it
        yet) the check is syntactic (returns True) and time Ranges are
        conservatively refused. Falsy (False) when not supported."""
        try:
            if index is None:
                self._compile_check(call)
                return True
            return self._compile(index, call)
        except Exception:
            # Any compile failure means "not fast-path" and the executor
            # falls back to the reference walk — correct either way, but a
            # climbing refusal count on a workload that should compile is
            # the signal a gate bug would otherwise bury.
            with self._lock:
                self.counters["compile_gate_refusals"] += 1
            return False

    def _compile_check(self, call: Call) -> None:
        if call.name == "Row":
            return
        if call.name in ("Intersect", "Union", "Difference", "Xor"):
            if not call.children:
                raise QueryError("empty")
            for ch in call.children:
                self._compile_check(ch)
            return
        if call.name == "Range" and call.has_condition_arg():
            return
        raise QueryError(f"not fast-path: {call.name}")
