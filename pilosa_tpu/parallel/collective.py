"""Generalized multi-host collective query execution.

The reference fans every call type out over HTTP and reduces in Python
(/root/reference/executor.go:1393-1440, 1464-1555). The TPU-native fast
path replaces that reduce loop with ONE SPMD program over a global device
mesh spanning every host's chips: each process feeds the shard planes it
owns, XLA inserts ICI/DCN collectives for the reductions, and the
all-reduced result materializes on every host.

Design (round-4 redesign of the round-3 CollectiveWorker):

- **Placement follows the cluster.** The leader derives each process's
  shard list from the REAL jump-hash placement (cluster/hash.py, reference
  cluster.go:776-857) and ships it in the descriptor; global array slots
  are ordered by process so every process contributes exactly the
  fragments it owns. Workers verify ownership of every assigned shard
  against their own cluster view and refuse loudly on mismatch — the
  round-3 block-contiguous layout silently counted unowned slots as zero.
- **Any fast-path call tree.** The descriptor carries the PQL string of
  the (already key-translated) call; every process compiles it with the
  shared engine compiler (parallel/engine.py _Compiler), so any
  Row/Intersect/Union/Difference/Xor/Range tree, TopN candidate counting,
  and BSI Sum/Min/Max run collectively — not just Count(Intersect).
- **Failure semantics.** Every process passes a named barrier (the
  jax.distributed runtime's wait_at_barrier, with a timeout) BEFORE
  entering the device program. A dead or lagging peer times the barrier
  out everywhere; the leader falls back to the HTTP fan-out path and the
  peers simply skip — nobody blocks forever inside an all-reduce.
- **Total order.** Collective entry is serialized per process by a single
  runner thread consuming descriptors in cluster-wide sequence order
  (sequence numbers from the jax.distributed KV store's atomic increment),
  so concurrent leaders cannot interleave SPMD programs differently on
  different processes (deadlock/cross-wired results).
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..constants import VIEW_BSI_GROUP_PREFIX, WORDS_PER_ROW
from ..errors import PilosaError
from .distributed import SHARD_AXIS, global_mesh

DEFAULT_TIMEOUT_MS = int(os.environ.get("PILOSA_COLLECTIVE_TIMEOUT_MS", "10000"))
_SPLIT = 0x7FFF  # 15-bit split keeps per-row sums exact without x64 (distributed._split_sum)


class CollectiveUnavailable(PilosaError):
    """The collective plane cannot (or must not) serve this request;
    callers fall back to the HTTP fan-out path."""


def _dist_client():
    """The jax.distributed runtime client (barrier + KV store), or None
    outside a multi-process job."""
    try:
        from jax._src import distributed as jdist

        return jdist.global_state.client
    except (ImportError, AttributeError):  # pragma: no cover
        # jax._src.distributed is private API: absent (ImportError) or
        # reorganized (AttributeError) both read as "no runtime client".
        return None


def placement(cluster, index: str, n_shards: int, n_processes: int) -> List[List[int]]:
    """Per-process shard lists from the REAL cluster placement.

    Each shard goes to the process of its first available owner per
    jump-hash (cluster.go:776-857). Raises CollectiveUnavailable when any
    owning node's jax process index is unknown (node not in the job, or
    membership status hasn't propagated yet)."""
    slots: List[List[int]] = [[] for _ in range(n_processes)]
    for s in range(n_shards):
        owners = cluster.shard_nodes(index, s)
        owner = next(
            (n for n in owners if n.id not in cluster.unavailable), None
        ) or (owners[0] if owners else None)
        if owner is None:
            raise CollectiveUnavailable(f"no owner for shard {s}")
        p = owner.process_idx
        if p is None or not (0 <= p < n_processes):
            raise CollectiveUnavailable(
                f"node {owner.id} has no known jax process index"
            )
        slots[p].append(s)
    return slots


class CollectiveBackend:
    """Leader + peer sides of collective execution for one server process."""

    def __init__(self, server):
        self.server = server
        self.holder = server.holder
        self.logger = server.logger
        self.timeout_ms = DEFAULT_TIMEOUT_MS
        # Compiled-program cache, entry-bounded LRU: keys embed baked Range
        # predicates, so varied predicates would otherwise pin one XLA
        # executable each forever (same bound as engine.py's fn caches).
        self._fn_cache: Dict[Tuple, object] = {}
        self._fn_budget = int(os.environ.get("PILOSA_FN_CACHE_ENTRIES", 256))
        self._leaf_cache: Dict[Tuple, Tuple[Tuple, object]] = {}
        self._leaf_bytes = 0
        self._leaf_budget = int(
            os.environ.get("PILOSA_COLLECTIVE_LEAF_BYTES", 1 << 28)
        )
        self._lock = threading.Lock()
        self._local_seq = 0
        self._runner = _Runner(self)
        # Descriptor broadcasts ride a shared pool: a thread per peer per
        # query would churn on the hot path (every full-index query).
        self._senders = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="collective-send"
        )

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        self._runner.close()
        self._senders.shutdown(wait=False)

    def active(self) -> bool:
        """True when a multi-process jax job spans the whole cluster — the
        precondition for the collective plane to cover all data."""
        import jax

        n_proc = jax.process_count()
        if n_proc <= 1:
            return False
        cluster = self.server.cluster
        if cluster.unavailable:
            # A down node can't reach the barrier; entering would stall
            # every query the full barrier timeout before falling back.
            # The failure detector already knows — fall back instantly.
            return False
        nodes = cluster.nodes
        if len(nodes) != n_proc:
            return False
        return all(n.process_idx is not None for n in nodes)

    # ---------------------------------------------------------- leader side

    def count(self, index: str, call) -> int:
        desc = self._descriptor(
            "count", index, query=str(call), sig=self._call_sig(index, call)
        )
        lo, hi = self._lead(desc)
        return (int(hi) << 15) + int(lo)

    def topn_counts(self, index: str, field: str, row_ids: Sequence[int],
                    src_call=None) -> np.ndarray:
        """Global per-row counts (optionally ∩ src bitmap) — the distributed
        TopN phase-2 inner loop, one SPMD program for the whole cluster."""
        desc = self._descriptor(
            "topn", index, field=field, rows=[int(r) for r in row_ids],
            query=str(src_call) if src_call is not None else None,
            sig=self._call_sig(index, src_call),
        )
        lo, hi = self._lead(desc)
        return (np.asarray(hi).astype(np.int64) << 15) + np.asarray(lo)

    def bsi_val_count(self, index: str, field: str, kind: str, depth: int,
                      filter_call=None):
        """Collective BSI Sum/Min/Max (fragment.go:565-837 bit-slice scans
        over the global plane set). kind='sum' -> (depth+1,) per-plane
        global counts; 'min'/'max' -> (bits, count)."""
        desc = self._descriptor(
            "bsi", index, field=field, bsi_kind=kind, depth=depth,
            query=str(filter_call) if filter_call is not None else None,
            sig=self._call_sig(index, filter_call),
        )
        out = self._lead(desc)
        if kind == "sum":
            lo, hi = out
            return (np.asarray(hi).astype(np.int64) << 15) + np.asarray(lo)
        bits, count = out
        return np.asarray(bits), int(count)

    def _call_sig(self, index: str, call) -> Optional[str]:
        """Canonical structure signature of a compiled call. Shipped in the
        descriptor so peers can detect schema divergence (a lagging bsig
        depth/offset bakes DIFFERENT predicates into each side of the SPMD
        program — silently wrong sums) and refuse instead of computing."""
        if call is None:
            return None
        comp, _ = self._compile(index, call)
        return repr(tuple(comp.signature))

    def _descriptor(self, kind: str, index: str, query: Optional[str] = None,
                    field: Optional[str] = None, rows: Optional[List[int]] = None,
                    bsi_kind: Optional[str] = None, depth: Optional[int] = None,
                    sig: Optional[str] = None) -> dict:
        import jax

        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        n_shards = idx.max_shard() + 1
        n_proc = jax.process_count()
        if n_proc > 1:
            if not self.active():
                raise CollectiveUnavailable(
                    "jax.distributed job does not span the cluster "
                    f"({len(self.server.cluster.nodes)} nodes, {n_proc} processes)"
                )
            slots = placement(self.server.cluster, index, n_shards, n_proc)
        else:
            slots = [list(range(n_shards))]
        d_local = jax.local_device_count()
        k = max(max(len(s) for s in slots), 1)
        k = ((k + d_local - 1) // d_local) * d_local
        return {
            "type": "collective-exec", "seq": self._next_seq(), "kind": kind,
            "index": index, "query": query, "field": field, "rows": rows,
            "bsiKind": bsi_kind, "depth": depth, "nShards": n_shards,
            "slots": slots, "k": k, "timeoutMs": self.timeout_ms,
            "sig": sig,
        }

    def _next_seq(self) -> int:
        client = _dist_client()
        if client is not None:
            try:
                return int(client.key_value_increment("pilosa-collective-seq", 1))
            except Exception as e:
                raise CollectiveUnavailable(f"seq allocation failed: {e}")
        with self._lock:
            self._local_seq += 1
            return self._local_seq

    def _lead(self, desc: dict):
        """Broadcast the descriptor, enter locally, return the result.

        The broadcast must not wait for peer responses (a peer blocks
        inside the collective until every process enters), and any failure
        surfaces as CollectiveUnavailable so the executor falls back to
        the HTTP fan-out path."""
        import jax

        if jax.process_count() > 1:
            for node in self.server.cluster.nodes:
                if node.id == self.server.cluster.node.id:
                    continue
                self._senders.submit(self._send, node, desc)
        fut = self._runner.submit(desc)
        try:
            return fut.result(timeout=desc["timeoutMs"] / 1000.0 + 30.0)
        except CollectiveUnavailable:
            raise
        except Exception as e:
            raise CollectiveUnavailable(f"collective execution failed: {e}")

    def _send(self, node, desc: dict) -> None:
        try:
            self.server.client.send_message(node, desc)
        except PilosaError as e:
            # The peer misses the descriptor; the barrier times out and
            # every process aborts cleanly instead of hanging.
            self.logger.error("collective broadcast to %s failed: %s", node.id, e)

    # ------------------------------------------------------------ peer side

    def receive(self, desc: dict) -> None:
        """Peer side of the broadcast: enqueue and return immediately (the
        HTTP handler thread must not block inside the collective)."""
        self._runner.submit(desc)

    # ----------------------------------------------------------- execution

    def _enter(self, desc: dict):
        """Execute one descriptor. Called only from the runner thread, in
        cluster-wide seq order."""
        import jax

        index = desc["index"]
        n_proc = jax.process_count()
        pid = jax.process_index()
        slots = desc["slots"]
        k = int(desc["k"])
        if len(slots) != n_proc:
            raise CollectiveUnavailable(
                f"descriptor spans {len(slots)} processes, job has {n_proc}"
            )
        my_shards = [int(s) for s in slots[pid]]
        if len(my_shards) > k:
            raise CollectiveUnavailable("slot range overflow")
        if n_proc > 1:
            self._verify_ownership(index, my_shards)
        mesh = global_mesh()
        self._verify_mesh_layout(mesh, pid)
        s_padded = n_proc * k

        kind = desc["kind"]
        call = None
        if desc.get("query"):
            from ..pql.parser import parse

            call = parse(desc["query"]).calls[0]

        if kind == "count":
            return self._run_count(desc, index, call, my_shards, k, s_padded, mesh)
        if kind == "topn":
            return self._run_topn(desc, index, call, my_shards, k, s_padded, mesh)
        if kind == "bsi":
            return self._run_bsi(desc, index, call, my_shards, k, s_padded, mesh)
        raise CollectiveUnavailable(f"unknown collective kind: {kind}")

    def _verify_ownership(self, index: str, my_shards: List[int]) -> None:
        """Refuse loudly when the leader's placement disagrees with this
        node's cluster view — silently contributing zero planes for
        unowned shards is a wrong count (ADVICE r3 high)."""
        cluster = self.server.cluster
        me = cluster.node.id
        for s in my_shards:
            if not cluster.owns_shard(me, index, s):
                raise CollectiveUnavailable(
                    f"placement mismatch: process assigned shard {s} of "
                    f"{index!r} but node {me} does not own it"
                )

    @staticmethod
    def _verify_mesh_layout(mesh, pid: int) -> None:
        """make_array_from_process_local_data assumes this process's devices
        hold the contiguous slot block [pid*k, (pid+1)*k); that holds only
        when mesh device order is process-contiguous. Check, don't assume."""
        devs = list(mesh.devices.flat)
        mine = [i for i, d in enumerate(devs) if d.process_index == pid]
        if not mine:
            raise CollectiveUnavailable(
                "this process owns no devices in the global mesh"
            )
        if mine != list(range(pid * len(mine), (pid + 1) * len(mine))):
            raise CollectiveUnavailable(
                "global device order is not process-contiguous; "
                "collective slot layout would misplace shards"
            )

    def _barrier(self, desc: dict) -> None:
        import jax

        if jax.process_count() <= 1:
            return
        client = _dist_client()
        if client is None:
            raise CollectiveUnavailable("no distributed runtime client")
        try:
            client.wait_at_barrier(
                f"pilosa-collective-{desc['seq']}", int(desc["timeoutMs"])
            )
        except Exception as e:
            raise CollectiveUnavailable(
                f"collective barrier timed out (seq {desc['seq']}): {e}"
            )

    # ------------------------------------------------------- plane assembly

    def _local_block(self, index: str, leaf, my_shards: List[int], k: int) -> np.ndarray:
        buf = np.zeros((k, WORDS_PER_ROW), dtype=np.uint32)
        for i, s in enumerate(my_shards):
            frag = self.holder.fragment(index, leaf.field, leaf.view, s)
            if frag is not None:
                buf[i] = frag.plane_np(leaf.row)
        return buf

    def _leaf_fingerprint(self, index: str, leaf, my_shards: List[int]) -> Tuple:
        # (incarnation, generation) pairs, as in engine._fingerprint: a
        # deleted-and-recreated index resets generation counters while this
        # name-keyed cache survives, and a bare counter climbing back to a
        # cached value would alias the old index's stale plane.
        return tuple(
            -1 if f is None else (f.incarnation, f.generation)
            for f in (
                self.holder.fragment(index, leaf.field, leaf.view, s)
                for s in my_shards
            )
        )

    def _global_leaf(self, index: str, leaf, my_shards: List[int], k: int,
                     s_padded: int, mesh):
        """(S_padded, W) global array for one leaf; cached per process and
        invalidated by this process's OWN fragment generations (each
        process's buffers are local, so staleness is a local property)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (index, leaf, tuple(my_shards), k, s_padded)
        fp = self._leaf_fingerprint(index, leaf, my_shards)
        with self._lock:
            cached = self._leaf_cache.get(key)
            if cached is not None and cached[0] == fp:
                self._leaf_cache[key] = self._leaf_cache.pop(key)  # LRU touch
                return cached[1]
        block = self._local_block(index, leaf, my_shards, k)
        sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
        arr = jax.make_array_from_process_local_data(
            sharding, block, (s_padded, WORDS_PER_ROW)
        )
        with self._lock:
            prev = self._leaf_cache.pop(key, None)
            if prev is not None:
                self._leaf_bytes -= prev[1].nbytes
            self._leaf_cache[key] = (fp, arr)
            self._leaf_bytes += arr.nbytes
            while self._leaf_bytes > self._leaf_budget and len(self._leaf_cache) > 1:
                old_key = next(iter(self._leaf_cache))
                if old_key == key:
                    break
                self._leaf_bytes -= self._leaf_cache.pop(old_key)[1].nbytes
        return arr

    def _global_stack(self, index: str, leaves, my_shards: List[int], k: int,
                      s_padded: int, mesh):
        """(L, S_padded, W) global array for a leaf stack (TopN rows, BSI
        planes). Gathered fresh: candidate sets vary per query."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        block = np.stack(
            [self._local_block(index, leaf, my_shards, k) for leaf in leaves]
        )
        sharding = NamedSharding(mesh, P(None, SHARD_AXIS, None))
        return jax.make_array_from_process_local_data(
            sharding, block, (len(leaves), s_padded, WORDS_PER_ROW)
        )

    def _compile(self, index: str, call):
        from .engine import _Compiler

        comp = _Compiler(self.holder, index)
        expr = comp.compile(call)
        return comp, expr

    def _fn(self, key: Tuple, build):
        with self._lock:
            fn = self._fn_cache.get(key)
            if fn is not None:
                self._fn_cache[key] = self._fn_cache.pop(key)  # LRU touch
        if fn is None:
            fn = build()
            with self._lock:
                self._fn_cache[key] = fn
                while len(self._fn_cache) > self._fn_budget:
                    self._fn_cache.pop(next(iter(self._fn_cache)))
        return fn

    # -------------------------------------------------------- program kinds

    def _check_sig(self, desc, comp) -> None:
        """Refuse when this process compiled a different program structure
        than the leader (schema divergence: a lagging bsig depth/offset
        bakes different predicates into each side of the SPMD program)."""
        want = desc.get("sig")
        if want is not None and repr(tuple(comp.signature)) != want:
            raise CollectiveUnavailable(
                "schema divergence: local call signature "
                f"{tuple(comp.signature)!r} != leader's {want}"
            )

    def _run_count(self, desc, index, call, my_shards, k, s_padded, mesh):
        import jax
        import jax.numpy as jnp

        comp, expr = self._compile(index, call)
        self._check_sig(desc, comp)
        leaves = tuple(
            self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
            for leaf in comp.leaves
        )
        sig = ("count", tuple(comp.signature), s_padded)

        def build():
            @jax.jit
            def fn(lv):
                pc = jax.lax.population_count(expr(lv)).astype(jnp.int32)
                per = jnp.sum(pc, axis=1)  # (S,) partials, each <= 2^20
                return jnp.sum(per & _SPLIT), jnp.sum(per >> 15)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc)
        lo, hi = fn(leaves)
        return int(lo), int(hi)

    def _run_topn(self, desc, index, call, my_shards, k, s_padded, mesh):
        import jax
        import jax.numpy as jnp

        from .engine import Leaf
        from ..constants import VIEW_STANDARD

        field = desc["field"]
        rows = [int(r) for r in desc["rows"]]
        leaves = [Leaf(field, VIEW_STANDARD, r) for r in rows]
        stacked = self._global_stack(index, leaves, my_shards, k, s_padded, mesh)
        src_leaves = None
        fsig = ()
        expr = None
        if call is not None:
            comp, expr = self._compile(index, call)
            self._check_sig(desc, comp)
            src_leaves = tuple(
                self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
                for leaf in comp.leaves
            )
            fsig = tuple(comp.signature)
        sig = ("topn", fsig, len(rows), s_padded)

        def build():
            @jax.jit
            def fn(stacked, src_lv):
                x = stacked
                if expr is not None:
                    x = jnp.bitwise_and(x, expr(src_lv)[None])
                pc = jax.lax.population_count(x).astype(jnp.int32)
                per = jnp.sum(pc, axis=2)  # (R, S)
                return jnp.sum(per & _SPLIT, axis=1), jnp.sum(per >> 15, axis=1)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc)
        lo, hi = fn(stacked, src_leaves)
        return np.asarray(lo), np.asarray(hi)

    def _run_bsi(self, desc, index, call, my_shards, k, s_padded, mesh):
        import jax
        import jax.numpy as jnp

        from .engine import Leaf

        field = desc["field"]
        depth = int(desc["depth"])
        kind = desc["bsiKind"]
        # The plane layout itself depends on the bsig depth: a peer whose
        # depth disagrees would read its bit-i planes as different
        # magnitudes than the leader. Verify, don't assume.
        fld = self.holder.field(index, field)
        bsig = fld.bsi_group(field) if fld is not None else None
        if bsig is None or bsig.bit_depth() != depth:
            local = "missing" if bsig is None else bsig.bit_depth()
            raise CollectiveUnavailable(
                f"schema divergence: bsig depth for {field!r} is {local}, "
                f"leader says {depth}"
            )
        view = VIEW_BSI_GROUP_PREFIX + field
        leaves = [Leaf(field, view, i) for i in range(depth + 1)]
        planes = self._global_stack(index, leaves, my_shards, k, s_padded, mesh)
        filter_leaves = None
        fsig = ()
        expr = None
        if call is not None:
            comp, expr = self._compile(index, call)
            self._check_sig(desc, comp)
            filter_leaves = tuple(
                self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
                for leaf in comp.leaves
            )
            fsig = tuple(comp.signature)
        sig = ("bsi", kind, depth, fsig, s_padded)

        def build():
            def total(x):
                pc = jax.lax.population_count(x).astype(jnp.int32)
                per = jnp.sum(pc, axis=-1)
                return jnp.sum(per)

            if kind == "sum":
                @jax.jit
                def fn(planes, flt):
                    x = planes
                    if expr is not None:
                        x = jnp.bitwise_and(x, expr(flt)[None])
                    pc = jax.lax.population_count(x).astype(jnp.int32)
                    per = jnp.sum(pc, axis=2)  # (D+1, S)
                    return (
                        jnp.sum(per & _SPLIT, axis=1),
                        jnp.sum(per >> 15, axis=1),
                    )
            else:
                maximize = kind == "max"

                @jax.jit
                def fn(planes, flt):
                    consider = planes[depth]
                    if expr is not None:
                        consider = jnp.bitwise_and(consider, expr(flt))
                    bits = []
                    for i in range(depth - 1, -1, -1):
                        if maximize:
                            x = jnp.bitwise_and(planes[i], consider)
                        else:
                            x = jnp.bitwise_and(consider, jnp.bitwise_not(planes[i]))
                        nonzero = total(x) > 0
                        bit = (
                            jnp.where(nonzero, 1, 0)
                            if maximize
                            else jnp.where(nonzero, 0, 1)
                        )
                        bits.append(bit.astype(jnp.int32))
                        consider = jnp.where(nonzero, x, consider)
                    bits = (
                        jnp.stack(bits[::-1])
                        if bits
                        else jnp.zeros((0,), jnp.int32)
                    )
                    return bits, total(consider)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc)
        out = fn(planes, filter_leaves)
        if kind == "sum":
            lo, hi = out
            return np.asarray(lo), np.asarray(hi)
        bits, count = out
        return np.asarray(bits), int(count)


class _Runner:
    """Single consumer thread executing descriptors in cluster-wide seq
    order. Seqs are dense except when a leader dies between allocating a
    seq and broadcasting it; a bounded gap wait keeps a dead leader from
    stalling the queue (its own peers' barrier times out regardless)."""

    GAP_TIMEOUT = 2.0

    def __init__(self, backend: CollectiveBackend):
        self.backend = backend
        self._heap: List[Tuple[int, int, dict, Future]] = []
        self._tiebreak = 0
        self._cond = threading.Condition()
        self._last_seq = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def submit(self, desc: dict) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._closed:
                fut.set_exception(CollectiveUnavailable("collective runner closed"))
                return fut
            self._tiebreak += 1
            heapq.heappush(
                self._heap, (int(desc["seq"]), self._tiebreak, desc, fut)
            )
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="collective-runner", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return fut

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._heap and not self._closed:
                    self._cond.wait()
                if self._closed:
                    for _, _, _, fut in self._heap:
                        if not fut.done():
                            fut.set_exception(
                                CollectiveUnavailable("collective runner closed")
                            )
                    self._heap.clear()
                    return
                # In-order delivery: wait (bounded) for a missing seq so all
                # processes execute collectives in the same order.
                deadline = time.monotonic() + self.GAP_TIMEOUT
                while (
                    self._heap
                    and self._heap[0][0] > self._last_seq + 1
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                if not self._heap:
                    continue
                seq, _, desc, fut = heapq.heappop(self._heap)
                if seq <= self._last_seq:
                    # A gap-skipped descriptor arrived late: its other
                    # participants already timed out at its barrier, and
                    # entering it now would both stall this runner for the
                    # full barrier timeout and break the same-order
                    # invariant. Reject, never execute.
                    fut.set_exception(CollectiveUnavailable(
                        f"stale collective seq {seq} (already past "
                        f"{self._last_seq})"
                    ))
                    continue
                self._last_seq = seq
            try:
                result = self.backend._enter(desc)
            except BaseException as e:
                if not fut.done():
                    fut.set_exception(e)
                continue
            if not fut.done():
                fut.set_result(result)
