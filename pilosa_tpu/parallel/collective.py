"""Generalized multi-host collective query execution — the PRIMARY read
path for whole-index fast-path queries (docs/multichip.md).

The reference fans every call type out over HTTP and reduces in Python
(/root/reference/executor.go:1393-1440, 1464-1555). The TPU-native fast
path replaces that reduce loop with ONE SPMD program over a global device
mesh spanning every host's chips: each process feeds the shard planes it
owns, XLA inserts ICI/DCN collectives for the reductions, and the
all-reduced result materializes on every host.

Design (round-4 redesign of the round-3 CollectiveWorker, promoted to the
default serving path in PR 12):

- **Placement follows the cluster.** The leader derives each process's
  shard list from the REAL jump-hash placement (cluster/hash.py, reference
  cluster.go:776-857) and ships it in the descriptor; global array slots
  are ordered by process so every process contributes exactly the
  fragments it owns. Workers verify ownership of every assigned shard
  against their own cluster view and refuse loudly on mismatch — the
  round-3 block-contiguous layout silently counted unowned slots as zero.
- **Any fast-path call tree.** The descriptor carries the PQL string of
  the (already key-translated) call; every process compiles it with the
  shared engine compiler (parallel/engine.py _Compiler), so any
  Row/Intersect/Union/Difference/Xor/Range tree, TopN candidate counting,
  and BSI Sum/Min/Max run collectively — not just Count(Intersect).
  Descriptor signatures are the CANONICAL plan signature
  (plan/signature.py), so commutative/associative respellings of one
  query shape share one descriptor signature and one compiled program.
- **Resident sharded stacks.** Each process keeps its slice of the
  global (S, W) leaf planes and (U, S, W) stacks device-resident,
  invalidated by per-fragment (incarnation, generation) fingerprints.
  A stale resident array refreshes by a per-device scattered update of
  just the dirty words (core/fragment.py journals) while the change
  stays under ``delta-max-fraction``; the cold path consults the tier
  manager's compressed host image before walking live containers, and
  LRU-evicted planes DEMOTE through the same tier (docs/
  tiered-storage.md) — per-query host→device plane assembly is a cache
  miss, not the steady state.
- **Batched launches.** ``count_batch`` evaluates N same-canonical-
  signature queries in ONE descriptor: one KV sequence slot, one
  barrier, one SPMD program entry (the collective path's fixed costs).
  The sched micro-batcher feeds it (sched/batcher.py collective_count).
- **Failure semantics.** Every process passes a named barrier (the
  jax.distributed runtime's wait_at_barrier, with a timeout) BEFORE
  entering the device program. A dead or lagging peer times the barrier
  out everywhere; the leader falls back to the HTTP fan-out path and the
  peers simply skip — nobody blocks forever inside an all-reduce.
  Barrier timeouts and broadcast losses feed per-mesh-slice breakers
  (device_health.CollectivePlaneHealth): once open, queries skip the
  collective rung INSTANTLY instead of paying a barrier timeout each,
  and a half-open probe query re-closes the plane. Topology refusals
  (stale epoch, ownership, schema divergence) fall back WITHOUT
  advancing the breakers — membership churn must refresh descriptors,
  not disable the plane wholesale.
- **Epoch-aware membership.** Descriptors carry the leader's routing
  epoch; a peer whose epoch diverges refuses before computing (the
  leader re-routes through the fan-out, which has its own epoch gates),
  ownership is re-verified at entry time against the receiver's CURRENT
  view, and every process re-checks the epoch after plane assembly so a
  cutover committing mid-gather can never ride a GC'd fragment into a
  silently-empty contribution.
- **Total order.** Collective entry is serialized per process by a single
  runner thread consuming descriptors in cluster-wide sequence order
  (sequence numbers from the jax.distributed KV store's atomic increment),
  so concurrent leaders cannot interleave SPMD programs differently on
  different processes (deadlock/cross-wired results).
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import failpoints
from ..constants import VIEW_BSI_GROUP_PREFIX, WORDS_PER_ROW
from ..errors import PilosaError
from ..obs import current as obs_current
from . import CollectiveConfig
from .device_health import BARRIER_TIMEOUT, BROADCAST, CollectivePlaneHealth
from .distributed import SHARD_AXIS, global_mesh

DEFAULT_TIMEOUT_MS = int(os.environ.get("PILOSA_COLLECTIVE_TIMEOUT_MS", "10000"))
_SPLIT = 0x7FFF  # 15-bit split keeps per-row sums exact without x64 (distributed._split_sum)


class CollectiveUnavailable(PilosaError):
    """The collective plane cannot (or must not) serve this request;
    callers fall back to the HTTP fan-out path. `reason` is the
    fallback-counter key (/debug/vars `collective.fallbacks`): breaker
    evidence only for reasons that indicate a FAULT (barrier-timeout,
    error) — topology churn (epoch, ownership, schema, placement,
    inactive) falls back without opening anything."""

    def __init__(self, message: str = "", reason: str = "error"):
        super().__init__(message)
        self.reason = reason


class CollectiveBarrierTimeout(CollectiveUnavailable):
    """A barrier wait expired: some participant never entered. The one
    failure kind that MUST advance the plane breaker — paying a full
    barrier timeout per query on a known-sick plane is the tax the
    breaker exists to remove."""

    def __init__(self, message: str = ""):
        super().__init__(message, reason="barrier-timeout")


def _dist_client():
    """The jax.distributed runtime client (barrier + KV store), or None
    outside a multi-process job."""
    try:
        from jax._src import distributed as jdist

        return jdist.global_state.client
    except (ImportError, AttributeError):  # pragma: no cover
        # jax._src.distributed is private API: absent (ImportError) or
        # reorganized (AttributeError) both read as "no runtime client".
        return None


def placement(cluster, index: str, n_shards: int, n_processes: int) -> List[List[int]]:
    """Per-process shard lists from the REAL cluster placement.

    Each shard goes to the process of its first available owner per
    jump-hash (cluster.go:776-857) — including per-shard routing
    overrides for committed live-rebalance cutovers (cluster/node.py
    shard_nodes follows Cluster.migrated), so a descriptor built
    mid-rebalance reflects the refreshed placement, not the pre-job one.
    Raises CollectiveUnavailable when any owning node's jax process
    index is unknown (node not in the job, or membership status hasn't
    propagated yet)."""
    slots: List[List[int]] = [[] for _ in range(n_processes)]
    for s in range(n_shards):
        owners = cluster.shard_nodes(index, s)
        owner = next(
            (n for n in owners if n.id not in cluster.unavailable), None
        ) or (owners[0] if owners else None)
        if owner is None:
            raise CollectiveUnavailable(
                f"no owner for shard {s}", reason="placement")
        p = owner.process_idx
        if p is None or not (0 <= p < n_processes):
            raise CollectiveUnavailable(
                f"node {owner.id} has no known jax process index",
                reason="placement",
            )
        slots[p].append(s)
    return slots


class CollectiveBackend:
    """Leader + peer sides of collective execution for one server process."""

    def __init__(self, server, config: Optional[CollectiveConfig] = None):
        self.server = server
        self.holder = server.holder
        self.logger = server.logger
        cfg = config or getattr(server, "collective_config", None)
        if cfg is None:
            # No resolved config (library/test use): honor the historical
            # env spellings directly. When a Config DID resolve the
            # [collective] section, flags > env > TOML already happened.
            cfg = CollectiveConfig(
                single_process=int(os.environ.get(
                    "PILOSA_COLLECTIVE_SINGLE_PROCESS", "0")),
                timeout_ms=DEFAULT_TIMEOUT_MS,
                leaf_budget_bytes=int(
                    os.environ.get("PILOSA_COLLECTIVE_LEAF_BYTES", 1 << 28)),
                delta_max_fraction=float(os.environ.get(
                    "PILOSA_COLLECTIVE_DELTA_MAX_FRACTION", "0.25")),
            )
        self.config = cfg
        self.enabled = bool(int(cfg.enabled))
        self.single_process = bool(int(cfg.single_process))
        self.timeout_ms = int(cfg.timeout_ms)
        # Per-device-count override for the MULTICHIP scaling curve:
        # restrict the global mesh to the first N devices (single-process
        # only — a multi-process mesh subset would break the
        # process-contiguity the slot layout assumes).
        self.mesh_devices: Optional[int] = None
        # Collective-plane breakers: barrier timeouts / broadcast losses
        # open per-slice and plane-wide breakers so a sick plane costs an
        # instant fallback, never a barrier timeout per query. Shares the
        # [resilience] section with the peer/device breakers.
        rcfg = getattr(
            getattr(getattr(server, "cluster", None), "health", None),
            "config", None)
        self.health = CollectivePlaneHealth(rcfg)
        # Compiled-program cache, entry-bounded LRU: keys embed baked Range
        # predicates, so varied predicates would otherwise pin one XLA
        # executable each forever (same bound as engine.py's fn caches).
        self._fn_cache: Dict[Tuple, object] = {}
        self._fn_budget = int(os.environ.get("PILOSA_FN_CACHE_ENTRIES", 256))
        # Resident sharded stacks: this process's slices of the global
        # leaf planes and (U, S, W) stacks, fingerprint-invalidated,
        # delta-refreshed, tier-demotable. One byte budget each.
        self._leaf_cache: Dict[Tuple, Tuple[Tuple, object]] = {}
        self._leaf_bytes = 0
        self._leaf_budget = int(cfg.leaf_budget_bytes)
        self._stack_cache: Dict[Tuple, Tuple[Tuple, object]] = {}
        self._stack_bytes = 0
        self._stack_budget = int(cfg.leaf_budget_bytes)
        self._delta_max_fraction = float(cfg.delta_max_fraction)
        self._lock = threading.Lock()
        self._local_seq = 0
        self.counters: Dict[str, int] = {
            "entries": 0,
            "served_count": 0, "served_topn": 0, "served_bsi": 0,
            "batched_entries": 0, "batched_launches": 0,
            "barrier_timeouts": 0, "breaker_short_circuits": 0,
            "resident_hits": 0, "delta_hits": 0, "delta_bytes": 0,
            "full_refreshes": 0, "full_refresh_bytes": 0,
            "tier_promotes": 0, "evictions": 0, "demotions": 0,
            "stale_epoch_refusals": 0, "epoch_rechecks": 0,
        }
        # Why the fast path refused, by CollectiveUnavailable.reason —
        # a climbing CollectiveFallback stat is undiagnosable without it.
        self.fallbacks: Dict[str, int] = {}
        self._runner = _Runner(self)
        # Descriptor broadcasts ride a shared pool: a thread per peer per
        # query would churn on the hot path (every full-index query).
        self._senders = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="collective-send"
        )

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        self._runner.close()
        self._senders.shutdown(wait=False)

    def active(self) -> bool:
        """True when the collective plane may serve whole-index queries:
        a multi-process jax job spanning the whole cluster, or (opt-in,
        `[collective] single-process`) a single-process job whose one
        node holds the whole index."""
        if not self.enabled:
            return False
        import jax

        n_proc = jax.process_count()
        cluster = self.server.cluster
        if n_proc <= 1:
            # One-pod mode: every fragment is local, the barrier is a
            # no-op, and the mesh is the local device mesh. Only safe
            # when the cluster IS this one node — a multi-node cluster
            # without a spanning jax job would count remote shards as
            # silently empty.
            return self.single_process and len(cluster.nodes) <= 1
        if cluster.unavailable:
            # A down node can't reach the barrier; entering would stall
            # every query the full barrier timeout before falling back.
            # The failure detector already knows — fall back instantly.
            return False
        nodes = cluster.nodes
        if len(nodes) != n_proc:
            return False
        return all(n.process_idx is not None for n in nodes)

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def note_fallback(self, reason: str) -> None:
        """Record WHY the fast path refused (the executor calls this on
        every CollectiveUnavailable it catches)."""
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def snapshot(self) -> dict:
        """Wholesale counter export — the `collective` group in
        /debug/vars plus diagnostics aggregates (pilint R4)."""
        with self._lock:
            out = dict(self.counters)
            out["fallbacks"] = dict(self.fallbacks)
            out["leaf_cache_entries"] = len(self._leaf_cache)
            out["leaf_cache_bytes"] = self._leaf_bytes
            out["stack_cache_entries"] = len(self._stack_cache)
            out["stack_cache_bytes"] = self._stack_bytes
        out["health"] = self.health.snapshot()
        return out

    def _tier(self):
        """The engine's TierManager, when one exists: the collective
        plane's resident stacks demote into (and promote from) the SAME
        compressed host tier as the per-node engine caches — tier keys
        share the (index, leaf, shards) shape. Peeks the lazy engine
        slot only: cache maintenance must never be what first opens the
        device backend."""
        ex = getattr(self.server, "executor", None)
        eng = getattr(ex, "_engine", None)
        return getattr(eng, "tier", None)

    # ---------------------------------------------------------- leader side

    def count(self, index: str, call) -> int:
        out = self.count_batch(index, [call])
        return int(out[0])

    def count_batch(self, index: str, calls: Sequence) -> List[int]:
        """N same-canonical-signature Counts in ONE collective entry:
        one KV seq slot, one barrier, one SPMD program — the batched
        launch the sched micro-batcher feeds (docs/multichip.md). The
        calls need not be distinct; duplicates compute once and fan
        back out. Returns per-call counts in input order."""
        calls = list(calls)
        sig = self._call_sig(index, calls[0])
        desc = self._descriptor(
            "count", index, queries=[str(c) for c in calls], sig=sig,
        )
        lo, hi = self._lead(desc)
        lo = np.asarray(lo)
        hi = np.asarray(hi).astype(np.int64)
        with self._lock:
            self.counters["served_count"] += len(calls)
            if len(calls) > 1:
                self.counters["batched_entries"] += len(calls)
                self.counters["batched_launches"] += 1
        return [int(h << 15) + int(l) for l, h in zip(lo, hi)]

    def topn_counts(self, index: str, field: str, row_ids: Sequence[int],
                    src_call=None) -> np.ndarray:
        """Global per-row counts (optionally ∩ src bitmap) — the distributed
        TopN phase-2 inner loop, one SPMD program for the whole cluster."""
        desc = self._descriptor(
            "topn", index, field=field, rows=[int(r) for r in row_ids],
            query=str(src_call) if src_call is not None else None,
            sig=self._call_sig(index, src_call),
        )
        lo, hi = self._lead(desc)
        self._count("served_topn")
        return (np.asarray(hi).astype(np.int64) << 15) + np.asarray(lo)

    def bsi_val_count(self, index: str, field: str, kind: str, depth: int,
                      filter_call=None):
        """Collective BSI Sum/Min/Max (fragment.go:565-837 bit-slice scans
        over the global plane set). kind='sum' -> (depth+1,) per-plane
        global counts; 'min'/'max' -> (bits, count)."""
        desc = self._descriptor(
            "bsi", index, field=field, bsi_kind=kind, depth=depth,
            query=str(filter_call) if filter_call is not None else None,
            sig=self._call_sig(index, filter_call),
        )
        out = self._lead(desc)
        self._count("served_bsi")
        if kind == "sum":
            lo, hi = out
            return (np.asarray(hi).astype(np.int64) << 15) + np.asarray(lo)
        bits, count = out
        return np.asarray(bits), int(count)

    def _call_sig(self, index: str, call) -> Optional[str]:
        """CANONICAL structure signature of a compiled call (the plan
        compiler's sig_tuple, docs/query-compiler.md) — commutative/
        associative respellings of one shape produce the SAME descriptor
        signature, so they share one collective program and one batcher
        group. Shipped in the descriptor so peers can detect schema
        divergence (a lagging bsig depth/offset bakes DIFFERENT
        predicates into each side of the SPMD program — silently wrong
        sums) and refuse instead of computing."""
        if call is None:
            return None
        comp, _ = self._compile(index, call)
        return repr(self._sig_tuple(comp))

    @staticmethod
    def _sig_tuple(comp) -> Tuple:
        return (comp.plan.sig_tuple if comp.plan is not None
                else tuple(comp.signature))

    def _descriptor(self, kind: str, index: str, query: Optional[str] = None,
                    queries: Optional[List[str]] = None,
                    field: Optional[str] = None, rows: Optional[List[int]] = None,
                    bsi_kind: Optional[str] = None, depth: Optional[int] = None,
                    sig: Optional[str] = None) -> dict:
        import jax

        idx = self.holder.index(index)
        if idx is None:
            from ..errors import IndexNotFoundError

            raise IndexNotFoundError(index)
        n_shards = idx.max_shard() + 1
        n_proc = jax.process_count()
        mesh_devices = None
        if n_proc > 1:
            if not self.active():
                raise CollectiveUnavailable(
                    "jax.distributed job does not span the cluster "
                    f"({len(self.server.cluster.nodes)} nodes, {n_proc} processes)",
                    reason="inactive",
                )
            slots = placement(self.server.cluster, index, n_shards, n_proc)
            d_local = jax.local_device_count()
        else:
            slots = [list(range(n_shards))]
            mesh_devices = self.mesh_devices
            d_local = mesh_devices or jax.local_device_count()
        k = max(max(len(s) for s in slots), 1)
        k = ((k + d_local - 1) // d_local) * d_local
        return {
            "type": "collective-exec", "kind": kind,
            "index": index, "query": query, "queries": queries,
            "field": field, "rows": rows,
            "bsiKind": bsi_kind, "depth": depth, "nShards": n_shards,
            "slots": slots, "k": k, "timeoutMs": self.timeout_ms,
            "sig": sig, "meshDevices": mesh_devices,
            # The leader's routing view: peers whose epoch diverges
            # refuse (clean fan-out fallback) rather than contributing
            # planes placed under a different topology.
            "epoch": int(getattr(self.server.cluster, "routing_epoch", 0)),
        }

    def _next_seq(self) -> int:
        client = _dist_client()
        if client is not None:
            try:
                return int(client.key_value_increment("pilosa-collective-seq", 1))
            except Exception as e:
                raise CollectiveUnavailable(f"seq allocation failed: {e}")
        with self._lock:
            self._local_seq += 1
            return self._local_seq

    def _lead(self, desc: dict):
        """Gate on the plane breakers, allocate the sequence slot,
        broadcast the descriptor, enter locally, return the result.

        The broadcast must not wait for peer responses (a peer blocks
        inside the collective until every process enters), and any failure
        surfaces as CollectiveUnavailable so the executor falls back to
        the HTTP fan-out path. Fault outcomes (barrier timeout, runtime
        error) feed the breakers; topology refusals do not."""
        import jax

        n_proc = jax.process_count()
        slices = list(range(n_proc))
        if not self.health.allow(slices):
            # Breaker open: instant fallback — the whole point is never
            # paying a barrier timeout per query on a known-sick plane.
            self._count("breaker_short_circuits")
            raise CollectiveUnavailable(
                "collective plane breaker open", reason="breaker-open")
        # Seq allocated AFTER the gate: a refused query must not burn a
        # cluster-wide sequence slot (and a batch burns exactly one).
        desc["seq"] = self._next_seq()
        if n_proc > 1:
            for node in self.server.cluster.nodes:
                if node.id == self.server.cluster.node.id:
                    continue
                self._senders.submit(self._send, node, desc)
        local = dict(desc)
        local["_trace"] = obs_current()
        fut = self._runner.submit(local)
        try:
            result = fut.result(timeout=desc["timeoutMs"] / 1000.0 + 30.0)
        except CollectiveBarrierTimeout:
            self._count("barrier_timeouts")
            self.health.record_failure(BARRIER_TIMEOUT, slices)
            raise
        except CollectiveUnavailable as e:
            if e.reason == "error":
                # A real fault (runtime error, lost client), not
                # topology churn — evidence for the plane breaker.
                self.health.record_failure("runtime")
            raise
        except Exception as e:
            self.health.record_failure("runtime")
            raise CollectiveUnavailable(f"collective execution failed: {e}")
        self.health.record_success(slices)
        return result

    def _send(self, node, desc: dict) -> None:
        try:
            self.server.client.send_message(node, desc)
        except PilosaError as e:
            # The peer misses the descriptor; the barrier times out and
            # every process aborts cleanly instead of hanging. The
            # breaker evidence points at the unreachable slice.
            if node.process_idx is not None:
                self.health.record_failure(BROADCAST, [node.process_idx])
            self.logger.error("collective broadcast to %s failed: %s", node.id, e)

    # ------------------------------------------------------------ peer side

    def receive(self, desc: dict) -> None:
        """Peer side of the broadcast: enqueue and return immediately (the
        HTTP handler thread must not block inside the collective). Peers
        do NOT consult the breakers — a probing leader's barrier must
        find every healthy peer waiting, or the plane could never
        re-close under a single-leader workload."""
        self._runner.submit(desc)

    # ----------------------------------------------------------- execution

    def _enter(self, desc: dict):
        """Execute one descriptor. Called only from the runner thread, in
        cluster-wide seq order."""
        import jax

        trace = desc.get("_trace")
        t_entry = time.monotonic()
        index = desc["index"]
        n_proc = jax.process_count()
        pid = jax.process_index()
        slots = desc["slots"]
        k = int(desc["k"])
        self._count("entries")
        cluster = self.server.cluster
        epoch0 = int(getattr(cluster, "routing_epoch", 0))
        want_epoch = desc.get("epoch")
        if want_epoch is not None and int(want_epoch) != epoch0:
            # The leader routed under a different topology than ours
            # (mid-rebalance cutover window). Refuse before computing:
            # the leader falls back to the fan-out, whose per-hop epoch
            # gates serve the query correctly either way.
            self._count("stale_epoch_refusals")
            raise CollectiveUnavailable(
                f"routing epoch divergence (descriptor {want_epoch}, "
                f"local {epoch0})", reason="epoch")
        if len(slots) != n_proc:
            raise CollectiveUnavailable(
                f"descriptor spans {len(slots)} processes, job has {n_proc}",
                reason="placement",
            )
        my_shards = [int(s) for s in slots[pid]]
        if len(my_shards) > k:
            raise CollectiveUnavailable("slot range overflow",
                                        reason="placement")
        if n_proc > 1:
            self._verify_ownership(index, my_shards)
        mesh = global_mesh(desc.get("meshDevices") if n_proc == 1 else None)
        self._verify_mesh_layout(mesh, pid)
        s_padded = n_proc * k

        kind = desc["kind"]
        queries = desc.get("queries")
        if queries is None:
            queries = [desc["query"]] if desc.get("query") else []
        calls = []
        if queries:
            from ..pql.parser import parse

            calls = [parse(q).calls[0] for q in queries]

        if kind == "count":
            out = self._run_count(desc, index, calls, my_shards, k,
                                  s_padded, mesh, trace)
        elif kind == "topn":
            out = self._run_topn(desc, index, calls[0] if calls else None,
                                 my_shards, k, s_padded, mesh, trace)
        elif kind == "bsi":
            out = self._run_bsi(desc, index, calls[0] if calls else None,
                                my_shards, k, s_padded, mesh, trace)
        else:
            raise CollectiveUnavailable(f"unknown collective kind: {kind}")
        if int(getattr(cluster, "routing_epoch", 0)) != epoch0:
            # A live-rebalance cutover committed while planes were being
            # assembled/computed: post-commit GC may have read a moved
            # shard's fragment as silently empty. Discard — the leader
            # re-runs through the fan-out on refreshed placement.
            self._count("epoch_rechecks")
            raise CollectiveUnavailable(
                f"routing epoch advanced during collective execution "
                f"({epoch0} -> {cluster.routing_epoch})", reason="epoch")
        if trace is not None:
            trace.record("collective.entry",
                         (time.monotonic() - t_entry) * 1000.0,
                         kind=kind, seq=desc.get("seq"))
        return out

    def _verify_ownership(self, index: str, my_shards: List[int]) -> None:
        """Refuse loudly when the leader's placement disagrees with this
        node's cluster view — silently contributing zero planes for
        unowned shards is a wrong count (ADVICE r3 high)."""
        cluster = self.server.cluster
        me = cluster.node.id
        for s in my_shards:
            if not cluster.owns_shard(me, index, s):
                raise CollectiveUnavailable(
                    f"placement mismatch: process assigned shard {s} of "
                    f"{index!r} but node {me} does not own it",
                    reason="ownership",
                )

    @staticmethod
    def _verify_mesh_layout(mesh, pid: int) -> None:
        """make_array_from_process_local_data assumes this process's devices
        hold the contiguous slot block [pid*k, (pid+1)*k); that holds only
        when mesh device order is process-contiguous. Check, don't assume."""
        devs = list(mesh.devices.flat)
        mine = [i for i, d in enumerate(devs) if d.process_index == pid]
        if not mine:
            raise CollectiveUnavailable(
                "this process owns no devices in the global mesh",
                reason="placement",
            )
        if mine != list(range(pid * len(mine), (pid + 1) * len(mine))):
            raise CollectiveUnavailable(
                "global device order is not process-contiguous; "
                "collective slot layout would misplace shards",
                reason="placement",
            )

    def _barrier(self, desc: dict, trace=None) -> None:
        import jax

        t0 = time.monotonic()
        try:
            # Deterministic chaos hook (docs/durability.md R6 table):
            # fires even in single-process mode, where the real barrier
            # is a no-op, so the MULTICHIP chaos leg exercises the
            # timeout -> breaker -> fallback ladder on one pod.
            failpoints.fire("collective-barrier")
            if jax.process_count() > 1:
                client = _dist_client()
                if client is None:
                    raise CollectiveUnavailable(
                        "no distributed runtime client")
                client.wait_at_barrier(
                    f"pilosa-collective-{desc['seq']}", int(desc["timeoutMs"])
                )
        except CollectiveUnavailable:
            raise
        except Exception as e:
            raise CollectiveBarrierTimeout(
                f"collective barrier timed out (seq {desc['seq']}): {e}"
            )
        finally:
            if trace is not None:
                trace.record("collective.barrier",
                             (time.monotonic() - t0) * 1000.0,
                             seq=desc.get("seq"))

    # ------------------------------------------------- resident plane stacks

    def _local_block(self, index: str, leaf, my_shards: List[int], k: int,
                     frags: Optional[List] = None) -> np.ndarray:
        buf = np.zeros((k, WORDS_PER_ROW), dtype=np.uint32)
        if frags is None:
            frags = [self.holder.fragment(index, leaf.field, leaf.view, s)
                     for s in my_shards]
        for i, frag in enumerate(frags):
            if frag is not None:
                buf[i] = frag.plane_np(leaf.row)
        return buf

    def _leaf_fingerprint(self, index: str, leaf, my_shards: List[int],
                          frags: Optional[List] = None) -> Tuple:
        # (incarnation, generation) pairs, as in engine._fingerprint: a
        # deleted-and-recreated index resets generation counters while this
        # name-keyed cache survives, and a bare counter climbing back to a
        # cached value would alias the old index's stale plane.
        if frags is None:
            frags = (
                self.holder.fragment(index, leaf.field, leaf.view, s)
                for s in my_shards
            )
        return tuple(
            -1 if f is None else (f.incarnation, f.generation)
            for f in frags
        )

    def _collect_updates(self, members, size: int):
        """Dirty-word deltas for stale cache members, or None when only a
        full re-assembly is safe — same contract as the engine's
        _collect_updates (missing fragment, recreated incarnation,
        journal overflow, or budget exceeded all poison to None).

        `members`: iterable of (coords, frag, row, old_fp, new_fp);
        coords are LOCAL block coordinates ((slot,) for a leaf,
        (u, slot) for a stack). Returns a list of (coords, col32
        indices, uint32 values) — possibly empty (generation churn from
        rows outside this cache, zero bytes to move)."""
        from .engine import ShardedQueryEngine

        out = []
        n32 = 0
        for coords, frag, row, old_fp, new_fp in members:
            if frag is None or old_fp == -1 or new_fp == -1:
                return None
            if old_fp[0] != new_fp[0] or frag.incarnation != new_fp[0]:
                return None
            w = frag.dirty_words_since(row, old_fp[1])
            if w is None:
                return None
            if not len(w):
                continue
            n32 += 2 * len(w)
            if n32 > self._delta_max_fraction * size:
                return None
            cols, vals = ShardedQueryEngine._updates32(
                w, frag.row_words64(row, w))
            out.append((coords, cols, vals))
        return out

    def _delta_scatter(self, arr, updates, pid: int, k: int, stacked: bool):
        """Apply (coords, cols, vals) updates to this process's
        addressable pieces of a global array and reassemble — the
        multi-process-safe delta path. Each piece is a SINGLE-DEVICE
        array, so the scatter is a local program (no collectives, no
        peer coordination); pieces without dirty words are reused
        as-is, so a 1-bit write moves a handful of scattered words to
        exactly one device instead of re-uploading the plane."""
        import jax

        from .engine import ShardedQueryEngine

        slot_axis = 1 if stacked else 0
        pieces = []
        for sh in arr.addressable_shards:
            sl = sh.index[slot_axis]
            lo = sl.start or 0
            hi = sl.stop if sl.stop is not None else arr.shape[slot_axis]
            sel = [(co, pid * k + co[-1] - lo, cols, vals)
                   for co, cols, vals in updates
                   if lo <= pid * k + co[-1] < hi]
            if not sel:
                pieces.append(sh.data)
                continue
            rows = np.concatenate(
                [np.full(len(c), r, np.int32) for _, r, c, _ in sel])
            cols = np.concatenate([c for _, _, c, _ in sel])
            vals = np.concatenate([v for _, _, _, v in sel])
            if stacked:
                us = np.concatenate(
                    [np.full(len(c), co[0], np.int32) for co, _, c, _ in sel])
                us, rows, cols, vals = ShardedQueryEngine._pad_updates(
                    [us, rows, cols, vals])
                fn = self._fn(
                    ("scatter3", sh.data.shape, len(rows)),
                    lambda: jax.jit(
                        lambda a, u, r, c, v: a.at[u, r, c].set(v)))
                pieces.append(fn(sh.data, us, rows, cols, vals))
            else:
                rows, cols, vals = ShardedQueryEngine._pad_updates(
                    [rows, cols, vals])
                fn = self._fn(
                    ("scatter2", sh.data.shape, len(rows)),
                    lambda: jax.jit(lambda a, r, c, v: a.at[r, c].set(v)))
                pieces.append(fn(sh.data, rows, cols, vals))
        return jax.make_array_from_single_device_arrays(
            arr.shape, arr.sharding, pieces)

    def _byte_put(self, cache: Dict, key, entry: Tuple, budget: int,
                  used: int, evicted: Optional[List] = None) -> int:
        """Insert at MRU, evict LRU past the byte budget; returns updated
        used-bytes. Caller holds self._lock. Evicted keys collect into
        `evicted` for off-lock tier demotion — eviction is demotion, not
        loss (docs/tiered-storage.md)."""
        prev = cache.pop(key, None)
        if prev is not None:
            used -= prev[1].nbytes
        used += entry[1].nbytes
        cache[key] = entry
        while used > budget and len(cache) > 1:
            old_key = next(iter(cache))
            if old_key == key:
                break
            used -= cache.pop(old_key)[1].nbytes
            self.counters["evictions"] += 1
            if evicted is not None:
                evicted.append(old_key)
        return used

    def _demote_keys(self, keys) -> None:
        """Hand evicted resident planes to the tier manager (off-lock):
        the compressed host image makes the next cold assembly a decode,
        not a container walk. Keys are cache keys; the tier key is their
        (index, leaf, shards) prefix — the same key space the engine
        uses, so the two planes share one inclusive host tier."""
        if not keys:
            return
        tier = self._tier()
        if tier is None:
            return
        from ..plan import Leaf

        for key in keys:
            index, leaves, shards = key[0], key[1], key[2]
            # Leaf IS a NamedTuple: a leaf-cache key holds one Leaf, a
            # stack-cache key holds a tuple of them — a bare tuple check
            # would iterate a single Leaf's fields.
            if isinstance(leaves, Leaf):
                leaves = (leaves,)
            for leaf in leaves:
                if tier.demote((index, leaf, shards)):
                    self._count("demotions")

    def _global_leaf(self, index: str, leaf, my_shards: List[int], k: int,
                     s_padded: int, mesh):
        """(S_padded, W) global array for one leaf — RESIDENT: cached per
        process, invalidated by this process's OWN fragment generations
        (each process's buffers are local, so staleness is a local
        property), delta-refreshed from the dirty-word journals, and
        assembled from the compressed tier image when cold."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        pid = jax.process_index()
        # Mesh identity in the key: the same (shards, k, s_padded) over a
        # DIFFERENT mesh width (mesh_devices scaling) is a different
        # device layout — a cross-mesh resident hit would silently serve
        # the old layout.
        key = (index, leaf, tuple(my_shards), k, s_padded,
               int(mesh.devices.size))
        frags = [self.holder.fragment(index, leaf.field, leaf.view, s)
                 for s in my_shards]
        fp = self._leaf_fingerprint(index, leaf, my_shards, frags)
        with self._lock:
            cached = self._leaf_cache.get(key)
            if cached is not None and cached[0] == fp:
                self._leaf_cache[key] = self._leaf_cache.pop(key)  # LRU touch
                self.counters["resident_hits"] += 1
                return cached[1]
            stale = cached
        evicted: List = []
        if stale is not None and self._delta_max_fraction > 0 \
                and len(stale[0]) == len(fp):
            updates = self._collect_updates(
                (((i,), frags[i], leaf.row, stale[0][i], fp[i])
                 for i in range(len(frags)) if stale[0][i] != fp[i]),
                stale[1].size,
            )
            if updates is not None:
                arr = (stale[1] if not updates else self._delta_scatter(
                    stale[1], updates, pid, k, stacked=False))
                moved = sum(c.nbytes + v.nbytes for _, c, v in updates)
                with self._lock:
                    self.counters["delta_hits"] += 1
                    self.counters["delta_bytes"] += moved
                    self._leaf_bytes = self._byte_put(
                        self._leaf_cache, key, (fp, arr),
                        self._leaf_budget, self._leaf_bytes, evicted)
                self._demote_keys(evicted)
                return arr
        # Cold (or delta-ineligible): compressed tier image first, live
        # container walk second.
        block = None
        tier = self._tier()
        if tier is not None:
            block = tier.promote((index, leaf, tuple(my_shards)), frags, fp, k)
        tier_hit = block is not None
        if block is None:
            block = self._local_block(index, leaf, my_shards, k, frags)
        sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
        arr = jax.make_array_from_process_local_data(
            sharding, block, (s_padded, WORDS_PER_ROW)
        )
        with self._lock:
            if tier_hit:
                self.counters["tier_promotes"] += 1
            self.counters["full_refreshes"] += 1
            self.counters["full_refresh_bytes"] += int(block.nbytes)
            self._leaf_bytes = self._byte_put(
                self._leaf_cache, key, (fp, arr),
                self._leaf_budget, self._leaf_bytes, evicted)
        self._demote_keys(evicted)
        return arr

    def _global_stack(self, index: str, leaves, my_shards: List[int], k: int,
                      s_padded: int, mesh):
        """(L, S_padded, W) global array for a leaf stack (TopN rows, BSI
        planes) — RESIDENT like the leaves: fingerprint-invalidated,
        delta-refreshed per device piece, LRU-bounded. BSI plane sets
        are stable per field (big win); TopN candidate stacks cache per
        rows-tuple so repeated hot TopNs stop re-walking containers."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        pid = jax.process_index()
        leaves = list(leaves)
        key = (index, tuple(leaves), tuple(my_shards), k, s_padded,
               int(mesh.devices.size))
        frags = [
            [self.holder.fragment(index, leaf.field, leaf.view, s)
             for s in my_shards]
            for leaf in leaves
        ]
        fp = tuple(
            self._leaf_fingerprint(index, leaf, my_shards, frags[u])
            for u, leaf in enumerate(leaves)
        )
        with self._lock:
            cached = self._stack_cache.get(key)
            if cached is not None and cached[0] == fp:
                self._stack_cache[key] = self._stack_cache.pop(key)
                self.counters["resident_hits"] += 1
                return cached[1]
            stale = cached
        evicted: List = []
        if stale is not None and self._delta_max_fraction > 0 \
                and len(stale[0]) == len(fp) \
                and all(len(o) == len(n) for o, n in zip(stale[0], fp)):

            def members():
                for u, leaf in enumerate(leaves):
                    if stale[0][u] == fp[u]:
                        continue
                    for i in range(len(my_shards)):
                        if stale[0][u][i] == fp[u][i]:
                            continue
                        yield ((u, i), frags[u][i], leaf.row,
                               stale[0][u][i], fp[u][i])

            updates = self._collect_updates(members(), stale[1].size)
            if updates is not None:
                arr = (stale[1] if not updates else self._delta_scatter(
                    stale[1], updates, pid, k, stacked=True))
                moved = sum(c.nbytes + v.nbytes for _, c, v in updates)
                with self._lock:
                    self.counters["delta_hits"] += 1
                    self.counters["delta_bytes"] += moved
                    self._stack_bytes = self._byte_put(
                        self._stack_cache, key, (fp, arr),
                        self._stack_budget, self._stack_bytes, evicted)
                self._demote_keys(evicted)
                return arr
        tier = self._tier()
        blocks = []
        for u, leaf in enumerate(leaves):
            block = None
            if tier is not None:
                block = tier.promote(
                    (index, leaf, tuple(my_shards)), frags[u], fp[u], k)
            if block is not None:
                self._count("tier_promotes")
            else:
                block = self._local_block(index, leaf, my_shards, k, frags[u])
            blocks.append(block)
        block = np.stack(blocks)
        sharding = NamedSharding(mesh, P(None, SHARD_AXIS, None))
        arr = jax.make_array_from_process_local_data(
            sharding, block, (len(leaves), s_padded, WORDS_PER_ROW)
        )
        with self._lock:
            self.counters["full_refreshes"] += 1
            self.counters["full_refresh_bytes"] += int(block.nbytes)
            self._stack_bytes = self._byte_put(
                self._stack_cache, key, (fp, arr),
                self._stack_budget, self._stack_bytes, evicted)
        self._demote_keys(evicted)
        return arr

    def _compile(self, index: str, call):
        from .engine import _Compiler

        comp = _Compiler(self.holder, index)
        expr = comp.compile(call)
        return comp, expr

    def _fn(self, key: Tuple, build):
        with self._lock:
            fn = self._fn_cache.get(key)
            if fn is not None:
                self._fn_cache[key] = self._fn_cache.pop(key)  # LRU touch
        if fn is None:
            fn = build()
            with self._lock:
                self._fn_cache[key] = fn
                while len(self._fn_cache) > self._fn_budget:
                    self._fn_cache.pop(next(iter(self._fn_cache)))
        return fn

    # -------------------------------------------------------- program kinds

    def _check_sig(self, desc, comp) -> None:
        """Refuse when this process compiled a different program structure
        than the leader (schema divergence: a lagging bsig depth/offset
        bakes different predicates into each side of the SPMD program)."""
        want = desc.get("sig")
        if want is not None and repr(self._sig_tuple(comp)) != want:
            raise CollectiveUnavailable(
                "schema divergence: local call signature "
                f"{self._sig_tuple(comp)!r} != leader's {want}",
                reason="schema",
            )

    def _run_count(self, desc, index, calls, my_shards, k, s_padded, mesh,
                   trace=None):
        import jax
        import jax.numpy as jnp

        # Duplicates (N clients asking the SAME hot query) compute once;
        # padding to a pow2 batch size keeps the compiled-program count
        # logarithmic in batch_max instead of linear.
        queries = [str(c) for c in calls]
        uniq: Dict[str, int] = {}
        ucalls = []
        for q, c in zip(queries, calls):
            if q not in uniq:
                uniq[q] = len(ucalls)
                ucalls.append(c)
        comps = [self._compile(index, c) for c in ucalls]
        for comp, _ in comps:
            self._check_sig(desc, comp)
        all_leaves = [
            tuple(self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
                  for leaf in comp.leaves)
            for comp, _ in comps
        ]
        n = len(all_leaves)
        n_pad = 1 << (n - 1).bit_length() if n else 1
        all_leaves = tuple(all_leaves + [all_leaves[0]] * (n_pad - n))
        expr = comps[0][1]
        sig = ("count", self._sig_tuple(comps[0][0]), n_pad, s_padded,
               int(mesh.devices.size))

        def build():
            @jax.jit
            def fn(lvs):
                los, his = [], []
                for lv in lvs:
                    pc = jax.lax.population_count(expr(lv)).astype(jnp.int32)
                    per = jnp.sum(pc, axis=1)  # (S,) partials, each <= 2^20
                    los.append(jnp.sum(per & _SPLIT))
                    his.append(jnp.sum(per >> 15))
                return jnp.stack(los), jnp.stack(his)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc, trace)
        lo, hi = fn(all_leaves)
        lo = np.asarray(lo)
        hi = np.asarray(hi)
        order = [uniq[q] for q in queries]
        return lo[order], hi[order]

    def _run_topn(self, desc, index, call, my_shards, k, s_padded, mesh,
                  trace=None):
        import jax
        import jax.numpy as jnp

        from .engine import Leaf
        from ..constants import VIEW_STANDARD

        field = desc["field"]
        rows = [int(r) for r in desc["rows"]]
        leaves = [Leaf(field, VIEW_STANDARD, r) for r in rows]
        stacked = self._global_stack(index, leaves, my_shards, k, s_padded, mesh)
        src_leaves = None
        fsig = ()
        expr = None
        if call is not None:
            comp, expr = self._compile(index, call)
            self._check_sig(desc, comp)
            src_leaves = tuple(
                self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
                for leaf in comp.leaves
            )
            fsig = self._sig_tuple(comp)
        sig = ("topn", fsig, len(rows), s_padded, int(mesh.devices.size))

        def build():
            @jax.jit
            def fn(stacked, src_lv):
                x = stacked
                if expr is not None:
                    x = jnp.bitwise_and(x, expr(src_lv)[None])
                pc = jax.lax.population_count(x).astype(jnp.int32)
                per = jnp.sum(pc, axis=2)  # (R, S)
                return jnp.sum(per & _SPLIT, axis=1), jnp.sum(per >> 15, axis=1)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc, trace)
        lo, hi = fn(stacked, src_leaves)
        return np.asarray(lo), np.asarray(hi)

    def _run_bsi(self, desc, index, call, my_shards, k, s_padded, mesh,
                 trace=None):
        import jax
        import jax.numpy as jnp

        from .engine import Leaf

        field = desc["field"]
        depth = int(desc["depth"])
        kind = desc["bsiKind"]
        # The plane layout itself depends on the bsig depth: a peer whose
        # depth disagrees would read its bit-i planes as different
        # magnitudes than the leader. Verify, don't assume.
        fld = self.holder.field(index, field)
        bsig = fld.bsi_group(field) if fld is not None else None
        if bsig is None or bsig.bit_depth() != depth:
            local = "missing" if bsig is None else bsig.bit_depth()
            raise CollectiveUnavailable(
                f"schema divergence: bsig depth for {field!r} is {local}, "
                f"leader says {depth}", reason="schema",
            )
        view = VIEW_BSI_GROUP_PREFIX + field
        leaves = [Leaf(field, view, i) for i in range(depth + 1)]
        planes = self._global_stack(index, leaves, my_shards, k, s_padded, mesh)
        filter_leaves = None
        fsig = ()
        expr = None
        if call is not None:
            comp, expr = self._compile(index, call)
            self._check_sig(desc, comp)
            filter_leaves = tuple(
                self._global_leaf(index, leaf, my_shards, k, s_padded, mesh)
                for leaf in comp.leaves
            )
            fsig = self._sig_tuple(comp)
        sig = ("bsi", kind, depth, fsig, s_padded, int(mesh.devices.size))

        def build():
            def total(x):
                pc = jax.lax.population_count(x).astype(jnp.int32)
                per = jnp.sum(pc, axis=-1)
                return jnp.sum(per)

            if kind == "sum":
                @jax.jit
                def fn(planes, flt):
                    x = planes
                    if expr is not None:
                        x = jnp.bitwise_and(x, expr(flt)[None])
                    pc = jax.lax.population_count(x).astype(jnp.int32)
                    per = jnp.sum(pc, axis=2)  # (D+1, S)
                    return (
                        jnp.sum(per & _SPLIT, axis=1),
                        jnp.sum(per >> 15, axis=1),
                    )
            else:
                maximize = kind == "max"

                @jax.jit
                def fn(planes, flt):
                    consider = planes[depth]
                    if expr is not None:
                        consider = jnp.bitwise_and(consider, expr(flt))
                    bits = []
                    for i in range(depth - 1, -1, -1):
                        if maximize:
                            x = jnp.bitwise_and(planes[i], consider)
                        else:
                            x = jnp.bitwise_and(consider, jnp.bitwise_not(planes[i]))
                        nonzero = total(x) > 0
                        bit = (
                            jnp.where(nonzero, 1, 0)
                            if maximize
                            else jnp.where(nonzero, 0, 1)
                        )
                        bits.append(bit.astype(jnp.int32))
                        consider = jnp.where(nonzero, x, consider)
                    bits = (
                        jnp.stack(bits[::-1])
                        if bits
                        else jnp.zeros((0,), jnp.int32)
                    )
                    return bits, total(consider)

            return fn

        fn = self._fn(sig, build)
        self._barrier(desc, trace)
        out = fn(planes, filter_leaves)
        if kind == "sum":
            lo, hi = out
            return np.asarray(lo), np.asarray(hi)
        bits, count = out
        return np.asarray(bits), int(count)


class _Runner:
    """Single consumer thread executing descriptors in cluster-wide seq
    order. Seqs are dense except when a leader dies between allocating a
    seq and broadcasting it; a bounded gap wait keeps a dead leader from
    stalling the queue (its own peers' barrier times out regardless)."""

    GAP_TIMEOUT = 2.0

    def __init__(self, backend: CollectiveBackend):
        self.backend = backend
        self._heap: List[Tuple[int, int, dict, Future]] = []
        self._tiebreak = 0
        self._cond = threading.Condition()
        self._last_seq = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def submit(self, desc: dict) -> Future:
        fut: Future = Future()
        with self._cond:
            if self._closed:
                fut.set_exception(CollectiveUnavailable(
                    "collective runner closed", reason="closed"))
                return fut
            self._tiebreak += 1
            heapq.heappush(
                self._heap, (int(desc["seq"]), self._tiebreak, desc, fut)
            )
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="collective-runner", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return fut

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._heap and not self._closed:
                    self._cond.wait()
                if self._closed:
                    for _, _, _, fut in self._heap:
                        if not fut.done():
                            fut.set_exception(CollectiveUnavailable(
                                "collective runner closed", reason="closed"))
                    self._heap.clear()
                    return
                # In-order delivery: wait (bounded) for a missing seq so all
                # processes execute collectives in the same order.
                deadline = time.monotonic() + self.GAP_TIMEOUT
                while (
                    self._heap
                    and self._heap[0][0] > self._last_seq + 1
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                if not self._heap:
                    continue
                seq, _, desc, fut = heapq.heappop(self._heap)
                if seq <= self._last_seq:
                    # A gap-skipped descriptor arrived late: its other
                    # participants already timed out at its barrier, and
                    # entering it now would both stall this runner for the
                    # full barrier timeout and break the same-order
                    # invariant. Reject, never execute.
                    fut.set_exception(CollectiveUnavailable(
                        f"stale collective seq {seq} (already past "
                        f"{self._last_seq})", reason="stale-seq",
                    ))
                    continue
                self._last_seq = seq
            try:
                result = self.backend._enter(desc)
            except BaseException as e:
                if not fut.done():
                    fut.set_exception(e)
                continue
            if not fut.done():
                fut.set_result(result)
