"""Micro-batching of concurrent Count queries into one device program.

TPU-first serving design with no reference analog: the reference runs a
goroutine per query and each query's cost is dominated by its own bitmap
loops (executor.go:1558-1593), but on an accelerator a single fast-path
Count costs one host->device dispatch round trip, so N concurrent queries
serialize into N round trips. The coalescer holds each arriving query for
a sub-millisecond window, groups queries with identical call structure,
and executes each group as ONE batched program via
ShardedQueryEngine.count_batch — N queries, one dispatch.

WHEN batching helps is transport-dependent, so the coalescer is adaptive
(round-3 BENCH showed a 2.6x serving REGRESSION on a remote-runtime link):

- **Local device** (dispatch overhead ~100us of host work per call):
  batching N queries into one program divides the per-call overhead by N.
  This is the regime the window exists for.
- **Remote runtime** (axon tunnel: ~70ms RTT per blocking call, transfers
  serialize): N independent blocking clients already pipeline N RTTs, and
  funneling them through one collector serializes what was parallel. The
  coalescer measures the dispatch RTT once at startup (a trivial jitted
  op, timed after warmup) and BYPASSES the window when RTT exceeds
  `PILOSA_COALESCE_RTT_BYPASS` (default 10ms) — queries go straight to
  the engine, which still serves repeats from its result memo.
- **Idle traffic**: even on a local device, batching needs overlap. The
  collector tracks an arrival-interval EWMA and bypasses when the
  expected number of queries per dispatch (arrival_rate x dispatch cost)
  is below ~2 — a lone query should not pay the window.

`PILOSA_COALESCE_FORCE=1` pins batching on (tests, benchmarks).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Sequence, Tuple

import numpy as np


class QueryCoalescer:
    def __init__(self, engine, window: float = 0.001, max_batch: int = 256,
                 max_inflight: int = None, rtt_bypass: float = None,
                 force: bool = None):
        if max_inflight is None:
            max_inflight = int(os.environ.get("PILOSA_COALESCE_INFLIGHT", "4"))
        if rtt_bypass is None:
            rtt_bypass = float(
                os.environ.get("PILOSA_COALESCE_RTT_BYPASS", "0.010")
            )
        self.engine = engine
        self.window = window
        self.max_batch = max_batch
        self.rtt_bypass = rtt_bypass
        self.force = (
            force if force is not None
            else os.environ.get("PILOSA_COALESCE_FORCE") == "1"
        )
        self._cond = threading.Condition()
        self._pending: List[Tuple] = []
        self._closed = False
        self._thread: threading.Thread = None
        # Materialization (blocking on the device round trip) runs off the
        # collector thread; the semaphore caps outstanding round trips so
        # under saturation the collector blocks and the next batch grows
        # instead of fragmenting into extra serialized RTTs.
        self._inflight = threading.BoundedSemaphore(max_inflight)
        self._finishers = ThreadPoolExecutor(
            max_workers=max_inflight + 2, thread_name_prefix="coalescer-finish"
        )
        self.batches_executed = 0
        self.queries_batched = 0
        self.bypassed = 0
        # Dispatch RTT, measured lazily on first use (compiling the probe at
        # construction would stall server open on a remote runtime).
        self.rtt: float = None
        self._rtt_lock = threading.Lock()
        # Arrival-interval EWMA (seconds); seeded pessimistic-slow so a
        # burst must actually arrive before batching engages.
        self._ewma_dt = 1.0
        self._last_arrival = None

    # ------------------------------------------------------------- adaptive

    def _measure_rtt(self) -> float:
        """Median blocking round trip of a trivial device op (timed after
        compile+warmup). ~100us on a locally-attached backend, tens of ms
        through a remote-runtime tunnel."""
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x: x + 1)
        x = jnp.zeros(8, jnp.int32)
        np.asarray(fn(x))  # compile + warm
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(x))
            samples.append(time.perf_counter() - t0)
        return sorted(samples)[len(samples) // 2]

    def _dispatch_rtt(self) -> float:
        if self.rtt is None:
            with self._rtt_lock:
                if self.rtt is None:
                    try:
                        self.rtt = self._measure_rtt()
                    except Exception:
                        self.rtt = 0.0  # measurement failure: assume local
        return self.rtt

    def _note_arrival(self) -> None:
        now = time.monotonic()
        if self._last_arrival is not None:
            dt = now - self._last_arrival
            self._ewma_dt = 0.8 * self._ewma_dt + 0.2 * min(dt, 1.0)
        self._last_arrival = now

    def _should_batch(self) -> bool:
        if self.force:
            return True
        rtt = self._dispatch_rtt()
        if rtt > self.rtt_bypass:
            # Remote-runtime regime: blocking clients already pipeline
            # their own RTTs; the collector would serialize them.
            return False
        # Local regime: batch only when arrivals actually overlap a
        # dispatch (expected queries per dispatch >= 2). The dispatch cost
        # floor keeps the estimate sane when rtt measures ~0.
        dispatch = max(rtt, 200e-6)
        return dispatch / max(self._ewma_dt, 1e-9) >= 2.0

    # ---------------------------------------------------------------- API

    def count(self, index: str, call, shards: Sequence[int]) -> int:
        """Blocking count; batched with concurrent callers when the
        transport regime favors it, direct to the engine otherwise."""
        self._note_arrival()
        if not self._should_batch():
            with self._cond:
                if self._closed:
                    raise RuntimeError("coalescer closed")
            self.bypassed += 1
            return self.engine.count(index, call, shards)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer closed")
            self._pending.append((index, call, tuple(shards), fut))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="query-coalescer", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return fut.result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._finishers.shutdown(wait=True)

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                # Hold the window open for stragglers (bounded by max_batch).
                deadline = time.monotonic() + self.window
                while len(self._pending) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch, self._pending = self._pending, []
            try:
                self._execute(batch)
            except BaseException as e:  # worker must never die with futures pending
                for it in batch:
                    if not it[3].done():
                        it[3].set_exception(e)

    def _execute(self, batch: List[Tuple]) -> None:
        # Group by (index, call structure, shard set): count_batch requires
        # structural identity. Compilation happens once here and is passed
        # through to the engine (no second AST walk on the hot path).
        # Queries already answered by the engine's result memo complete
        # immediately without joining a device batch.
        groups: Dict[Tuple, List[Tuple]] = {}
        for item in batch:
            index, call, shards, fut = item
            try:
                comp_expr = self.engine._compile(index, call)
                hit, token = self.engine.memo_probe(index, comp_expr[0], shards)
                if hit is not None:
                    fut.set_result(hit)
                    continue
                key = (index, tuple(comp_expr[0].signature), shards)
            except Exception as e:
                fut.set_exception(e)
                continue
            groups.setdefault(key, []).append(item + (comp_expr, token))

        # Dispatch every group async (the device pipeline stays full), then
        # hand materialization to the finisher pool so the collector starts
        # gathering the next batch immediately — batches overlap the device
        # round trip instead of serializing on it.
        for (index, _sig, shards), items in groups.items():
            self._inflight.acquire()  # released by _finish
            try:
                if len(items) == 1:
                    _, call, _, fut, comp_expr, _token = items[0]
                    out = self.engine.count_async(
                        index, call, shards, comp_expr=comp_expr
                    )
                else:
                    calls = [it[1] for it in items]
                    comps = [it[4] for it in items]
                    out = self.engine.count_batch_async(
                        index, calls, list(shards), comps=comps
                    )
                    self.batches_executed += 1
                    self.queries_batched += len(items)
                self._finishers.submit(self._finish, items, out)
            except Exception as e:
                self._inflight.release()
                for it in items:
                    if not it[3].done():
                        it[3].set_exception(e)

    def _finish(self, items: List[Tuple], out) -> None:
        try:
            counts = np.asarray(out).reshape(-1)
            for it, n in zip(items, counts[: len(items)]):
                # Feed the result memo BEFORE resolving the future, with
                # the PROBE-TIME token so a write that landed mid-flight
                # invalidates rather than getting a stale count stamped
                # with its own generation. Store-then-resolve means a
                # caller that observes the result also observes the memo.
                self.engine.memo_store(it[5], int(n))
                it[3].set_result(int(n))
        except Exception as e:
            for it in items:
                if not it[3].done():
                    it[3].set_exception(e)
        finally:
            self._inflight.release()
