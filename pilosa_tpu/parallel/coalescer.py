"""Micro-batching of concurrent Count queries into one device program.

TPU-first serving design with no reference analog: the reference runs a
goroutine per query and each query's cost is dominated by its own bitmap
loops (executor.go:1558-1593), but on an accelerator a single fast-path
Count costs one host->device dispatch round trip, so N concurrent queries
serialize into N round trips. The coalescer holds each arriving query for
a sub-millisecond window, groups queries with identical call structure,
and executes each group as ONE batched program via
ShardedQueryEngine.count_batch — N queries, one dispatch.

Latency math: a query pays at most `window` extra wait; with dispatch RTT
>> window (tens of ms through a TPU runtime vs 1ms window) batching wins
whenever 2+ queries overlap, and a lone query pays only the window.

Batches are also capped at `max_inflight` outstanding device round trips:
result transfers serialize on the host<->device link, so once the link is
saturated, dispatching another small batch only adds a full RTT — blocking
the collector instead lets the next batch grow to the arrival rate times
the RTT (batch-to-the-bandwidth-delay-product), which is exactly the batch
size that keeps the link busy with the fewest round trips.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Sequence, Tuple

import numpy as np


class QueryCoalescer:
    def __init__(self, engine, window: float = 0.001, max_batch: int = 256,
                 max_inflight: int = None):
        if max_inflight is None:
            import os

            max_inflight = int(os.environ.get("PILOSA_COALESCE_INFLIGHT", "4"))
        self.engine = engine
        self.window = window
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._pending: List[Tuple] = []
        self._closed = False
        self._thread: threading.Thread = None
        # Materialization (blocking on the device round trip) runs off the
        # collector thread; the semaphore caps outstanding round trips so
        # under saturation the collector blocks and the next batch grows
        # instead of fragmenting into extra serialized RTTs.
        self._inflight = threading.BoundedSemaphore(max_inflight)
        self._finishers = ThreadPoolExecutor(
            max_workers=max_inflight + 2, thread_name_prefix="coalescer-finish"
        )
        self.batches_executed = 0
        self.queries_batched = 0

    # ---------------------------------------------------------------- API

    def count(self, index: str, call, shards: Sequence[int]) -> int:
        """Blocking count; internally batched with concurrent callers."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer closed")
            self._pending.append((index, call, tuple(shards), fut))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="query-coalescer", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return fut.result()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._finishers.shutdown(wait=True)

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                # Hold the window open for stragglers (bounded by max_batch).
                deadline = time.monotonic() + self.window
                while len(self._pending) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch, self._pending = self._pending, []
            try:
                self._execute(batch)
            except BaseException as e:  # worker must never die with futures pending
                for it in batch:
                    if not it[3].done():
                        it[3].set_exception(e)

    def _execute(self, batch: List[Tuple]) -> None:
        # Group by (index, call structure, shard set): count_batch requires
        # structural identity. Compilation happens once here and is passed
        # through to the engine (no second AST walk on the hot path).
        groups: Dict[Tuple, List[Tuple]] = {}
        for item in batch:
            index, call, shards, fut = item
            try:
                comp_expr = self.engine._compile(index, call)
                key = (index, tuple(comp_expr[0].signature), shards)
            except Exception as e:
                fut.set_exception(e)
                continue
            groups.setdefault(key, []).append(item + (comp_expr,))

        # Dispatch every group async (the device pipeline stays full), then
        # hand materialization to the finisher pool so the collector starts
        # gathering the next batch immediately — batches overlap the device
        # round trip instead of serializing on it.
        for (index, _sig, shards), items in groups.items():
            self._inflight.acquire()  # released by _finish
            try:
                if len(items) == 1:
                    _, call, _, fut, comp_expr = items[0]
                    out = self.engine.count_async(
                        index, call, shards, comp_expr=comp_expr
                    )
                else:
                    calls = [it[1] for it in items]
                    comps = [it[4] for it in items]
                    out = self.engine.count_batch_async(
                        index, calls, list(shards), comps=comps
                    )
                    self.batches_executed += 1
                    self.queries_batched += len(items)
                self._finishers.submit(self._finish, items, out)
            except Exception as e:
                self._inflight.release()
                for it in items:
                    if not it[3].done():
                        it[3].set_exception(e)

    def _finish(self, items: List[Tuple], out) -> None:
        try:
            counts = np.asarray(out).reshape(-1)
            for it, n in zip(items, counts[: len(items)]):
                it[3].set_result(int(n))
        except Exception as e:
            for it in items:
                if not it[3].done():
                    it[3].set_exception(e)
        finally:
            self._inflight.release()
