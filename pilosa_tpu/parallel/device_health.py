"""Device-plane fault state: error classification and the dispatch breakers.

The storage, network, and membership layers each got a fault story
(docs/durability.md, docs/fault-tolerance.md, docs/rebalance.md); this
module gives the TPU device plane one. An engine dispatch that raises —
HBM ``RESOURCE_EXHAUSTED``, an XLA compile rejection, a generic
``XlaRuntimeError``, a hang caught by the dispatch watchdog — is first
CLASSIFIED (oom / compile / runtime / timeout), then fed into two
breakers modeled on the per-peer circuit breaker in ``cluster/health.py``:

  per-signature     a query STRUCTURE whose fused device program keeps
                    failing (a pathological compile, a shape that trips a
                    runtime bug) is quarantined: the executor routes that
                    signature down to the per-shard XLA walk while every
                    other signature keeps the fused path. Re-admission is
                    a half-open probe after an exponential backoff.

  plane-wide        consecutive dispatch failures across signatures mean
                    the DEVICE is sick (dead tunnel, wedged runtime), not
                    one program: the whole engine demotes to host
                    execution (executor answers popcounts from host-tier
                    compressed bytes / live containers, no device work at
                    all) until a half-open probe dispatch succeeds.

``plan(sig)`` is the routing gate the executor consults before device
work: ``"device"`` (dispatch normally — possibly AS the half-open
probe), ``"shard"`` (signature quarantined: per-shard XLA path), or
``"host"`` (plane demoted: host execution ladder). The engine reports
every dispatch outcome through ``record_success``/``record_failure``,
which is what re-closes a probing breaker.

Stdlib-only on purpose (mirrors cluster/health.py): the executor's
routing decisions and the tests' breaker-lifecycle assertions need no
jax, and the clock is injectable for deterministic tests.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, Optional, Tuple

from ..errors import PilosaError

# Breaker states (shared vocabulary with cluster/health.py; the strings
# surface in /debug/vars `device_plane` and diagnostics).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# Classification kinds (counter suffixes, DeviceDispatchError.kind).
OOM = "oom"
COMPILE = "compile"
RUNTIME = "runtime"
TIMEOUT = "timeout"

# Bound on tracked signatures: a long-lived server seeing endless query
# shapes must not grow breaker state without bound; CLOSED entries are
# dropped oldest-first past this.
_MAX_SIGS = 1024


class DeviceDispatchError(PilosaError):
    """A device dispatch failed after classification (and, for OOM, after
    backpressure + one retry). Carries the classified kind so the
    executor's ladder can choose the right fallback rung; the original
    exception rides ``__cause__``."""

    def __init__(self, kind: str, sig=None, message: str = ""):
        super().__init__(
            message or f"device dispatch failed ({kind})")
        self.kind = kind
        self.sig = sig


class DeviceDispatchTimeout(PilosaError):
    """Raised by the engine's dispatch watchdog when a device call does
    not return within ``[engine] dispatch-watchdog`` seconds. The
    underlying dispatch thread cannot be killed — it parks a worker of
    the engine's dedicated dispatch pool until the runtime answers — so
    the watchdog's job is to free the SERVING thread and let the breaker
    stop sending work at a wedged device."""


_OOM_RE = re.compile(
    r"resource_exhausted|out of memory|out_of_memory|\boom\b"
    r"|while trying to allocate|failed to allocate")
_COMPILE_RE = re.compile(
    r"compil|invalid_argument|unimplemented|lowering|unsupported|mosaic")


def classify_device_error(e: BaseException) -> str:
    """Map a dispatch exception to oom / compile / timeout / runtime.

    Classification is by type first (watchdog timeouts carry their own
    type), then by message substring — jax surfaces XLA's status codes
    (``RESOURCE_EXHAUSTED``, ``INVALID_ARGUMENT``) in the text of
    ``XlaRuntimeError``, and the injected-fault failpoints deliberately
    use the same spellings so a fault test classifies exactly like the
    real error would."""
    if isinstance(e, DeviceDispatchTimeout) or isinstance(e, TimeoutError):
        return TIMEOUT
    try:
        from concurrent.futures import TimeoutError as _FutTimeout

        if isinstance(e, _FutTimeout):
            return TIMEOUT
    except ImportError:  # pragma: no cover - stdlib always has it
        pass
    text = f"{type(e).__name__}: {e}".lower()
    if _OOM_RE.search(text):
        return OOM
    if _COMPILE_RE.search(text):
        return COMPILE
    return RUNTIME


class _Breaker:
    __slots__ = ("state", "consec_failures", "opened_at", "backoff",
                 "probe_at", "open_count")

    def __init__(self):
        self.state = CLOSED
        self.consec_failures = 0
        self.opened_at = 0.0
        self.backoff = 0.0
        self.probe_at = 0.0
        self.open_count = 0


class DevicePlaneHealth:
    """Thread-safe device-plane breaker state for one engine.

    `config` is a ``cluster.health.ResilienceConfig`` (the device knobs
    live in the same ``[resilience]`` section as the peer breakers they
    are modeled on); `clock` is injectable for deterministic tests."""

    def __init__(self, config=None, clock: Optional[Callable[[], float]] = None):
        import time

        if config is None:
            from ..cluster.health import ResilienceConfig

            config = ResilienceConfig()
        self.config = config
        self.clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._plane = _Breaker()
        self._sigs: Dict[Tuple, _Breaker] = {}
        self.counters: Dict[str, int] = {
            "dispatch_failures": 0,
            "failures_oom": 0, "failures_compile": 0,
            "failures_runtime": 0, "failures_timeout": 0,
            "plane_opened": 0, "plane_closed": 0, "plane_probes": 0,
            "plane_short_circuits": 0,
            "sig_quarantined": 0, "sig_restored": 0, "sig_probes": 0,
            "sig_short_circuits": 0,
        }

    # ------------------------------------------------------------- routing

    def plan(self, sig: Optional[Tuple] = None) -> str:
        """Routing decision for one dispatch of structure `sig` (None =
        structure unknown; only the plane breaker applies).

        "device": dispatch normally. When a breaker's backoff has
        elapsed this call atomically claims the half-open probe — the
        dispatch it gates IS the probe, and the engine's
        record_success/record_failure resolves it. A claimed probe that
        never reports (the query was answered by a memo, the caller
        died) expires after `probe_ttl` and counts as failed, exactly
        like the peer breaker's lost probes.

        "shard": this signature is quarantined — run the per-shard XLA
        walk instead of the fused program.

        "host": the plane breaker is open — no device work at all;
        answer from host execution."""
        now = self.clock()
        with self._mu:
            s = self._sigs.get(sig) if sig is not None else None
            sig_base = self.config.device_sig_backoff
            if self._plane.state != CLOSED:
                if (s is not None and s.state != CLOSED
                        and not self._due_locked(s, now, sig_base)):
                    # A quarantined signature inside its OWN backoff must
                    # not serve as the plane's half-open probe: its
                    # program fails for its own reasons (bad compile,
                    # shape-specific bug), and letting it probe would
                    # re-open a healthy plane on every attempt. Once the
                    # SIG's backoff elapses it becomes a legitimate joint
                    # probe — without that, a workload whose every query
                    # shares the quarantined signature could never
                    # re-close the plane at all. (Side-effect-free check:
                    # the sig probe slot is only CLAIMED below, after the
                    # plane gate admits a dispatch — claiming first would
                    # orphan a sig probe every time the plane then
                    # short-circuits.)
                    self.counters["plane_short_circuits"] += 1
                    return "host"
                gate = self._gate_locked(
                    self._plane, now, "plane_probes", "plane_short_circuits",
                    self.config.device_breaker_backoff)
                if gate is False:
                    return "host"
                if s is not None and s.state != CLOSED:
                    # Joint probe: claim the sig slot too, so the one
                    # dispatch resolves both breakers.
                    self._gate_locked(s, now, "sig_probes",
                                      "sig_short_circuits", sig_base)
                return "device"
            if s is not None:
                gate = self._gate_locked(s, now, "sig_probes",
                                         "sig_short_circuits", sig_base)
                if gate is False:
                    return "shard"
        return "device"

    def _due_locked(self, b: _Breaker, now: float, base: float) -> bool:
        """Side-effect-free twin of _gate_locked: True when a probe COULD
        be claimed for this breaker right now (must hold _mu). `base` is
        the breaker's OWN configured backoff (plane vs sig)."""
        if b.state == OPEN:
            return now - b.opened_at >= b.backoff
        if b.state == HALF_OPEN:
            return now - b.probe_at >= base
        return True

    def _gate_locked(self, b: _Breaker, now: float, probes_key: str,
                     short_key: str, base: float) -> Optional[bool]:
        """Breaker gate for one dispatch (must hold _mu). None = CLOSED
        (dispatch, no probe semantics); True = dispatch AS the half-open
        probe; False = short-circuit to the degraded route. `base` is the
        breaker's OWN configured backoff — the plane and sig breakers
        each double from (and re-claim at) their own knob, so a large
        device-sig-backoff is honored rather than collapsing to the
        plane's scale.

        An unresolved HALF_OPEN probe re-claims after one base backoff
        interval instead of wedging until probe_ttl: unlike the peer
        breaker, a claimed device probe can legitimately dispatch NOTHING
        — the probing query may be answered by the result memo — so a
        quiet probe usually means 'no evidence', not 'lost caller'.
        probe_ttl still bounds the truly-lost case as a failure."""
        if b.state == CLOSED:
            return None
        if b.state == HALF_OPEN:
            if now - b.probe_at > self.config.probe_ttl:
                self._reopen(b, now, base)
            elif now - b.probe_at >= base:
                b.probe_at = now
                self.counters[probes_key] += 1
                return True
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False

    # ---------------------------------------------------------- accounting

    def record_success(self, sig: Optional[Tuple] = None) -> None:
        """A device dispatch completed: reset failure streaks and close
        any probing breaker (plane and, when known, signature)."""
        with self._mu:
            p = self._plane
            p.consec_failures = 0
            if p.state != CLOSED:
                p.state = CLOSED
                p.backoff = 0.0
                self.counters["plane_closed"] += 1
            if sig is not None:
                s = self._sigs.get(sig)
                if s is not None:
                    s.consec_failures = 0
                    if s.state != CLOSED:
                        s.state = CLOSED
                        s.backoff = 0.0
                        self.counters["sig_restored"] += 1

    def record_failure(self, sig: Optional[Tuple], kind: str) -> None:
        """A device dispatch failed with classified `kind`: advance both
        breakers. A failed half-open probe re-opens with doubled backoff;
        `device_sig_failures` consecutive failures quarantine the
        signature, `device_breaker_failures` consecutive failures (any
        signature) open the plane."""
        now = self.clock()
        cfg = self.config
        with self._mu:
            self.counters["dispatch_failures"] += 1
            key = f"failures_{kind}"
            self.counters[key] = self.counters.get(key, 0) + 1
            p = self._plane
            p.consec_failures += 1
            if p.state == HALF_OPEN:
                self._reopen(p, now, cfg.device_breaker_backoff)
            elif (p.state == CLOSED
                  and p.consec_failures >= cfg.device_breaker_failures):
                p.state = OPEN
                p.opened_at = now
                p.backoff = cfg.device_breaker_backoff
                p.open_count += 1
                self.counters["plane_opened"] += 1
            if sig is None:
                return
            s = self._sigs.get(sig)
            if s is None:
                s = self._sigs[sig] = _Breaker()
                self._trim_sigs_locked()
            s.consec_failures += 1
            if s.state == HALF_OPEN:
                self._reopen(s, now, cfg.device_sig_backoff)
            elif (s.state == CLOSED
                  and s.consec_failures >= cfg.device_sig_failures):
                s.state = OPEN
                s.opened_at = now
                s.backoff = cfg.device_sig_backoff
                s.open_count += 1
                self.counters["sig_quarantined"] += 1

    def _reopen(self, b: _Breaker, now: float, base: float) -> None:
        # Must hold _mu. Failed (or expired) half-open probe: back off
        # harder, same doubling discipline as the peer breaker. `base`
        # is the breaker's own knob; the cap never sits below it, so a
        # sig backoff configured above the plane cap can't SHRINK on the
        # first failed probe.
        b.state = OPEN
        b.opened_at = now
        b.backoff = min(
            max(b.backoff, base) * 2,
            max(self.config.device_breaker_backoff_max, base))
        b.open_count += 1

    def _trim_sigs_locked(self) -> None:
        if len(self._sigs) <= _MAX_SIGS:
            return
        for key in [k for k, b in self._sigs.items() if b.state == CLOSED]:
            del self._sigs[key]
            if len(self._sigs) <= _MAX_SIGS:
                return
        # Every entry is open (pathological): drop oldest regardless.
        while len(self._sigs) > _MAX_SIGS:
            self._sigs.pop(next(iter(self._sigs)))

    # ---------------------------------------------------------- inspection

    def plane_state(self) -> str:
        with self._mu:
            return self._plane.state

    def sig_state(self, sig: Tuple) -> str:
        with self._mu:
            s = self._sigs.get(sig)
            return s.state if s is not None else CLOSED

    def snapshot(self) -> dict:
        """Wholesale counter + breaker-state export for /debug/vars (the
        `device_plane` group) and diagnostics. Every key in
        self.counters is observable through here (pilint R4)."""
        with self._mu:
            # WHICH canonical shapes are quarantined, not just how many:
            # signatures are the canonical plan IR (docs/query-compiler.md),
            # so the repr is a readable op tree an operator can match to a
            # workload. Bounded — a pathological flood must not balloon a
            # stats scrape.
            # Bounded in BOTH dimensions (16 entries, 256 chars each),
            # with the repr work stopping AT the entry bound: a
            # pathological flood can hold _MAX_SIGS open breakers, and
            # building 1024 multi-KB IR reprs under the health lock
            # would block concurrent dispatch classification.
            quarantined = 0
            open_sigs = []
            for sig, b in self._sigs.items():
                if b.state == CLOSED:
                    continue
                quarantined += 1
                if len(open_sigs) < 16:
                    open_sigs.append(repr(sig)[:256])
            return {
                **dict(self.counters),
                "plane_state": self._plane.state,
                "plane_backoff": round(self._plane.backoff, 3),
                "plane_open_count": self._plane.open_count,
                "sigs_tracked": len(self._sigs),
                "sigs_open": quarantined,
                "open_signatures": open_sigs,
            }


# Collective failure kinds (counter suffixes; alongside OOM/COMPILE/...).
BARRIER_TIMEOUT = "barrier_timeout"
BROADCAST = "broadcast"


class CollectivePlaneHealth:
    """Breakers for the multi-host collective serving plane
    (parallel/collective.py, docs/multichip.md).

    Two levels, mirroring DevicePlaneHealth:

      per-mesh-slice    one breaker per jax process (= mesh slice). A
                        descriptor broadcast that can't reach a node, or
                        a barrier timeout while that node was a
                        participant, quarantines its slice: every query
                        whose placement spans it skips the collective
                        rung instantly (HTTP fan-out) instead of paying
                        a full barrier timeout per query.

      plane-wide        consecutive collective failures of any kind open
                        the whole plane — the leader stops entering
                        barriers at all until a half-open probe query
                        closes it again.

    The gate is consulted on the LEADER side only (``allow``): peers
    always enter descriptors they receive, so a probing leader's barrier
    finds every healthy peer waiting and one clean query re-closes the
    plane everywhere it opened. ``allow`` claims the half-open probe
    atomically, exactly like the peer/device breakers; the probing
    query's recorded outcome resolves it. Stdlib-only and clock-
    injectable like the rest of this module."""

    def __init__(self, config=None, clock: Optional[Callable[[], float]] = None):
        import time

        if config is None:
            from ..cluster.health import ResilienceConfig

            config = ResilienceConfig()
        self.config = config
        self.clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._plane = _Breaker()
        self._slices: Dict[int, _Breaker] = {}
        self.counters: Dict[str, int] = {
            "collective_failures": 0,
            "failures_barrier_timeout": 0, "failures_broadcast": 0,
            "failures_runtime": 0,
            "plane_opened": 0, "plane_closed": 0, "plane_probes": 0,
            "plane_short_circuits": 0,
            "slice_quarantined": 0, "slice_restored": 0,
            "slice_probes": 0, "slice_short_circuits": 0,
        }

    def allow(self, slices) -> bool:
        """Leader-side gate for one collective entry spanning `slices`
        (process indices). True = enter (possibly AS the half-open probe
        of the plane and/or any probing slice); False = skip the
        collective rung and fall back to the HTTP fan-out now, without
        waiting out a barrier.

        Two passes: a side-effect-free due check over EVERY breaker
        first, probe claims second — claiming the plane's probe and then
        short-circuiting on a still-backed-off slice would orphan the
        probe, which expires as a FAILURE and doubles the plane's
        backoff from short-circuits alone (the same hazard
        DevicePlaneHealth.plan avoids with _due_locked)."""
        now = self.clock()
        base = self.config.collective_breaker_backoff
        with self._mu:
            if not self._due_locked(self._plane, now, base):
                self.counters["plane_short_circuits"] += 1
                return False
            open_slices = []
            for p in slices:
                s = self._slices.get(int(p))
                if s is None or s.state == CLOSED:
                    continue
                if not self._due_locked(s, now, base):
                    self.counters["slice_short_circuits"] += 1
                    return False
                open_slices.append(s)
            gate = self._gate_locked(
                self._plane, now, "plane_probes", "plane_short_circuits",
                base)
            if gate is False:
                # Due-but-refused edge (a HALF_OPEN probe past probe_ttl
                # reopens inside the gate): nothing claimed yet, clean
                # short-circuit.
                return False
            for s in open_slices:
                self._gate_locked(s, now, "slice_probes",
                                  "slice_short_circuits", base)
        return True

    def _due_locked(self, b: _Breaker, now: float, base: float) -> bool:
        """Side-effect-free twin of _gate_locked: True when the breaker
        would admit this entry right now (must hold _mu)."""
        if b.state == OPEN:
            return now - b.opened_at >= b.backoff
        if b.state == HALF_OPEN:
            return now - b.probe_at >= base
        return True

    # _gate_locked / _reopen shared with DevicePlaneHealth by copy of
    # semantics, not inheritance: the two classes gate different things
    # (dispatches vs barrier entries) and coupling them through a base
    # class would make every breaker tweak a cross-plane change.
    def _gate_locked(self, b: _Breaker, now: float, probes_key: str,
                     short_key: str, base: float) -> Optional[bool]:
        if b.state == CLOSED:
            return None
        if b.state == HALF_OPEN:
            if now - b.probe_at > self.config.probe_ttl:
                self._reopen(b, now, base)
            elif now - b.probe_at >= base:
                b.probe_at = now
                self.counters[probes_key] += 1
                return True
        if b.state == OPEN and now - b.opened_at >= b.backoff:
            b.state = HALF_OPEN
            b.probe_at = now
            self.counters[probes_key] += 1
            return True
        self.counters[short_key] += 1
        return False

    def _reopen(self, b: _Breaker, now: float, base: float) -> None:
        b.state = OPEN
        b.opened_at = now
        b.backoff = min(
            max(b.backoff, base) * 2,
            max(self.config.collective_breaker_backoff_max, base))
        b.open_count += 1

    def record_success(self, slices=()) -> None:
        """A collective entry completed: close any probing breaker."""
        with self._mu:
            p = self._plane
            p.consec_failures = 0
            if p.state != CLOSED:
                p.state = CLOSED
                p.backoff = 0.0
                self.counters["plane_closed"] += 1
            for pidx in slices:
                s = self._slices.get(int(pidx))
                if s is None:
                    continue
                s.consec_failures = 0
                if s.state != CLOSED:
                    s.state = CLOSED
                    s.backoff = 0.0
                    self.counters["slice_restored"] += 1

    def record_failure(self, kind: str, slices=()) -> None:
        """A collective entry failed with classified `kind`
        (barrier_timeout / broadcast / runtime). `slices` names the
        processes the evidence points at — the broadcast target for a
        send failure, every participant for a barrier timeout (the
        barrier cannot attribute; the member monitor narrows it)."""
        now = self.clock()
        cfg = self.config
        with self._mu:
            self.counters["collective_failures"] += 1
            key = f"failures_{kind}"
            self.counters[key] = self.counters.get(key, 0) + 1
            p = self._plane
            p.consec_failures += 1
            if p.state == HALF_OPEN:
                self._reopen(p, now, cfg.collective_breaker_backoff)
            elif (p.state == CLOSED
                  and p.consec_failures >= cfg.collective_breaker_failures):
                p.state = OPEN
                p.opened_at = now
                p.backoff = cfg.collective_breaker_backoff
                p.open_count += 1
                self.counters["plane_opened"] += 1
            for pidx in slices:
                s = self._slices.get(int(pidx))
                if s is None:
                    s = self._slices[int(pidx)] = _Breaker()
                s.consec_failures += 1
                if s.state == HALF_OPEN:
                    self._reopen(s, now, cfg.collective_breaker_backoff)
                elif (s.state == CLOSED
                      and s.consec_failures
                      >= cfg.collective_breaker_failures):
                    s.state = OPEN
                    s.opened_at = now
                    s.backoff = cfg.collective_breaker_backoff
                    s.open_count += 1
                    self.counters["slice_quarantined"] += 1

    def plane_state(self) -> str:
        with self._mu:
            return self._plane.state

    def slice_state(self, pidx: int) -> str:
        with self._mu:
            s = self._slices.get(int(pidx))
            return s.state if s is not None else CLOSED

    def snapshot(self) -> dict:
        """Counter + breaker-state export (the `collective` group's
        `health` sub-dict in /debug/vars); every counter key is
        observable through here (pilint R4)."""
        with self._mu:
            return {
                **dict(self.counters),
                "plane_state": self._plane.state,
                "plane_backoff": round(self._plane.backoff, 3),
                "plane_open_count": self._plane.open_count,
                "slices": {
                    str(p): b.state for p, b in self._slices.items()
                    if b.state != CLOSED
                },
            }
