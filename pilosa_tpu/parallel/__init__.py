"""Sharded device query engine: mesh placement, compiled-program and
device-tensor caches, delta refresh, multi-host collectives."""

from __future__ import annotations

from dataclasses import dataclass


# The [engine] config section IS this dataclass (same pattern as
# [scheduler]/SchedulerConfig and [storage]/StorageConfig). It lives in the
# package __init__ — NOT engine.py — so config.py can import it without
# pulling jax into every CLI startup. Env vars (PILOSA_TPU_ENGINE_*, same
# spellings config.py maps for this section) override per-process.
@dataclass
class EngineConfig:
    """Device-cache refresh knobs for ShardedQueryEngine.

    delta_max_fraction: a stale resident plane/stack is refreshed by a
        small scattered update (indices+values host->HBM) only while the
        changed 32-bit words stay under this fraction of the tensor;
        past it the full regather path wins. 0 disables the delta path.
    delta_journal_ops: per-fragment dirty-word journal bound
        (core/fragment.py); overflow falls back to full regather.
    gather_workers: threads for the cold-path per-shard host container
        walks (0 = auto-size to the CPU count, 1 = serial).
    """

    delta_max_fraction: float = 0.25
    delta_journal_ops: int = 4096
    gather_workers: int = 0
    # Engine mesh width: 0 = all local devices (default). A positive N
    # restricts the per-node engine's mesh to the first N local devices.
    # The operational reason is the multi-device CPU backend: concurrent
    # sharded programs whose scalar reductions lower to cross-device
    # all-reduces can interleave their rendezvous and deadlock (a
    # jax-level hazard the micro-batcher only narrows), so CPU
    # deployments that want the COLLECTIVE plane on the full device set
    # pin the engine to mesh-devices=1 — per-node programs then carry no
    # collectives at all and only the (runner-serialized) collective
    # plane uses the full mesh. docs/multichip.md.
    mesh_devices: int = 0
    # Cache budgets (0 = auto). Auto means: the legacy env override
    # (PILOSA_LEAF_CACHE_BYTES / PILOSA_STACK_CACHE_BYTES /
    # PILOSA_MEMO_ENTRIES / PILOSA_AUX_MEMO_ENTRIES) if set, else the
    # [tier] hbm-bytes split (byte budgets only), else the platform
    # default. A nonzero config value loses only to the legacy env var —
    # env stays the per-process override, as before these were
    # configurable at all. Effective values surface in /debug/vars
    # (engine_budgets).
    leaf_cache_bytes: int = 0
    stack_cache_bytes: int = 0
    memo_entries: int = 0
    aux_memo_entries: int = 0
    # Device-fault handling (docs/fault-tolerance.md, device-plane
    # section). dispatch_watchdog: seconds a device dispatch may block
    # before the watchdog frees the serving thread and the failure is
    # classified `timeout` into the device breakers (0 disables; the
    # wedged dispatch itself cannot be killed — it parks a worker of the
    # engine's dedicated 4-slot dispatch pool until the runtime answers,
    # and once every slot is parked further dispatches run inline
    # unwatchdogged). cold_host_count: 1 answers a one-off Count whose
    # leaves are ALL demoted to the host tier directly from the
    # compressed bytes in one numpy pass — no decode + device_put for a
    # plane nobody re-reads (ROADMAP compressed-domain execution); the
    # SECOND touch of the same leaf set promotes normally so hot planes
    # still climb back into HBM. 0 disables.
    dispatch_watchdog: float = 0.0
    cold_host_count: int = 1
    # plan_cache: 1 caches each Call tree's canonical plan (signature +
    # leaf slots + lowered expression, plan/signature.py) on the Call
    # object, keyed by the index's write epoch — one lowering per query
    # instead of one per dispatch site / shard batch / TopN chunk. 0
    # recompiles every time (escape hatch).
    plan_cache: int = 1


# The [collective] config section (docs/multichip.md) — jax-free here for
# the same reason as EngineConfig: config.py/cli.py import it at startup.
@dataclass
class CollectiveConfig:
    """Multi-host collective serving plane knobs
    (parallel/collective.py).

    enabled: 0 turns the collective rung off entirely (every full-index
        query takes the HTTP fan-out) — the escape hatch.
    single_process: 1 lets a single-process job with a single-node
        cluster serve through the collective plane over its LOCAL device
        mesh (a one-pod deployment whose chips hold the whole index; the
        barrier degenerates to a no-op). Default 0: multi-node clusters
        must span a real jax.distributed job.
    timeout_ms: barrier timeout — how long a process waits for its peers
        before aborting a collective entry (PILOSA_COLLECTIVE_TIMEOUT_MS
        env keeps working as the per-process override).
    leaf_budget_bytes: resident sharded-stack budget per process; LRU
        past it, evicted planes demote through the tier manager
        (PILOSA_COLLECTIVE_LEAF_BYTES env override).
    delta_max_fraction: same contract as [engine] delta-max-fraction,
        for the collective plane's resident stacks: a stale resident
        global array refreshes by a per-device scattered update while
        the changed words stay under this fraction. 0 disables deltas
        (every staleness is a full re-assembly).
    """

    enabled: int = 1
    single_process: int = 0
    timeout_ms: int = 10000
    leaf_budget_bytes: int = 1 << 28
    delta_max_fraction: float = 0.25
