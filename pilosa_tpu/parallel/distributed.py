"""Multi-host collective execution: jax.distributed over ICI/DCN.

The reference scales across hosts with scatter-gather RPC over its private
protobuf plane (executor.go:1393-1440 mapReduce + NCCL/MPI in its training
stack). The TPU-native equivalent is a *global device mesh*: every host
process joins one `jax.distributed` job, the shard axis spans all hosts'
chips, each host feeds only the shard planes it owns
(`jax.make_array_from_process_local_data`), and a single jitted program
counts/reduces with XLA-inserted collectives that ride ICI within a host
and DCN between hosts — no Python in the reduce path.

SPMD discipline: every participating process must enter the same program
with the same shapes. The serving flow is therefore leader-driven: the
node that received the query broadcasts the (already compiled) query
descriptor over the cluster plane, every process calls `global_count`
together, and the all-reduced scalar materializes on every host — the
leader answers the client, the others discard it. `CollectiveWorker`
implements the non-leader side as a long-poll loop.

Single-process use (tests, one-host clusters) works unchanged: initialize()
is a no-op when num_processes == 1 and the global mesh degenerates to the
local one.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

SHARD_AXIS = "shards"


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join (or skip joining) a multi-host jax.distributed job.

    Args fall back to PILOSA_JAX_COORDINATOR / PILOSA_JAX_NUM_PROCESSES /
    PILOSA_JAX_PROCESS_ID so deployments can configure pods by env alone.
    Returns True when a multi-process runtime was initialized."""
    coordinator_address = coordinator_address or os.environ.get(
        "PILOSA_JAX_COORDINATOR"
    )
    if num_processes is None:
        num_processes = int(os.environ.get("PILOSA_JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PILOSA_JAX_PROCESS_ID", "0"))
    if not coordinator_address or num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(limit: Optional[int] = None):
    """1-D mesh over every device in the job — all hosts' chips after
    initialize(), just the local ones otherwise. XLA partitions programs
    over it and inserts ICI collectives within a host, DCN across hosts.

    `limit` restricts the mesh to the first N devices — single-process
    only (the MULTICHIP bench's per-device-count scaling curve); a
    multi-process subset would break the process-contiguous slot layout
    the collective plane verifies."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if limit:
        devs = devs[: int(limit)]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def process_shard_slots(n_shards: int) -> tuple:
    """(global_padded, lo, hi): this process's contiguous slot range after
    padding the shard axis to a multiple of the global device count.
    Placement is block-contiguous, matching NamedSharding's default layout
    over the leading axis, so slot -> owning process is pure arithmetic —
    the same determinism jump-hash gives the HTTP cluster plane."""
    import jax

    n_dev = jax.device_count()
    per_proc = jax.local_device_count()
    padded = n_shards if n_shards % n_dev == 0 else ((n_shards // n_dev) + 1) * n_dev
    per_slot = padded // n_dev
    lo = jax.process_index() * per_proc * per_slot
    hi = lo + per_proc * per_slot
    return padded, lo, hi


def make_global_planes(local_planes: np.ndarray, n_shards_padded: int,
                       mesh=None):
    """Assemble a (S_global, W) device array sharded over the global mesh
    from this host's local block of shard planes. `local_planes` must be
    exactly this process's slot range (process_shard_slots)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else global_mesh()
    sharding = NamedSharding(mesh, P(SHARD_AXIS, None))
    global_shape = (n_shards_padded, local_planes.shape[-1])
    return jax.make_array_from_process_local_data(
        sharding, local_planes, global_shape
    )


def _split_sum(pc):
    """Overflow-safe scalar reduction without x64: per-shard partial sums
    (each ≤ 2^25 for a 2^20-column plane) are split into low/high 15-bit
    halves and all-reduced as two int32 scalars — exact up to 2^15 × S
    per half, i.e. ~64k shards / 2^41 bits, where a single int32 sum
    would wrap at 2^31 (jnp.int64 silently canonicalizes to int32 unless
    jax_enable_x64, which we don't force process-wide)."""
    import jax.numpy as jnp

    per = jnp.sum(pc.astype(jnp.int32), axis=tuple(range(1, pc.ndim)))
    lo = jnp.sum(per & 0x7FFF)
    hi = jnp.sum(per >> 15)
    return lo, hi


def global_count(planes) -> int:
    """Popcount-sum over a globally sharded (S, W) uint32 plane array.

    One jitted program per shape (cached by jax): per-device partial
    popcounts then an all-reduce that XLA lowers to ICI/DCN collectives.
    Every process gets the full scalar — fully-replicated output is the
    SPMD analog of the reference's coordinator-side merge loop."""
    import jax

    @jax.jit
    def fn(p):
        return _split_sum(jax.lax.population_count(p))

    lo, hi = fn(planes)
    return (int(hi) << 15) + int(lo)


def global_and_count(planes_a, planes_b) -> int:
    """Count(Intersect) across the global mesh: elementwise AND stays
    device-local (same sharding both sides — zero communication), only the
    scalar reduction crosses hosts."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(a, b):
        return _split_sum(jax.lax.population_count(jnp.bitwise_and(a, b)))

    lo, hi = fn(planes_a, planes_b)
    return (int(hi) << 15) + int(lo)


# NOTE: the round-3 CollectiveWorker lived here. It assumed block-contiguous
# slot->process placement, which contradicts the cluster's jump-hash
# placement and silently counted unowned slots as zeros. The production
# collective plane is parallel/collective.py (placement follows jump-hash,
# workers verify ownership, entry is barrier-guarded and seq-ordered). The
# low-level helpers above remain for hand-assembled plane blocks (tests,
# benchmarks).
