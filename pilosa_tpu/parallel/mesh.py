"""Device mesh + shard placement for data-parallel query execution.

The reference's parallelism is data parallelism over 2^20-column shards
(SURVEY.md §2: executor.go:1464-1593 goroutine-per-shard + scatter-gather
RPC). The TPU-native equivalent: shards are laid out along a 1-D 'shards'
mesh axis; per-shard bitplane kernels run on every device in SPMD and
scalar reductions (Count/Sum/TopN candidate counts) ride ICI collectives
inserted by XLA (or explicit psum under shard_map).

Pipeline/tensor/sequence/expert parallelism have no analog in a bitmap
index (SURVEY.md §2 records their absence in the reference); the mesh is
deliberately 1-D.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shards"


def default_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over this process's LOCAL devices.

    Local, not global: the per-node engine's programs are entered by this
    process alone (per-shard fan-out hands each node its own shards), and
    a program sharded over other processes' devices would block inside the
    runtime waiting for peers that never enter it. The multi-host global
    mesh belongs exclusively to the collective plane, where every process
    enters together (parallel/collective.py)."""
    devices = list(devices if devices is not None else jax.local_devices())
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_sharding(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    """NamedSharding splitting dimension `axis` over the shard mesh axis."""
    spec = [None] * ndim
    spec[axis] = SHARD_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_shards(n_shards: int, n_devices: int) -> int:
    """Number of shard slots after padding to a device multiple."""
    if n_shards % n_devices == 0:
        return n_shards
    return ((n_shards // n_devices) + 1) * n_devices


def device_for_shard(shard_index: int, n_shards_padded: int, n_devices: int) -> int:
    """Block placement: contiguous runs of shards per device (matches the
    default NamedSharding block layout over the leading axis)."""
    per = n_shards_padded // n_devices
    return shard_index // per
