"""Canonical query-plan compiler: PQL call tree -> canonical IR + signature.

The device engine compiles one jitted program per query *structure* and
keys every downstream system on that structure's signature: the compiled-
program cache, the result memo, the micro-batcher's coalescing groups,
and the per-signature device breaker (docs/fault-tolerance.md). Before
this module the signature was the raw AST walk order, so two trees that
differ only by commutative operand order — `Intersect(Union(a,b), c)` vs
`Intersect(c, Union(a,b))` — compiled two XLA programs, held two memo
spaces, and could never coalesce into one fused launch.

This module lowers a call tree into a CANONICAL intermediate form:

  - commutative operands (Intersect / Union / Xor) sort into a canonical
    order, so operand shuffles of one expression share one signature;
  - associative chains flatten into k-ary nodes (`Intersect(Intersect(a,
    b), c)` -> `Intersect(a, b, c)`), so the lowered program reduces all
    k operands in ONE pass instead of a pairwise tree (the k-ary
    set-intersection idea of arXiv:1103.2409 applied at plan level);
  - `Difference` normalizes to (head, sorted tail): `a \\ b \\ c` and
    `Difference(a, Union(b, c))` both lower to `head AND NOT(OR(tail))`
    — one complement instead of one per operand;
  - leaf planes dedupe into slots assigned in canonical traversal order,
    so structurally equal trees also share leaf-binding order (and
    therefore the engine's result-memo keys).

The SIGNATURE is the slotted canonical IR itself — a nested tuple of op
kinds, arities, slot ids, and baked predicates (BSI base values, time-
range view sets). It is injective over canonical programs: two
semantically different lowered programs always differ in some node of
the tuple, so they can never collide on a signature; two trees equal up
to commutativity/associativity always canonicalize to the same tuple.
Concrete row ids are DATA (leaf bindings), not structure — they appear
in the leaves list, never in the signature — which is exactly what lets
the batched device program serve any same-shape query with index
vectors as inputs (parallel/engine.py `_count_batch_setops`).

Plans are cached on the Call object itself (`cached_plan`), validated by
the index's write epoch: the executor touches a query's tree once per
dispatch site (support gate, batcher enqueue, host ladder, per-chunk
TopN src compiles), and before this cache each touch re-walked the AST.

jax-free on purpose (pilint R2): lowering to jnp closures happens in
parallel/engine.py from the IR this module emits.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

from .. import failpoints
from ..constants import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from ..errors import BSIGroupNotFoundError, FieldNotFoundError, QueryError
from ..obs import span as obs_span
from ..pql.ast import BETWEEN, Call, GT, GTE, LT, LTE, NEQ


class Leaf(NamedTuple):
    """A fragment row that must be materialized on device. NamedTuple,
    not frozen dataclass: Leaf construction/hash/eq run per call on the
    batch-serving hot path (slot dicts, cache keys)."""

    field: str
    view: str
    row: int


# IR node kinds (first element of every IR tuple). The commutative ops
# keep their PQL names so signatures stay readable in traces and breaker
# snapshots; the BSI/time kinds are plan-internal.
NARY_OPS = ("Intersect", "Union", "Xor")
SETOP_KINDS = frozenset(("leaf",) + NARY_OPS + ("Difference",))


class PlanStats:
    """Module-wide plan-compiler counters, surfaced as the `plan` group
    of /debug/vars (pilint R4: observable wholesale via snapshot())."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {
            # Canonical lowerings actually performed vs answered from the
            # on-Call cache. cache_hits >> builds on the serving path is
            # the satellite fix working (one build per query, not one per
            # dispatch site / shard batch / TopN chunk).
            "plan_builds": 0, "plan_cache_hits": 0,
            # Canonicalization effect: nodes whose operands were
            # reordered into canonical order, and nested same-op /
            # Difference-tail nodes merged into a k-ary parent. Nonzero
            # reorders on a workload prove shuffled spellings are
            # landing on shared programs.
            "plan_reorders": 0, "plan_flattens": 0,
        }

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.counters)


STATS = PlanStats()


def snapshot() -> dict:
    """The `plan` counter group (handler /debug/vars, diagnostics)."""
    return STATS.snapshot()


class CompiledPlan:
    """One canonical lowering of a call tree for one index.

    signature: single-entry list holding the slotted canonical IR tuple
        (list for compatibility with the historical `comp.signature`
        surface — every consumer does `tuple(comp.signature)`).
    leaves: canonical-order Leaf list; slot i in the IR is leaves[i].
    ir: slotted canonical IR (nested tuples; see module docstring).
    setops_only: True when every node is a set-op over standard-view
        leaves — the shapes the batched gather program can serve.
    expr: lowered jnp closure cache slot, owned by parallel/engine.py
        (None until the engine first lowers this plan; benign race).
    """

    __slots__ = ("index", "ir", "leaves", "signature", "sig_tuple",
                 "setops_only", "expr")

    def __init__(self, index: str, ir: tuple, leaves: List[Leaf],
                 setops_only: bool):
        self.index = index
        self.ir = ir
        self.leaves = leaves
        self.signature = [ir]
        self.sig_tuple = (ir,)
        self.setops_only = setops_only
        self.expr = None


class _Builder:
    """AST -> concrete canonical IR -> slotted IR + leaf slots."""

    def __init__(self, holder, index: str, field_cache: Optional[Dict]):
        self.holder = holder
        self.index = index
        self._field_cache = field_cache
        self.reorders = 0
        self.flattens = 0

    # -------------------------------------------------- concrete IR
    #
    # Concrete nodes carry leaf identities (field, view, row) so the
    # canonical sort is a pure function of the subtree INCLUDING its
    # data bindings: ties between equal-structure siblings break on row
    # ids, making the leaf-binding order deterministic too (shared
    # memo/stack keys for shuffled spellings of one query).

    def _field_exists(self, field_name: str) -> bool:
        fc = self._field_cache
        if fc is not None:
            ok = fc.get(field_name)
            if ok is None:
                ok = self.holder.field(self.index, field_name) is not None
                fc[field_name] = ok
            return ok
        return self.holder.field(self.index, field_name) is not None

    def concrete(self, c: Call) -> tuple:
        if c.name == "Row":
            field_name = c.field_arg()
            if not self._field_exists(field_name):
                raise FieldNotFoundError(field_name)
            row_id, ok = c.uint_arg(field_name)
            if not ok:
                raise QueryError("Row() must specify row")
            return ("leaf", field_name, VIEW_STANDARD, row_id)
        if c.name in NARY_OPS:
            if not c.children:
                raise QueryError(
                    f"empty {c.name} query is currently not supported")
            kids: List[tuple] = []
            for ch in c.children:
                sub = self.concrete(ch)
                if sub[0] == c.name:
                    # Associative chain: merge the child's operands into
                    # this node (k-ary flattening).
                    kids.extend(sub[1])
                    self.flattens += 1
                else:
                    kids.append(sub)
            ordered = sorted(kids, key=repr)
            if ordered != kids:
                self.reorders += 1
            return (c.name, tuple(ordered))
        if c.name == "Difference":
            if not c.children:
                raise QueryError(
                    "empty Difference query is currently not supported")
            head = self.concrete(c.children[0])
            tail: List[tuple] = []

            def absorb(node: tuple) -> None:
                # A Union in subtracting position is the same program as
                # its flattened operands: a \\ (b U c) == a \\ b \\ c.
                if node[0] == "Union":
                    tail.extend(node[1])
                    self.flattens += 1
                else:
                    tail.append(node)

            if head[0] == "Difference":
                # (a \\ b...) \\ c... == a \\ b... \\ c...
                inner_head, inner_tail = head[1], head[2]
                tail.extend(inner_tail)
                head = inner_head
                self.flattens += 1
            for ch in c.children[1:]:
                absorb(self.concrete(ch))
            ordered = sorted(tail, key=repr)
            if ordered != tail:
                self.reorders += 1
            return ("Difference", head, tuple(ordered))
        if c.name == "Range" and c.has_condition_arg():
            return self._concrete_bsi(c)
        if c.name == "Range":
            return self._concrete_time_range(c)
        raise QueryError(f"not fast-path compilable: {c.name}")

    def _concrete_time_range(self, c: Call) -> tuple:
        field_name, row_id, views = resolve_time_range(
            self.holder, self.index, c)
        if not views:
            raise QueryError("Range() covers no populated views")
        if len(views) > 256:
            raise QueryError("Range() spans too many views for the fast path")
        return ("timerange", field_name, tuple(views), row_id)

    def _concrete_bsi(self, c: Call) -> tuple:
        (field_name, cond), = c.args.items()
        fld = self.holder.field(self.index, field_name)
        if fld is None:
            raise FieldNotFoundError(field_name)
        bsig = fld.bsi_group(field_name)
        if bsig is None:
            raise BSIGroupNotFoundError(field_name)
        depth = bsig.bit_depth()
        view = VIEW_BSI_GROUP_PREFIX + field_name

        if cond.op == NEQ and cond.value is None:
            return ("notnull", field_name, view, depth)

        if cond.op == BETWEEN:
            predicates = cond.int_slice_value()
            if len(predicates) != 2:
                raise QueryError(
                    "Range(): BETWEEN condition requires exactly two "
                    "integer values")
            lo, hi, out_of_range = bsig.base_value_between(*predicates)
            if out_of_range:
                return ("zero", field_name, view, depth)
            if predicates[0] <= bsig.min and predicates[1] >= bsig.max:
                return ("notnull", field_name, view, depth)
            return ("between", field_name, view, depth, lo, hi)

        value = cond.value
        if not isinstance(value, int) or isinstance(value, bool):
            raise QueryError("Range(): conditions only support integer values")
        base, out_of_range = bsig.base_value(cond.op, value)
        if out_of_range and cond.op != NEQ:
            return ("zero", field_name, view, depth)
        if (
            (cond.op == LT and value > bsig.max)
            or (cond.op == LTE and value >= bsig.max)
            or (cond.op == GT and value < bsig.min)
            or (cond.op == GTE and value <= bsig.min)
            or (out_of_range and cond.op == NEQ)
        ):
            return ("notnull", field_name, view, depth)
        return ("cmp", cond.op, field_name, view, depth, base)

    # --------------------------------------------------- slotted IR

    def slotted(self, node: tuple, leaves: List[Leaf],
                slots: Dict[Leaf, int]) -> tuple:
        def slot(leaf: Leaf) -> int:
            i = slots.get(leaf)
            if i is None:
                i = len(leaves)
                leaves.append(leaf)
                slots[leaf] = i
            return i

        kind = node[0]
        if kind == "leaf":
            return ("leaf", slot(Leaf(node[1], node[2], node[3])))
        if kind in NARY_OPS:
            return (kind, tuple(
                self.slotted(ch, leaves, slots) for ch in node[1]))
        if kind == "Difference":
            return ("Difference",
                    self.slotted(node[1], leaves, slots),
                    tuple(self.slotted(ch, leaves, slots)
                          for ch in node[2]))
        if kind == "timerange":
            _, field, views, row = node
            return ("timerange", tuple(
                slot(Leaf(field, v, row)) for v in views))
        # BSI kinds register every bit plane (rows 0..depth) like the
        # historical compiler did, keeping memo/fingerprint coverage —
        # and therefore staleness semantics — identical even for the
        # constant-folded zero/notnull programs.
        if kind == "cmp":
            _, op, field, view, depth, base = node
            idxs = tuple(slot(Leaf(field, view, i)) for i in range(depth + 1))
            return ("cmp", op, idxs, depth, base)
        if kind == "between":
            _, field, view, depth, lo, hi = node
            idxs = tuple(slot(Leaf(field, view, i)) for i in range(depth + 1))
            return ("between", idxs, depth, lo, hi)
        # zero / notnull
        _, field, view, depth = node
        idxs = tuple(slot(Leaf(field, view, i)) for i in range(depth + 1))
        if kind == "zero":
            return ("zero", idxs[0])
        return ("notnull", idxs[depth])


def _setops_only(ir: tuple) -> bool:
    kind = ir[0]
    if kind not in SETOP_KINDS:
        return False
    if kind == "leaf":
        return True
    if kind == "Difference":
        return _setops_only(ir[1]) and all(_setops_only(ch) for ch in ir[2])
    return all(_setops_only(ch) for ch in ir[1])


def resolve_time_range(holder, index: str, c: Call):
    """(field_name, row_id, present views) for a time-quantum Range call
    — THE one implementation of the argument parsing and present-view
    pruning, shared by the canonical lowering and the host evaluator.
    The degraded host answer must match the compiled program bit for
    bit, so the view set they union over cannot be allowed to diverge."""
    from ..timeq import parse_timestamp, views_by_time_range

    field_name = c.field_arg()
    fld = holder.field(index, field_name)
    if fld is None:
        raise FieldNotFoundError(field_name)
    row_id, ok = c.uint_arg(field_name)
    if not ok:
        raise QueryError("Range() must specify row")
    start = c.args.get("_start")
    end = c.args.get("_end")
    if not isinstance(start, str) or not isinstance(end, str):
        raise QueryError("Range() start/end time required")
    q = fld.time_quantum()
    if not q:
        raise QueryError("Range() field has no time quantum")
    views = views_by_time_range(
        VIEW_STANDARD, parse_timestamp(start), parse_timestamp(end), q
    )
    # Prune to views that exist in the field: an hour-quantum range
    # over years enumerates tens of thousands of view names, and a
    # leaf per ABSENT view would materialize a zero plane per shard
    # (the per-shard fallback just skips missing fragments). Present
    # views bound the work to actual data.
    return field_name, row_id, [v for v in views if fld.view(v) is not None]


def build_plan(holder, index: str, call: Call,
               field_cache: Optional[Dict] = None) -> CompiledPlan:
    """Lower `call` into its canonical plan for `index`. Raises QueryError
    (or a schema error) when the tree is not fast-path compilable — the
    engine's support gate turns that into the per-shard fallback."""
    failpoints.fire("plan-lower")
    with obs_span("plan.compile"):
        b = _Builder(holder, index, field_cache)
        concrete = b.concrete(call)
        leaves: List[Leaf] = []
        slots: Dict[Leaf, int] = {}
        ir = b.slotted(concrete, leaves, slots)
        plan = CompiledPlan(index, ir, leaves, _setops_only(ir))
    STATS.inc("plan_builds")
    if b.reorders:
        STATS.inc("plan_reorders", b.reorders)
    if b.flattens:
        STATS.inc("plan_flattens", b.flattens)
    return plan


def _epoch_token(holder, index: str) -> Optional[Tuple]:
    idx = holder.index(index)
    if idx is None:
        return None
    ep = idx.write_epoch
    return (index, ep.incarnation, ep.value)


def cached_plan(holder, index: str, call: Call,
                field_cache: Optional[Dict] = None,
                enabled: bool = True) -> CompiledPlan:
    """build_plan with a single-slot cache on the Call object, valid
    while the index's write epoch stands still. The executor touches one
    query's tree at several dispatch sites (support gate, micro-batcher
    enqueue, host-ladder compile, per-chunk TopN src compile) and used
    to re-walk the AST at each; within one query execution these are all
    cache hits now. The epoch token keys the entry: a write anywhere in
    the index (which can create time views or stretch a BSI range, both
    of which change the lowering) invalidates it — conservative but
    O(1), matching the engine memo's epoch fast path."""
    if enabled:
        token = _epoch_token(holder, index)
        cached = getattr(call, "_plan_cache", None)
        if (cached is not None and token is not None
                and cached[0] == token):
            STATS.inc("plan_cache_hits")
            return cached[1]
    plan = build_plan(holder, index, call, field_cache=field_cache)
    if enabled and token is not None:
        # Benign publication race: concurrent builders of the same Call
        # produce equivalent plans; last write wins.
        call._plan_cache = (token, plan)
    return plan
