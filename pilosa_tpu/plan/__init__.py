"""Query-plan compiler (docs/query-compiler.md).

Canonical lowering of PQL call trees: commutative-operand sorting, k-ary
flattening of associative chains, leaf-slot assignment, and the
injective structure signature that keys the engine's compiled-program
cache, the result memo, the micro-batcher's coalescing groups, and the
per-signature device breaker. jax-free (pilint R2): the jnp lowering of
the emitted IR lives in parallel/engine.py.
"""

from .signature import (  # noqa: F401
    CompiledPlan,
    Leaf,
    NARY_OPS,
    PlanStats,
    SETOP_KINDS,
    STATS,
    build_plan,
    cached_plan,
    resolve_time_range,
    snapshot,
)
