"""(row, column) iterators (port of /root/reference/iterator.go).

Iterate set bits of a fragment in (rowID, columnID) order, with seek
support — used by export, block-data extraction and tests.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from .constants import SHARD_WIDTH


class BufIterator:
    """Peek/unread wrapper (reference bufIterator)."""

    def __init__(self, it: Iterator[Tuple[int, int]]):
        self._it = iter(it)
        self._buf: Optional[Tuple[int, int]] = None
        self._eof = False

    def next(self) -> Optional[Tuple[int, int]]:
        if self._buf is not None:
            v, self._buf = self._buf, None
            return v
        try:
            return next(self._it)
        except StopIteration:
            self._eof = True
            return None

    def peek(self) -> Optional[Tuple[int, int]]:
        if self._buf is None:
            self._buf = self.next()
        return self._buf

    def unread(self, value: Tuple[int, int]) -> None:
        assert self._buf is None
        self._buf = value


def fragment_iterator(fragment, seek_row: int = 0) -> Iterator[Tuple[int, int]]:
    """Yield (rowID, absolute columnID) pairs in ascending order."""
    base = fragment.shard * SHARD_WIDTH
    vals = fragment.storage.slice()
    start = np.searchsorted(vals, np.uint64(seek_row * SHARD_WIDTH))
    for pos in vals[start:]:
        pos = int(pos)
        yield pos // SHARD_WIDTH, base + pos % SHARD_WIDTH


def slice_iterator(row_ids, column_ids) -> Iterator[Tuple[int, int]]:
    """Iterator over parallel (rowIDs, columnIDs) arrays (reference
    sliceIterator), sorted by (row, col)."""
    pairs = sorted(zip((int(r) for r in row_ids), (int(c) for c in column_ids)))
    return iter(pairs)


def limit_iterator(it, max_row: int, max_col: int) -> Iterator[Tuple[int, int]]:
    """Stop before (max_row, *) or columns >= max_col (reference limitIterator)."""
    for row, col in it:
        if row >= max_row:
            return
        if col % SHARD_WIDTH >= max_col:
            continue
        yield row, col
