"""Anti-entropy: merkle-block sync of replicated fragments.

Port of the reference's holderSyncer/fragmentSyncer (holder.go:566-774,
fragment.go:1716-1904): walk every locally-owned fragment, compare
HASH_BLOCK_SIZE-row block checksums across replicas, pull differing blocks,
majority-vote merge locally, and push Set/Clear diffs back to replicas as
PQL. Attribute stores sync first via block-checksum diff (attr.go:80-120).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..constants import SHARD_WIDTH, VIEW_STANDARD
from ..errors import PilosaError


class HolderSyncer:
    def __init__(self, server):
        self.server = server
        self.holder = server.holder
        self.cluster = server.cluster
        self.client = server.client
        # Hinted-handoff store (cluster/hints.py): shards with pending or
        # expired hints sync FIRST — they are the ones KNOWN to be
        # divergent — instead of waiting their turn in the full-holder
        # walk. None for library holders without a server-owned store.
        self.hints = getattr(server, "hints", None)
        # Per-sweep pacing ([anti-entropy] pace): seconds slept between
        # fragment syncs so one sweep can't saturate replicas with
        # back-to-back block RPCs.
        self.pace = getattr(server, "anti_entropy_pace", 0.0)

    def _remote_replicas(self, index: str, shard: int):
        nodes = self.cluster.shard_nodes(index, shard)
        me = self.cluster.node.id
        if not any(n.id == me for n in nodes):
            return None  # not owned here
        # Shared peer-health state (cluster/health.py): a replica whose
        # breaker is open gets skipped for this sweep instead of costing
        # one connect timeout per fragment; the next sweep retries after
        # the breaker readmits it.
        health = self.cluster.health
        return [n for n in nodes if n.id != me and not health.is_down(n.id)]

    def sync_holder(self) -> None:
        import time as _t

        # Collect the whole fragment worklist first so hint-flagged
        # shards (pending, expired, or overflowed hints — the shards
        # KNOWN to be divergent) can be ordered to the FRONT of the
        # sweep; everything else keeps its stable walk order behind them.
        work = []
        for index_name in self.holder.index_names():
            idx = self.holder.index(index_name)
            self._sync_attrs(index_name, None, idx.column_attr_store)
            for field_name in idx.field_names():
                fld = idx.field(field_name)
                self._sync_attrs(index_name, field_name, fld.row_attr_store)
                for view_name in fld.view_names():
                    view = fld.view(view_name)
                    for shard in view.available_shards():
                        work.append((index_name, field_name, view_name,
                                     shard))
        priority = (self.hints.priority_shards()
                    if self.hints is not None else set())
        if priority:
            work.sort(key=lambda w: (w[0], w[3]) not in priority)
        first = True
        unrepaired = set()
        for index_name, field_name, view_name, shard in work:
            if not first and self.pace > 0:
                # Per-sweep pacing: spread the block-RPC load out.
                _t.sleep(self.pace)
            first = False
            replicas = self._remote_replicas(index_name, shard)
            if not replicas:
                if replicas is not None:
                    # Owned here but every remote replica is DOWN:
                    # nothing was repaired. A hint-flagged shard must
                    # keep its flag, or the outage that created the
                    # divergence would also erase its priority ordering.
                    # (None = not owned here: the owners' sweeps are the
                    # repair path, so those flags still settle below.)
                    unrepaired.add((index_name, shard))
                continue
            try:
                self._sync_fragment(
                    index_name, field_name, view_name, shard, replicas
                )
            except (PilosaError, OSError) as e:
                # One fragment's failure (peer down mid-sync, an
                # oversized diff rejected, a local disk fault
                # while persisting a merge) must not abort the
                # rest of the sweep.
                self.server.logger.error(
                    "anti-entropy: %s/%s/%s/%s sync failed: %s",
                    index_name, field_name, view_name, shard, e,
                )
                unrepaired.add((index_name, shard))
        if self.hints is not None:
            # A completed sweep settles every hint-priority flag whose
            # shard was actually repaired (pending per-peer hint records
            # stay — replay is idempotent and cheaper than dropping them
            # mid-log); flags for shards that failed mid-sync or had no
            # reachable replica survive to keep their ordering. Flags for
            # shards this node doesn't even hold are settled: their
            # owners' sweeps are the repair path, and keeping dead flags
            # would pin the priority set forever.
            for key in priority:
                if key not in unrepaired:
                    self.hints.note_synced(*key)

    # ---------------------------------------------------------------- attrs

    def _sync_attrs(self, index: str, field, store) -> None:
        health = self.cluster.health
        replicas = [
            n for n in self.cluster.nodes
            if n.id != self.cluster.node.id and not health.is_down(n.id)
        ]
        if not replicas:
            return
        blocks = [{"id": bid, "checksum": chk.hex()} for bid, chk in store.blocks()]
        for node in replicas:
            try:
                remote_attrs = self.client.attr_diff(node, index, field, blocks)
            except PilosaError:
                continue
            if remote_attrs:
                store.set_bulk_attrs(remote_attrs)

    # ------------------------------------------------------------- fragment

    def _sync_fragment(self, index: str, field: str, view: str, shard: int, replicas) -> None:
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            return
        if frag.quarantined:
            # A quarantined fragment booted empty after its file failed
            # validation. Letting its emptiness vote in the block merge
            # below could CLEAR acknowledged bits on healthy replicas, so
            # restore a full copy from a replica first; the normal checksum
            # walk then runs on repaired data (and pushes nothing, since
            # local now matches the repair source).
            if not self._repair_fragment(index, field, view, shard, frag, replicas):
                return  # no replica could serve a copy; retry next sweep
        local_blocks = {b.id: b.checksum for b in frag.blocks()}

        # Gather remote block checksums; union of block ids drives the merge.
        remote_blocks: List[Tuple[object, Dict[int, bytes]]] = []
        for node in replicas:
            try:
                blocks = self.client.fragment_blocks(node, index, field, shard,
                                                     view=view)
                remote_blocks.append(
                    (node, {b["id"]: bytes.fromhex(b["checksum"]) for b in blocks})
                )
            except PilosaError:
                continue

        all_ids = set(local_blocks)
        for _, blocks in remote_blocks:
            all_ids.update(blocks)

        for block_id in sorted(all_ids):
            checksums = [blocks.get(block_id) for _, blocks in remote_blocks]
            if all(c == local_blocks.get(block_id) for c in checksums):
                continue
            self._merge_block(index, field, view, shard, block_id, frag, remote_blocks)

    def _repair_fragment(self, index, field, view, shard, frag, replicas) -> bool:
        """Restore a quarantined fragment from the first replica that can
        ship a full copy (the resize shard-retrieval RPC), folding back any
        writes acknowledged locally while the fragment served degraded.
        Returns True when the fragment is whole again."""
        import io

        for node in replicas:
            try:
                data = self.client.retrieve_shard_from_uri(
                    node, index, field, view, shard
                )
            except PilosaError as e:
                self.server.logger.error(
                    "anti-entropy: repair pull %s/%s/%s/%s from %s failed: %s",
                    index, field, view, shard, node.id, e,
                )
                continue
            # Under the fragment's (reentrant) write mutex for the whole
            # capture -> restore -> fold-back sequence: a write landing
            # between the local snapshot and read_from's storage swap would
            # otherwise be silently dropped from the repaired fragment.
            with frag._mu:
                # Bits acknowledged AFTER quarantine (the corrupt original
                # booted empty, so everything currently in storage is
                # post-quarantine): a full replica restore must not drop
                # them.
                local_pos = frag.storage.slice()
                try:
                    frag.read_from(io.BytesIO(data))  # clears the quarantine
                except PilosaError as e:
                    self.server.logger.error(
                        "anti-entropy: repair stream %s/%s/%s/%s from %s "
                        "bad: %s", index, field, view, shard, node.id, e,
                    )
                    continue
                except OSError as e:
                    if frag.quarantined:
                        # Failed before the in-memory restore landed.
                        self.server.logger.error(
                            "anti-entropy: repair of %s/%s/%s/%s from %s "
                            "errored: %s", index, field, view, shard,
                            node.id, e,
                        )
                        continue
                    # The restore DID land (read_from swapped storage and
                    # cleared the quarantine) — only its trailing snapshot
                    # failed to persist. Fall through to the fold-back: the
                    # in-memory state is whole, and bulk_import/next
                    # snapshot retries persistence.
                    self.server.logger.error(
                        "anti-entropy: repaired %s/%s/%s/%s from %s but "
                        "snapshot persist failed (will retry): %s",
                        index, field, view, shard, node.id, e,
                    )
                if len(local_pos):
                    rows = local_pos // np.uint64(SHARD_WIDTH)
                    cols = (local_pos % np.uint64(SHARD_WIDTH)) + np.uint64(
                        shard * SHARD_WIDTH
                    )
                    frag.bulk_import(rows, cols)
            self.server.logger.info(
                "anti-entropy: repaired quarantined fragment %s/%s/%s/%s "
                "from %s", index, field, view, shard, node.id,
            )
            return True
        return False

    def _merge_block(self, index, field, view, shard, block_id, frag, remote_blocks) -> None:
        """Pull remote pairs, consensus-merge, push diffs (fragment.go:1737-1809)."""
        datas = []
        nodes = []
        for node, _ in remote_blocks:
            try:
                d = self.client.block_data(node, index, field, view, shard, block_id)
            except PilosaError:
                continue
            datas.append((np.asarray(d["rowIDs"], dtype=np.uint64),
                          np.asarray(d["columnIDs"], dtype=np.uint64)))
            nodes.append(node)
        if not datas:
            return
        sets, clears = frag.merge_block(block_id, datas)
        base = shard * SHARD_WIDTH
        for node, add, rem in zip(nodes, sets, clears):
            if not add and not rem:
                continue
            if view == VIEW_STANDARD:
                # Push standard-view diffs as Set/Clear PQL
                # (fragment.go:1814-1903 — the reference only syncs this
                # view). Chunked: one giant request for a large divergence
                # would trip the peer's max_writes_per_request cap (5000)
                # and the whole diff would be rejected.
                calls = [f"Set({base + c}, {field}={r})" for r, c in add]
                calls += [f"Clear({base + c}, {field}={r})" for r, c in rem]
                # Chunk under the CONFIGURED write cap, not a hardcoded
                # guess — a cluster run with a smaller cap would reject
                # every chunk and never converge.
                cap = getattr(self.server.executor, "max_writes_per_request", 0)
                chunk = min(1000, cap) if cap and cap > 0 else 1000
                for i in range(0, len(calls), chunk):
                    self.client.query_node(
                        node, index, " ".join(calls[i : i + chunk]), remote=True
                    )
            else:
                # Time/bsig views are unreachable via PQL writes; apply the
                # diff through the view-addressed internal endpoint instead.
                self.client.send_block_diff(
                    node, index, field, view, shard, block_id,
                    [[int(r), int(base + c)] for r, c in add],
                    [[int(r), int(base + c)] for r, c in rem],
                )
