"""Node identity and cluster membership/placement.

Port of the data-placement core of /root/reference/cluster.go: Node, cluster
states, partition/shardNodes placement with replication. The full resize
state machine lives in cluster/resize.py; this module is dependency-light so
the executor can use placement without pulling in networking.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Set, Tuple

from ..constants import DEFAULT_PARTITION_N
from .hash import JmpHasher, partition as partition_of
from .health import DownView, HealthRegistry

# Cluster states (reference cluster.go:43-45).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"


@dataclass
class Node:
    id: str
    uri: str = ""
    is_coordinator: bool = False
    # jax.distributed process index when this node is part of a multi-host
    # device-mesh job (None otherwise). The collective plane needs every
    # node's index to map jump-hash shard placement onto global-array slots
    # (parallel/collective.py placement); it propagates via node-join /
    # cluster-status messages and the member monitor's status probes.
    process_idx: Optional[int] = None

    def to_dict(self):
        d = {"id": self.id, "uri": self.uri, "isCoordinator": self.is_coordinator}
        if self.process_idx is not None:
            d["processIdx"] = self.process_idx
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(
            id=d["id"], uri=d.get("uri", ""),
            is_coordinator=d.get("isCoordinator", False),
            process_idx=d.get("processIdx"),
        )


class Cluster:
    """Membership + placement. Single-node by default; multi-node clusters
    append Nodes (sorted by id, as the reference maintains them)."""

    def __init__(
        self,
        node: Optional[Node] = None,
        nodes: Optional[List[Node]] = None,
        replica_n: int = 1,
        partition_n: int = DEFAULT_PARTITION_N,
        hasher=None,
    ):
        self.node = node or Node(id="node0")
        self.nodes: List[Node] = nodes or [self.node]
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.hasher = hasher or JmpHasher()
        self.state = STATE_NORMAL
        # Per-peer fault-tolerance state (cluster/health.py): circuit
        # breakers, retry budget, rolling latencies. The server installs
        # its [resilience] config via health.configure(); library users
        # get the defaults. Placement ignores this; the executor's owner
        # selection, retry, and hedging logic consult it.
        self.health = HealthRegistry()
        # Node ids currently down (failure detector; the reference's
        # memberlist suspicion state). A set-like view over the breaker
        # state: `in` means "breaker not closed", add/discard force it.
        self.unavailable = DownView(self.health)
        # Per-shard routing epochs (cluster/rebalance.py). During a live
        # rebalance `next_nodes` holds the target membership and
        # `migrated` the (index, shard) pairs whose cutover committed:
        # placement for a migrated shard follows the NEXT topology while
        # every other shard stays on the old owners — a half-migrated
        # cluster never serves a hole. `routing_epoch` is monotonic;
        # forwarded requests stamp it, and a receiver that has advanced
        # past the sender's epoch answers 409 (one re-route) instead of
        # serving from a moved/GC'd shard.
        self.routing_epoch = 0
        self.next_nodes: Optional[List[Node]] = None
        self.migrated: Set[Tuple[str, int]] = set()
        self._routing_mu = threading.Lock()

    # ------------------------------------------------------------ placement

    def partition(self, index: str, shard: int) -> int:
        return partition_of(index, shard, self.partition_n)

    def _placement(self, nodes: List[Node], partition_id: int) -> List[Node]:
        if not nodes:
            return []
        replica_n = min(self.replica_n, len(nodes)) or 1
        node_index = self.hasher.hash(partition_id, len(nodes))
        return [nodes[(node_index + i) % len(nodes)] for i in range(replica_n)]

    def partition_nodes(self, partition_id: int) -> List[Node]:
        return self._placement(self.nodes, partition_id)

    def shard_nodes(self, index: str, shard: int) -> List[Node]:
        # Snapshot the override state once: a concurrent commit/abort can
        # null next_nodes between a check and a re-read, and
        # _placement(None) would return zero owners for an owned shard.
        nxt = self.next_nodes
        nodes = self.nodes
        if nxt is not None and (index, shard) in self.migrated:
            nodes = nxt
        return self._placement(nodes, self.partition(index, shard))

    # ------------------------------------------------------ routing epochs

    def _advance_epoch(self, epoch: Optional[int]) -> None:
        # Must hold _routing_mu. An epoch carried by a coordinator
        # message is AUTHORITATIVE: merge with max() only. A local
        # routing change with no message epoch bumps by one. Doing both
        # (max(local+1, msg)) overshoots under message reordering — a
        # later commit's merge jumps the counter, then an earlier
        # commit's +1 pushes it past every number the coordinator will
        # ever send, and the node ends permanently ahead of the cluster.
        if epoch is not None:
            self.routing_epoch = max(self.routing_epoch, epoch)
        else:
            self.routing_epoch += 1

    def begin_rebalance(self, new_nodes: List[Node], committed=(),
                        epoch: Optional[int] = None) -> None:
        """Install the target membership of a live rebalance. Placement
        keeps following the OLD nodes until per-shard cutovers commit."""
        with self._routing_mu:
            self.next_nodes = sorted(new_nodes, key=lambda n: n.id)
            self.migrated = {(i, int(s)) for i, s in committed}
            self._advance_epoch(epoch)

    def apply_cutover(self, index: str, shard: int,
                      epoch: Optional[int] = None) -> None:
        """Commit one shard's routing flip to the next topology."""
        with self._routing_mu:
            if self.next_nodes is None:
                # No rebalance in flight (late/duplicate commit); still
                # merge an authoritative epoch so a node that already
                # collapsed the overrides doesn't fall behind.
                if epoch is not None:
                    self.routing_epoch = max(self.routing_epoch, epoch)
                return
            if (index, shard) in self.migrated:
                # Idempotent: the source flips at freeze time and again on
                # the broadcast commit; only the first advances the epoch.
                if epoch is not None:
                    self.routing_epoch = max(self.routing_epoch, epoch)
                return
            self.migrated.add((index, shard))
            self._advance_epoch(epoch)

    def revert_cutover(self, index: str, shard: int,
                       epoch: Optional[int] = None) -> None:
        """Reverse migration (autoscale abort, docs/rebalance.md): flip
        one committed shard's routing BACK to the prior topology after
        its data has been streamed back to the prior owners. The inverse
        of apply_cutover; idempotent the same way."""
        with self._routing_mu:
            if self.next_nodes is None:
                if epoch is not None:
                    self.routing_epoch = max(self.routing_epoch, epoch)
                return
            if (index, shard) not in self.migrated:
                # Late/duplicate revert; still merge an authoritative
                # epoch so this node doesn't fall behind.
                if epoch is not None:
                    self.routing_epoch = max(self.routing_epoch, epoch)
                return
            self.migrated.discard((index, shard))
            self._advance_epoch(epoch)

    def commit_topology(self, new_nodes: Optional[List[Node]] = None,
                        epoch: Optional[int] = None) -> None:
        """Job completion: the target membership becomes THE membership
        and the per-shard overrides collapse."""
        with self._routing_mu:
            nodes = new_nodes if new_nodes is not None else self.next_nodes
            if nodes is not None:
                self.nodes = sorted(nodes, key=lambda n: n.id)
            self.next_nodes = None
            self.migrated = set()
            self._advance_epoch(epoch)

    def adopt_topology_if_ahead(self, new_nodes: List[Node],
                                epoch: Optional[int]) -> bool:
        """Anti-entropy adoption (member monitor): atomically re-validate
        and commit a peer's post-job topology. The monitor's decision to
        adopt runs OUTSIDE the routing lock, so a rebalance-begin landing
        between the decision and the commit would otherwise have its
        next_nodes/migrated overrides wiped by the late commit — routing
        cut-over shards back to their old owners until the job's complete
        broadcast. Returns False when the adoption lost the race (a begin
        installed overrides, or the epoch caught up meanwhile)."""
        with self._routing_mu:
            if (self.next_nodes is not None
                    or epoch is None
                    or epoch <= self.routing_epoch):
                return False
            self.nodes = sorted(new_nodes, key=lambda n: n.id)
            self.migrated = set()
            self.routing_epoch = epoch
            return True

    def abort_rebalance(self, committed=None) -> bool:
        """Drop a live rebalance. Returns True when routing fully
        reverted to the old topology; False when cutovers had already
        committed — those shards keep the mixed routing (their data now
        lives on the new owners; reverting would lose post-cutover
        writes) until a resumed job finishes the move."""
        with self._routing_mu:
            kept = {(i, int(s)) for i, s in committed} if committed else set()
            kept &= self.migrated
            if not kept:
                self.next_nodes = None
                self.migrated = set()
                self.routing_epoch += 1
                return True
            self.migrated = kept
            self.routing_epoch += 1
            return False

    def mark_unavailable(self, node_id: str) -> None:
        self.unavailable.add(node_id)

    def mark_available(self, node_id: str) -> None:
        self.unavailable.discard(node_id)

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def contains_shards(self, index: str, max_shard: int, node: Node) -> List[int]:
        return [
            s
            for s in range(max_shard + 1)
            if any(n.id == node.id for n in self.partition_nodes(self.partition(index, s)))
        ]

    def node_by_id(self, node_id: str) -> Optional[Node]:
        for n in self.nodes:
            if n.id == node_id:
                return n
        # Mid-rebalance, a cut-over shard's owners come from the target
        # membership (e.g. the joining node) before it appears in `nodes`.
        if self.next_nodes is not None:
            for n in self.next_nodes:
                if n.id == node_id:
                    return n
        return None

    def coordinator_node(self) -> Optional[Node]:
        """The coordinator, preferring an AVAILABLE flagged node: after a
        failover a survivor can transiently hold both the dead
        coordinator's stale flag and the successor's fresh claim — joins
        must route to the live one, not the lowest-id corpse."""
        flagged = [n for n in self.nodes if n.is_coordinator]
        for n in flagged:
            if n.id not in self.unavailable:
                return n
        return flagged[0] if flagged else None

    def is_coordinator(self) -> bool:
        return self.node.is_coordinator

    def add_node(self, node: Node) -> None:
        if self.node_by_id(node.id) is None:
            self.nodes.append(node)
            self.nodes.sort(key=lambda n: n.id)

    def remove_node(self, node_id: str) -> bool:
        n = self.node_by_id(node_id)
        if n is None:
            return False
        self.nodes.remove(n)
        # Drop health/availability state with the membership entry: a
        # removed node's stale breaker must not shadow a later re-add
        # that reuses the same id.
        self.health.prune(node_id)
        return True
