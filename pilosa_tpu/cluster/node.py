"""Node identity and cluster membership/placement.

Port of the data-placement core of /root/reference/cluster.go: Node, cluster
states, partition/shardNodes placement with replication. The full resize
state machine lives in cluster/resize.py; this module is dependency-light so
the executor can use placement without pulling in networking.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..constants import DEFAULT_PARTITION_N
from .hash import JmpHasher, partition as partition_of
from .health import DownView, HealthRegistry

# Cluster states (reference cluster.go:43-45).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"


@dataclass
class Node:
    id: str
    uri: str = ""
    is_coordinator: bool = False
    # jax.distributed process index when this node is part of a multi-host
    # device-mesh job (None otherwise). The collective plane needs every
    # node's index to map jump-hash shard placement onto global-array slots
    # (parallel/collective.py placement); it propagates via node-join /
    # cluster-status messages and the member monitor's status probes.
    process_idx: Optional[int] = None

    def to_dict(self):
        d = {"id": self.id, "uri": self.uri, "isCoordinator": self.is_coordinator}
        if self.process_idx is not None:
            d["processIdx"] = self.process_idx
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(
            id=d["id"], uri=d.get("uri", ""),
            is_coordinator=d.get("isCoordinator", False),
            process_idx=d.get("processIdx"),
        )


class Cluster:
    """Membership + placement. Single-node by default; multi-node clusters
    append Nodes (sorted by id, as the reference maintains them)."""

    def __init__(
        self,
        node: Optional[Node] = None,
        nodes: Optional[List[Node]] = None,
        replica_n: int = 1,
        partition_n: int = DEFAULT_PARTITION_N,
        hasher=None,
    ):
        self.node = node or Node(id="node0")
        self.nodes: List[Node] = nodes or [self.node]
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.hasher = hasher or JmpHasher()
        self.state = STATE_NORMAL
        # Per-peer fault-tolerance state (cluster/health.py): circuit
        # breakers, retry budget, rolling latencies. The server installs
        # its [resilience] config via health.configure(); library users
        # get the defaults. Placement ignores this; the executor's owner
        # selection, retry, and hedging logic consult it.
        self.health = HealthRegistry()
        # Node ids currently down (failure detector; the reference's
        # memberlist suspicion state). A set-like view over the breaker
        # state: `in` means "breaker not closed", add/discard force it.
        self.unavailable = DownView(self.health)

    # ------------------------------------------------------------ placement

    def partition(self, index: str, shard: int) -> int:
        return partition_of(index, shard, self.partition_n)

    def partition_nodes(self, partition_id: int) -> List[Node]:
        if not self.nodes:
            return []
        replica_n = min(self.replica_n, len(self.nodes)) or 1
        node_index = self.hasher.hash(partition_id, len(self.nodes))
        return [
            self.nodes[(node_index + i) % len(self.nodes)] for i in range(replica_n)
        ]

    def shard_nodes(self, index: str, shard: int) -> List[Node]:
        return self.partition_nodes(self.partition(index, shard))

    def mark_unavailable(self, node_id: str) -> None:
        self.unavailable.add(node_id)

    def mark_available(self, node_id: str) -> None:
        self.unavailable.discard(node_id)

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def contains_shards(self, index: str, max_shard: int, node: Node) -> List[int]:
        return [
            s
            for s in range(max_shard + 1)
            if any(n.id == node.id for n in self.partition_nodes(self.partition(index, s)))
        ]

    def node_by_id(self, node_id: str) -> Optional[Node]:
        for n in self.nodes:
            if n.id == node_id:
                return n
        return None

    def coordinator_node(self) -> Optional[Node]:
        """The coordinator, preferring an AVAILABLE flagged node: after a
        failover a survivor can transiently hold both the dead
        coordinator's stale flag and the successor's fresh claim — joins
        must route to the live one, not the lowest-id corpse."""
        flagged = [n for n in self.nodes if n.is_coordinator]
        for n in flagged:
            if n.id not in self.unavailable:
                return n
        return flagged[0] if flagged else None

    def is_coordinator(self) -> bool:
        return self.node.is_coordinator

    def add_node(self, node: Node) -> None:
        if self.node_by_id(node.id) is None:
            self.nodes.append(node)
            self.nodes.sort(key=lambda n: n.id)

    def remove_node(self, node_id: str) -> bool:
        n = self.node_by_id(node_id)
        if n is None:
            return False
        self.nodes.remove(n)
        # Drop health/availability state with the membership entry: a
        # removed node's stale breaker must not shadow a later re-add
        # that reuses the same id.
        self.health.prune(node_id)
        return True
