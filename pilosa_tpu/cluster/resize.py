"""Cluster resize: coordinator-driven shard redistribution.

Port of the reference's resizeJob flow (cluster.go:1080-1423): when a node
joins/leaves with data present, the coordinator diffs old-vs-new shard
placement, builds one ResizeInstruction per node listing fragment sources,
broadcasts RESIZING, each node streams the fragments it is gaining from
source peers, acks with resize-complete, and the coordinator flips the
cluster back to NORMAL and broadcasts the new status.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional

from ..cluster.node import Cluster, Node, STATE_NORMAL, STATE_RESIZING
from ..errors import PilosaError


def fragment_sources(
    old_cluster: Cluster, new_cluster: Cluster, schema: List[dict],
    max_shards: Dict[str, int], source_ok=None,
) -> Dict[str, List[dict]]:
    """Per-node list of fragments each node must fetch, with a source node
    owning that fragment in the old placement (cluster.go:689 fragSources).

    `source_ok(node_id, index, field, view, shard) -> bool` lets the
    caller steer source selection away from unhealthy replicas: the
    first old owner it accepts wins, falling back to placement order if
    it rejects them all (a degraded source beats no source — the fetch
    itself still fails loudly if the source refuses). Shards with NO old
    owner (an empty prior cluster) are skipped outright: there is
    nothing to fetch, and blindly indexing old_owners[0] raised."""
    sources: Dict[str, List[dict]] = {n.id: [] for n in new_cluster.nodes}
    for idx_info in schema:
        index = idx_info["name"]
        max_shard = max_shards.get(index, 0)
        for shard in range(max_shard + 1):
            old_owners = [n.id for n in old_cluster.shard_nodes(index, shard)]
            if not old_owners:
                continue
            new_owners = [n.id for n in new_cluster.shard_nodes(index, shard)]
            gaining = [nid for nid in new_owners if nid not in old_owners]
            if not gaining:
                continue
            for f_info in idx_info.get("fields", []):
                for v_info in f_info.get("views", []):
                    src = old_owners[0]
                    if source_ok is not None:
                        for cand in old_owners:
                            if source_ok(cand, index, f_info["name"],
                                         v_info["name"], shard):
                                src = cand
                                break
                    for node_id in gaining:
                        sources[node_id].append(
                            {
                                "index": index,
                                "field": f_info["name"],
                                "view": v_info["name"],
                                "shard": shard,
                                "sourceNodeID": src,
                            }
                        )
    return sources


class ResizeJob:
    def __init__(self, job_id: str, instructions: Dict[str, List[dict]], new_nodes: List[Node]):
        self.id = job_id
        self.instructions = instructions
        self.new_nodes = new_nodes
        self.acks = {node_id: False for node_id in instructions}
        self.lock = threading.Lock()

    def ack(self, node_id: str) -> bool:
        with self.lock:
            self.acks[node_id] = True
            return all(self.acks.values())


class ResizeCoordinator:
    """Runs on the coordinator node; one job at a time (cluster.go:1095)."""

    def __init__(self, server):
        self.server = server
        self.job: Optional[ResizeJob] = None
        self._lock = threading.Lock()

    def begin(self, new_nodes: List[Node]) -> None:
        cluster = self.server.cluster
        with self._lock:
            if self.job is not None:
                raise PilosaError("a resize job is already running")
            old = Cluster(
                node=cluster.node,
                nodes=list(cluster.nodes),
                replica_n=cluster.replica_n,
                partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            new = Cluster(
                node=cluster.node,
                nodes=sorted(new_nodes, key=lambda n: n.id),
                replica_n=cluster.replica_n,
                partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            schema = self.server.holder.schema()
            max_shards = {
                name: idx.max_shard() for name, idx in self.server.holder.indexes.items()
            }
            sources = fragment_sources(old, new, schema, max_shards)
            job = ResizeJob(uuid.uuid4().hex[:8], sources, new.nodes)
            self.job = job

        cluster.state = STATE_RESIZING
        status = {
            "type": "cluster-status",
            "state": STATE_RESIZING,
            "nodes": [n.to_dict() for n in new.nodes],
        }
        self.server.broadcast_message(status)

        node_uris = {n.id: n.uri for n in old.nodes}
        node_uris.update({n.id: n.uri for n in new.nodes})
        for node_id, instr_sources in sources.items():
            msg = {
                "type": "resize-instruction",
                "jobID": job.id,
                "nodeID": node_id,
                "coordinatorID": cluster.node.id,
                "coordinatorURI": cluster.node.uri,
                "schema": schema,
                "sources": instr_sources,
                "nodeURIs": node_uris,
                "maxShards": max_shards,
            }
            if self.job is not job:
                return  # an earlier dispatch already aborted this job
            if node_id == cluster.node.id:
                follow_resize_instruction(self.server, msg)
            else:
                target = next((n for n in new.nodes if n.id == node_id), None)
                if target is not None:
                    try:
                        self.server.client.send_message(target, msg)
                    except PilosaError as e:
                        # An undeliverable instruction can never be acked:
                        # abort now instead of hanging in RESIZING forever.
                        self.abort(
                            f"cannot deliver resize instruction to "
                            f"{node_id}: {e}"
                        )
                        return

    def abort(self, reason: str) -> None:
        """Abandon the running job: the membership never flipped (nodes
        flip only on full completion), so the cluster returns to NORMAL on
        the OLD topology and no node garbage-collects anything
        (cluster.go:1247 job abort)."""
        with self._lock:
            job = self.job
            self.job = None
        if job is None:
            return
        self.server.logger.error("resize job %s aborted: %s", job.id, reason)
        cluster = self.server.cluster
        cluster.state = STATE_NORMAL
        self.server.broadcast_message(
            {
                "type": "cluster-status",
                "state": STATE_NORMAL,
                "nodes": [n.to_dict() for n in cluster.nodes],
            }
        )

    def complete(self, node_id: str, error: str = "",
                 job_id: str = "") -> None:
        with self._lock:
            job = self.job
        if job is None or (job_id and job_id != job.id):
            return  # stale ack from an earlier (aborted) job
        if error:
            self.abort(f"node {node_id} failed its resize instruction: {error}")
            return
        with self._lock:
            job = self.job
            if job is None:
                return
            done = job.ack(node_id)
            if done:
                self.job = None
        if done:
            cluster = self.server.cluster
            cluster.nodes = job.new_nodes
            cluster.state = STATE_NORMAL
            # Checkpoint membership so a restarting coordinator knows which
            # nodes to wait for (startup topology quorum).
            self.server.topology.save(job.new_nodes)
            self.server.broadcast_message(
                {
                    "type": "cluster-status",
                    "state": STATE_NORMAL,
                    "nodes": [n.to_dict() for n in job.new_nodes],
                }
            )
            # Post-resize GC on the COORDINATOR too: followers run the
            # holder cleaner on their RESIZING -> NORMAL status
            # transition, but the coordinator never receives its own
            # broadcast — without this it kept every fragment it stopped
            # owning, forever.
            from .topology import HolderCleaner

            removed = HolderCleaner(self.server).clean_holder()
            if removed:
                self.server.logger.info(
                    "resize %s: holder cleaner removed %d fragments",
                    job.id, len(removed))


def follow_resize_instruction(server, msg: dict) -> None:
    """Receiver side (cluster.go:1179 followResizeInstruction)."""
    import io

    server.holder.apply_schema(msg.get("schema", []))
    for index_name, max_shard in msg.get("maxShards", {}).items():
        idx = server.holder.index(index_name)
        if idx is not None:
            idx.set_remote_max_shard(max_shard)
    node_uris = msg.get("nodeURIs", {})
    errors = []
    for src in msg.get("sources", []):
        source_uri = node_uris.get(src["sourceNodeID"])
        if source_uri is None or src["sourceNodeID"] == server.cluster.node.id:
            continue
        try:
            data = server.client.retrieve_shard_from_uri(
                source_uri, src["index"], src["field"], src["view"], src["shard"]
            )
        except PilosaError as e:
            # A fetch failure must ABORT the resize, not complete with
            # holes: after completion every node garbage-collects shards
            # it no longer owns, so at replica_n=1 a silently-skipped
            # fragment would be lost when its old owner cleans up
            # (reference cluster.go followResizeInstruction propagates the
            # error and the coordinator aborts the job).
            errors.append(
                f"{src['index']}/{src['field']}/{src['view']}/{src['shard']} "
                f"from {src['sourceNodeID']}: {e}"
            )
            continue
        fld = server.holder.field(src["index"], src["field"])
        if fld is None:
            continue
        view = fld.create_view_if_not_exists(src["view"])
        frag = view.create_fragment_if_not_exists(src["shard"])
        frag.read_from(io.BytesIO(data))

    complete = {
        "type": "resize-complete",
        "jobID": msg.get("jobID"),
        "nodeID": server.cluster.node.id,
    }
    if errors:
        complete["error"] = "; ".join(errors[:4])
    if msg.get("coordinatorID") == server.cluster.node.id:
        mark_resize_instruction_complete(server, complete)
    else:
        server.client.send_message(
            Node(id=msg.get("coordinatorID", ""), uri=msg.get("coordinatorURI", "")),
            complete,
        )


def mark_resize_instruction_complete(server, msg: dict) -> None:
    coordinator = getattr(server, "resize_coordinator", None)
    if coordinator is not None:
        coordinator.complete(
            msg.get("nodeID", ""), error=msg.get("error", ""),
            job_id=msg.get("jobID", ""),
        )
