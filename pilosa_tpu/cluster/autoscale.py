"""Trace-driven autoscaler: sustained load -> membership change, with
full revert on abort.

The control loop closes the multi-tenant QoS story (docs/rebalance.md,
docs/scheduler.md): the scheduler measures per-index traffic and the
trace recorder measures per-stage latency; this controller turns a
SUSTAINED excursion of those signals into a rebalance join (scale-out
from a standby pool) or leave (scale-in of a node it added earlier),
through the exact same coordinator path an operator join/leave takes —
there is no second resize mechanism to keep correct.

Design points:

- **Hysteresis, not thresholds.** A decision needs `window` consecutive
  samples on the same side of a watermark (every sample >= scale-out-qps
  to grow, every sample <= scale-in-qps to shrink), plus a `cooldown`
  since the last action. One hot scrape never moves data.
- **Single-flight.** step() is try-lock guarded: the monitor timer, a
  debug trigger, and a test driving the clock can overlap without ever
  running two control decisions concurrently (the hint-daemon pattern,
  cluster/hints.py).
- **Full revert.** Before acting the controller arms
  RebalanceCoordinator.revert_on_abort, so ANY abort of the job it
  started — operator abort, shard failure, lost instruction — escalates
  into the reverse migration (rebalance.py begin_revert): committed
  shards stream back to their prior owners and routing is restored
  byte-identically. An autoscale job either completes or leaves nothing.
- **Only takes back what it gave.** Scale-in removes the most recently
  autoscale-added node; nodes the operator placed are never touched, and
  `min-nodes`/`max-nodes` bound the membership either way. The added-node
  list is checkpointed to `.autoscale.json` so a restarted coordinator
  still knows what it owns.

jax-free (config.py imports AutoscaleConfig at CLI startup).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from .. import failpoints
from ..obs import activate, deactivate
from ..obs import record as obs_record
from .node import Node

STATE_FILE = ".autoscale.json"


@dataclass
class AutoscaleConfig:
    """[autoscale] knobs (TOML + PILOSA_TPU_AUTOSCALE_* env + CLI flags).
    See docs/rebalance.md for how they interact."""

    # Seconds between control steps; 0 disables the controller entirely
    # (no monitor thread is spawned — the [tier] prefetch-interval gating
    # pattern).
    interval: float = 0.0
    # Consecutive samples that must agree before a decision: every sample
    # in the window >= scale-out-qps grows the cluster, every sample
    # <= scale-in-qps shrinks it. Anything mixed is "hold".
    window: int = 3
    # High watermark: cluster-wide queries/sec (summed index_traffic
    # diffs) above which a sustained window triggers scale-out.
    scale_out_qps: float = 100.0
    # Low watermark for scale-in. Must sit strictly below scale-out-qps:
    # the dead band between them is what stops flapping.
    scale_in_qps: float = 10.0
    # Optional latency trigger: when > 0, a window in which the worst
    # per-stage p99 (trace recorder stage histograms) stays above this
    # ALSO counts as sustained-high, even below the qps watermark — a few
    # expensive tenants can saturate devices at low qps. 0 ignores
    # latency.
    p99_ms: float = 0.0
    # Seconds after any scale action before the next one may fire;
    # rebalance jobs also block decisions while in flight.
    cooldown: float = 300.0
    # Membership bounds. max-nodes 0 means "bounded by the standby pool".
    min_nodes: int = 1
    max_nodes: int = 0
    # Comma-separated URIs (host:port) of standby nodes: running servers
    # that are not cluster members. Scale-out admits the first standby
    # not already a member; empty disables scale-out.
    standby: str = ""

    def validate(self) -> "AutoscaleConfig":
        if self.interval < 0:
            raise ValueError("[autoscale] interval must be >= 0")
        if self.window < 1:
            raise ValueError("[autoscale] window must be >= 1")
        if self.scale_out_qps <= 0:
            raise ValueError("[autoscale] scale-out-qps must be > 0")
        if not 0 <= self.scale_in_qps < self.scale_out_qps:
            raise ValueError(
                "[autoscale] scale-in-qps must be in [0, scale-out-qps)")
        if self.p99_ms < 0:
            raise ValueError("[autoscale] p99-ms must be >= 0")
        if self.cooldown < 0:
            raise ValueError("[autoscale] cooldown must be >= 0")
        if self.min_nodes < 1:
            raise ValueError("[autoscale] min-nodes must be >= 1")
        if self.max_nodes and self.max_nodes < self.min_nodes:
            raise ValueError(
                "[autoscale] max-nodes must be 0 or >= min-nodes")
        return self

    def standby_uris(self) -> List[str]:
        return [u.strip() for u in self.standby.split(",") if u.strip()]


def _hist_p99(snap: dict) -> float:
    """p99 upper-bound estimate from a Histogram.snapshot() dict: the
    smallest bucket bound whose cumulative count covers 99% of samples
    (the observed max for the +Inf overflow bucket)."""
    total = snap.get("count", 0)
    if not total:
        return 0.0
    target = 0.99 * total
    seen = 0
    finite = sorted(
        ((float(k), n) for k, n in snap["buckets"].items() if k != "+Inf"),
        key=lambda kv: kv[0])
    for bound, n in finite:
        seen += n
        if seen >= target:
            return bound
    return float(snap.get("max") or 0.0)


class AutoscaleController:
    """One instance per server; step() runs on the server's monitor timer
    (server.py _spawn) and is safe to call directly from tests or a debug
    trigger."""

    def __init__(self, server, config: Optional[AutoscaleConfig] = None,
                 clock=time.monotonic):
        self.server = server
        self.config = (config or AutoscaleConfig()).validate()
        self.clock = clock
        self._flight = threading.Lock()  # single-flight step guard
        self._lock = threading.Lock()  # samples/counters/added
        self._samples: deque = deque(maxlen=max(1, self.config.window))
        self._last_total: Optional[int] = None
        self._last_time: Optional[float] = None
        self._last_action_at: Optional[float] = None
        self.last_decision = "idle"
        # Node ids this controller added (insertion order). Scale-in pops
        # from the tail; the operator's original membership is never
        # shrunk. Survives coordinator restarts via the checkpoint.
        self._added: List[str] = []
        self.counters: Dict[str, int] = {
            "steps": 0,
            "samples": 0,
            "scale_out": 0,
            "scale_in": 0,
            "skipped_inflight": 0,
            "skipped_cooldown": 0,
            "skipped_rebalancing": 0,
            "skipped_bounds": 0,
            "errors": 0,
        }
        self._load_state()

    # ------------------------------------------------------------ persist

    def _state_path(self) -> Optional[str]:
        if not self.server.data_dir:
            return None
        return os.path.join(self.server.data_dir, STATE_FILE)

    def _load_state(self) -> None:
        path = self._state_path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                state = json.load(f)
            self._added = [str(n) for n in state.get("added", [])]
        except (OSError, ValueError) as e:
            self.server.logger.error(
                "autoscale: unreadable checkpoint %s: %s", path, e)

    def _persist(self) -> None:
        path = self._state_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"added": list(self._added)}, f)
        # pilint: allow-blocking(_flight is a try-acquire single-flight busy flag — contenders skip instead of waiting, so nothing can queue behind this tiny checkpoint rename)
        os.replace(tmp, path)

    # ------------------------------------------------------------ sensing

    def _sample(self, now: float) -> Optional[dict]:
        """One observation: cluster-wide qps (index_traffic diff over the
        step interval) and the worst per-stage p99. The first call only
        seeds the traffic baseline."""
        total = sum(self.server.scheduler.index_traffic().values())
        last_total, last_time = self._last_total, self._last_time
        self._last_total, self._last_time = total, now
        if last_total is None or now <= (last_time or now):
            return None
        qps = max(0.0, total - last_total) / (now - last_time)
        p99 = 0.0
        if self.config.p99_ms > 0:
            hists = self.server.trace_recorder.stage_histograms()
            p99 = max(
                (_hist_p99(s) for s in hists.values()), default=0.0)
        return {"qps": qps, "p99_ms": p99}

    def _decide(self) -> str:
        """Pure hysteresis over the sample window; caller handles
        cooldown/bounds/in-flight gating."""
        cfg = self.config
        if len(self._samples) < cfg.window:
            return "hold"
        over = all(
            s["qps"] >= cfg.scale_out_qps
            or (cfg.p99_ms > 0 and s["p99_ms"] >= cfg.p99_ms)
            for s in self._samples)
        if over:
            return "out"
        under = all(
            s["qps"] <= cfg.scale_in_qps
            and (cfg.p99_ms == 0 or s["p99_ms"] < cfg.p99_ms)
            for s in self._samples)
        return "in" if under else "hold"

    # ------------------------------------------------------------- acting

    def _arm_revert(self):
        """Ensure the rebalance coordinator exists and arm its
        revert-on-abort contract for the job this action is about to
        start. Returns the coordinator (to disarm if no job began)."""
        from .rebalance import RebalanceCoordinator

        server = self.server
        if server.rebalance_coordinator is None:
            server.rebalance_coordinator = RebalanceCoordinator(server)
        server.rebalance_coordinator.revert_on_abort = True
        return server.rebalance_coordinator

    def _scale_out(self) -> bool:
        server = self.server
        cluster = server.cluster
        member_uris = {n.uri for n in cluster.nodes}
        uri = next((u for u in self.config.standby_uris()
                    if u not in member_uris), None)
        if uri is None:
            self.counters["skipped_bounds"] += 1
            return False
        try:
            # The standby is a RUNNING server that simply isn't a member:
            # ask it who it is rather than inventing an identity the
            # rebalance plane would then disagree with.
            # pilint: allow-blocking(_flight is a try-acquire single-flight busy flag — contenders skip instead of waiting, so the standby probe blocks nobody)
            status = server.client.status(uri)
            node = Node(id=status["localID"], uri=uri)
        except Exception as e:
            self.counters["errors"] += 1
            server.logger.error(
                "autoscale: standby %s unreachable: %s", uri, e)
            return False
        coord = self._arm_revert()
        server.logger.info(
            "autoscale: sustained load -> scale-out, admitting %s (%s)",
            node.id, uri)
        try:
            server.handle_node_join(node)
        except Exception as e:
            self.counters["errors"] += 1
            server.logger.error("autoscale: join of %s failed: %s",
                                node.id, e)
            coord.revert_on_abort = coord.job is not None
            return False
        with self._lock:
            if node.id not in self._added:
                self._added.append(node.id)
        self._persist()
        if coord.job is None:
            # Empty holder: the join was a plain status broadcast, no
            # rebalance job to guard — don't leave the flag armed for a
            # future operator job.
            coord.revert_on_abort = False
        self.counters["scale_out"] += 1
        return True

    def _scale_in(self) -> bool:
        server = self.server
        with self._lock:
            victim = self._added[-1] if self._added else None
        if victim is None or server.cluster.node_by_id(victim) is None:
            self.counters["skipped_bounds"] += 1
            return False
        coord = self._arm_revert()
        server.logger.info(
            "autoscale: sustained idle -> scale-in, removing %s", victim)
        try:
            server.handle_node_leave(victim)
        except Exception as e:
            self.counters["errors"] += 1
            server.logger.error("autoscale: leave of %s failed: %s",
                                victim, e)
            coord.revert_on_abort = coord.job is not None
            return False
        with self._lock:
            if victim in self._added:
                self._added.remove(victim)
        self._persist()
        if coord.job is None:
            coord.revert_on_abort = False
        self.counters["scale_in"] += 1
        return True

    # --------------------------------------------------------------- step

    def step(self) -> str:
        """One control iteration. Returns the decision taken:
        "out"/"in" (acted), "hold", or a skip reason."""
        if not self._flight.acquire(blocking=False):
            self.counters["skipped_inflight"] += 1
            return "skipped-inflight"
        try:
            return self._step_locked()
        finally:
            self._flight.release()

    def _step_locked(self) -> str:
        failpoints.fire("autoscale-step")
        server = self.server
        self.counters["steps"] += 1
        start = self.clock()
        sample = self._sample(start)
        decision = "seeding"
        if sample is not None:
            self.counters["samples"] += 1
            self._samples.append(sample)
            decision = self._decide()
        # The decision span lands in the trace ring + stage histograms
        # like any query stage; the controller runs outside any request,
        # so it opens its own one-span trace (sample-rate gated).
        t = server.trace_recorder.maybe_start(pql="autoscale")
        tok = activate(t) if t is not None else None
        try:
            obs_record(
                "autoscale.decide", (self.clock() - start) * 1000.0,
                decision=decision,
                qps=round(sample["qps"], 2) if sample else None)
        finally:
            if t is not None:
                deactivate(tok)
                server.trace_recorder.finish(t)
        # Non-coordinators (and offline-rebalance deployments) sample but
        # never act: a failover promotion inherits a warm window, and the
        # reverse-migration revert contract only exists on the online
        # rebalance path — never autoscale through the stop-the-world
        # resize.
        if not server.cluster.is_coordinator():
            return self._note("not-coordinator")
        if not server.rebalance_config.online:
            return self._note("offline-rebalance")
        if decision not in ("out", "in"):
            return self._note(decision)
        coord = server.rebalance_coordinator
        if coord is not None and coord.job is not None:
            self.counters["skipped_rebalancing"] += 1
            return self._note("skipped-rebalancing")
        now = self.clock()
        if (self._last_action_at is not None
                and now - self._last_action_at < self.config.cooldown):
            self.counters["skipped_cooldown"] += 1
            return self._note("skipped-cooldown")
        n = len(server.cluster.nodes)
        if decision == "out":
            cap = self.config.max_nodes
            if cap and n >= cap:
                self.counters["skipped_bounds"] += 1
                return self._note("skipped-bounds")
            acted = self._scale_out()
        else:
            if n <= self.config.min_nodes:
                self.counters["skipped_bounds"] += 1
                return self._note("skipped-bounds")
            acted = self._scale_in()
        if acted:
            self._last_action_at = now
            # A fresh mandate is required for the NEXT action: reuse of a
            # pre-action window would chain scale-outs off one burst.
            self._samples.clear()
            return self._note(decision)
        return self._note("hold")

    def _note(self, decision: str) -> str:
        self.last_decision = decision
        return decision

    # ------------------------------------------------------------ reading

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["added_nodes"] = list(self._added)
            out["window"] = [dict(s) for s in self._samples]
        out["last_decision"] = self.last_decision
        out["interval"] = self.config.interval
        out["scale_out_qps"] = self.config.scale_out_qps
        out["scale_in_qps"] = self.config.scale_in_qps
        out["cooldown"] = self.config.cooldown
        return out
