"""Shard placement math (port of /root/reference/cluster.go:776-857).

Kept byte-identical to the reference: shard -> partition via FNV-1a 64 over
(index name + big-endian shard), partition -> node via jump consistent
hashing, replicas on consecutive ring nodes. The same math places shards on
TPU mesh devices (parallel/mesh.py) so single-host and multi-host layouts
agree.
"""

from __future__ import annotations

import struct

from ..constants import DEFAULT_PARTITION_N

_MASK64 = (1 << 64) - 1


def fnv64a(data: bytes) -> int:
    h = 14695981039346656037
    for b in data:
        h ^= b
        h = (h * 1099511628211) & _MASK64
    return h


def partition(index: str, shard: int, partition_n: int = DEFAULT_PARTITION_N) -> int:
    data = index.encode() + struct.pack(">Q", shard)
    return fnv64a(data) % partition_n


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash (cluster.go:846-857 jmphasher)."""
    b, j = -1, 0
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


class JmpHasher:
    def hash(self, key: int, n: int) -> int:
        return jump_hash(key, n)


class ModHasher:
    """Deterministic placement for tests (reference test/cluster.go:18)."""

    def hash(self, key: int, n: int) -> int:
        return key % n if n else 0
