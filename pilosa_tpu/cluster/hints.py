"""Hinted handoff: durable per-peer hint logs for missed write forwards.

The write path's last durability hole (docs/durability.md "Write-path
consistency"): a write forwarded to a breaker-open or failing replica used
to be skipped outright, leaving the acked bit on a single node until the
next full anti-entropy sweep. This module closes it the Dynamo/Cassandra
way, adapted to the bitmap op-stream:

  capture      the coordinator's LOCAL apply already encodes every
               mutation as WAL op records (storage/bitmap.py point +
               OP_BULK codec — the same bytes the rebalance catch-up
               stream ships). core/fragment.py's capture hook hands those
               bytes to the executor's fan-out, so a hint is byte-
               identical to what the missed replica's own WAL would have
               recorded.

  append       when a forward is skipped (breaker open) or fails at the
               transport, the op batch is appended to a durable per-peer
               log under <data-dir>/hints/ — O(batch) disk write, never a
               connect timeout. While a peer has undelivered hints, LATER
               writes for it append behind them too (per-peer FIFO), so
               replay order matches coordinator apply order and a drain
               can never resurrect a bit a newer write cleared.

  deliver      a background daemon replays each peer's log in order with
               a checkpointed cursor, gated by the peer's circuit breaker
               (cluster/health.py): an OPEN breaker skips the peer for
               free, an elapsed backoff makes the delivery attempt the
               half-open probe, and a delivery success re-closes it.
               Replay is idempotent set/clear — a redelivered record after
               a crash between send and checkpoint is harmless.

  expire       records carry a wall-clock birth time; past `hint-ttl`
               they are dropped at delivery and the shard is flagged for
               the anti-entropy syncer, which orders flagged shards first
               (cluster/syncer.py). The syncer is always the backstop —
               hints only shrink the repair window from sweep-interval to
               seconds.

Hints that cannot carry op bytes (the coordinating node holds no local
replica of the shard, so nothing was captured) degrade to a MARKER: no
payload, but the (index, shard) is flagged for priority anti-entropy the
same way an expired hint is.

Jax-free and numpy/stdlib-only: config.py imports ReplicationConfig at
CLI startup.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import urllib.parse
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import failpoints

WRITE_ONE = "one"
WRITE_QUORUM = "quorum"
WRITE_ALL = "all"
_LEVELS = (WRITE_ONE, WRITE_QUORUM, WRITE_ALL)


@dataclass
class ReplicationConfig:
    """The `[replication]` config section (TOML + env + CLI, config.py).
    See docs/durability.md "Write-path consistency"."""

    # Ack gate for the owner write fan-out (executor.tolerant_owner_fanout):
    # `one` acks when any owner applied (the reference's behavior),
    # `quorum` requires a majority of replicaN, `all` requires every
    # owner. An unmet level surfaces as a typed retryable 503 AFTER hints
    # were enqueued for the missed owners — there is no rollback; the
    # applied copies stand and repair flows toward the missed ones.
    write_consistency: str = WRITE_ONE
    # Hints older than this are dropped at delivery (their shard falls
    # back to priority anti-entropy). Bounds how stale a replayed op can
    # be, and how long a dead peer's log is worth keeping.
    hint_ttl: float = 3600.0
    # Per-peer hint log byte budget. At the cap, appends are refused (the
    # shard is flagged for priority anti-entropy instead) so one dead
    # peer under heavy ingest cannot eat the disk.
    hint_max_bytes: int = 64 << 20
    # Delivery daemon cadence (seconds between drain sweeps); 0 disables
    # background delivery (tests drive deliver_once() by hand).
    deliver_interval: float = 1.0
    # Max hint-log bytes replayed toward one peer per sweep: bounds how
    # long a drain monopolizes the daemon thread and how big a burst a
    # freshly-recovered peer absorbs at once.
    deliver_batch_bytes: int = 4 << 20

    def validate(self) -> "ReplicationConfig":
        if self.write_consistency not in _LEVELS:
            raise ValueError(
                "replication.write-consistency must be one of "
                f"{'/'.join(_LEVELS)}, got {self.write_consistency!r}")
        if self.hint_ttl <= 0:
            raise ValueError("replication.hint-ttl must be > 0")
        if self.hint_max_bytes < 0:
            raise ValueError("replication.hint-max-bytes must be >= 0")
        if self.deliver_interval < 0:
            raise ValueError("replication.deliver-interval must be >= 0")
        if self.deliver_batch_bytes <= 0:
            raise ValueError("replication.deliver-batch-bytes must be > 0")
        return self

    def required_owners(self, n_owners: int) -> int:
        """How many owners must APPLY (not hint) before the ack."""
        if self.write_consistency == WRITE_ALL:
            return n_owners
        if self.write_consistency == WRITE_QUORUM:
            return n_owners // 2 + 1
        return 1


# Hint record framing. One record per captured fragment op batch:
#
#   <I body_len> <I crc32(body)> body
#   body := <d created> <Q shard> <H len(index)> <H len(field)>
#           <H len(view)> index field view ops
#
# `ops` is a run of storage/bitmap.py WAL records (point + OP_BULK) —
# byte-identical to what the coordinator's local WAL appended for the
# same write, replayed on the peer via the SAME _apply_op_stream framing
# (storage/bitmap.decode_op_records) so the two codecs cannot drift.
# Empty ops = a marker hint (sync-priority only, no payload to replay).
_HEAD = struct.Struct("<II")
_BODY = struct.Struct("<dQHHH")

# Torn-tail scanning needs an upper bound to reject absurd lengths from
# bit rot without reading the whole remainder as one "record".
_MAX_RECORD = 256 << 20


class HintRecord:
    __slots__ = ("created", "index", "field", "view", "shard", "ops", "size")

    def __init__(self, created, index, field, view, shard, ops, size=0):
        self.created = created
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.ops = ops  # b"" for a marker hint
        self.size = size  # on-disk footprint incl. framing

    @property
    def marker(self) -> bool:
        return not self.ops


def encode_record(rec: HintRecord) -> bytes:
    i = rec.index.encode()
    f = rec.field.encode()
    v = rec.view.encode()
    body = _BODY.pack(rec.created, rec.shard, len(i), len(f), len(v)) \
        + i + f + v + rec.ops
    import zlib

    return _HEAD.pack(len(body), zlib.crc32(body)) + body


def decode_records(data: bytes, offset: int = 0):
    """Yield (record, next_offset) from `offset`; stops at the first
    incomplete or checksum-failing record (the torn tail). The caller
    decides whether trailing garbage is a crash artifact (truncate) —
    unlike the fragment WAL there is no quarantine tier: hints are a
    redundancy layer and anti-entropy backstops anything lost here."""
    import zlib

    n = len(data)
    while offset + _HEAD.size <= n:
        body_len, crc = _HEAD.unpack_from(data, offset)
        end = offset + _HEAD.size + body_len
        if body_len > _MAX_RECORD or end > n:
            return  # incomplete / implausible trailing record
        body = data[offset + _HEAD.size:end]
        if zlib.crc32(body) != crc:
            return
        created, shard, li, lf, lv = _BODY.unpack_from(body, 0)
        p = _BODY.size
        index = body[p:p + li].decode()
        field = body[p + li:p + li + lf].decode()
        view = body[p + li + lf:p + li + lf + lv].decode()
        ops = bytes(body[p + li + lf + lv:])
        yield HintRecord(created, index, field, view, shard, ops,
                         size=end - offset), end
        offset = end


def _peer_dirname(peer_id: str) -> str:
    # Peer ids are URIs in static clusters ("localhost:10101") — percent-
    # encode so ':' and '/' cannot escape the hints directory.
    return urllib.parse.quote(peer_id, safe="")


class _PeerLog:
    __slots__ = ("lock", "fh", "path", "cursor_path", "cursor", "size",
                 "pending", "shards", "unsynced")

    def __init__(self):
        self.lock = threading.Lock()
        self.fh = None
        self.path = ""
        self.cursor_path = ""
        self.cursor = 0  # delivered byte offset
        self.size = 0
        self.pending = 0  # undelivered record count
        self.shards: Dict[Tuple[str, int], int] = {}  # pending per shard
        self.unsynced = 0  # appends since last fsync (batch mode)


class HintStore:
    """Durable per-peer hint logs + the delivery state machine.

    Thread model: appends come from write fan-out threads, delivery from
    the server's monitor thread, snapshots from the handler. Per-peer
    state rides a per-peer lock; the store-level lock only guards the
    peer map and shared counters. Network sends never run under any lock
    (delivery reads records under the peer lock, sends outside it)."""

    def __init__(self, path: Optional[str],
                 config: Optional[ReplicationConfig] = None,
                 storage_config=None,
                 clock: Optional[Callable[[], float]] = None):
        from ..storage import StorageConfig

        self.path = path  # None = memory-only (library/test holders)
        self.config = (config or ReplicationConfig()).validate()
        self.storage_config = storage_config or StorageConfig()
        self.clock = clock or time.time
        self._mu = threading.Lock()
        # Delivery is single-flighted: cursors assume one replayer. The
        # server's daemon is normally the only caller, but tests drive
        # deliver_once by hand — a concurrent attempt returns 0 instead
        # of racing the cursor.
        self._deliver_mu = threading.Lock()
        self._peers: Dict[str, _PeerLog] = {}
        # Shards owed a priority anti-entropy pass: expired hints, marker
        # hints, overflow-refused appends. Cleared by note_synced when the
        # syncer repairs the shard.
        self._needs_sync: Set[Tuple[str, int]] = set()
        self.counters: Dict[str, int] = {
            "hints_appended": 0,
            "hints_delivered": 0,
            "hints_expired": 0,
            "hints_rejected": 0,   # peer answered 4xx: hint unreplayable
            "hints_markers": 0,
            "hints_overflow": 0,   # appends refused at hint-max-bytes
            "hints_truncated": 0,  # torn/corrupt log tails cut at open
            "append_errors": 0,
            "bytes_appended": 0,
            "bytes_delivered": 0,
            "drains": 0,           # peer logs drained to empty
            "deliver_errors": 0,
        }
        if self.path:
            os.makedirs(self.path, exist_ok=True)
            self._reload()

    # ----------------------------------------------------------- lifecycle

    def _reload(self) -> None:
        """Rebuild in-memory pending state from the on-disk logs (crash /
        restart recovery). A torn tail — the SIGKILL-mid-append artifact —
        truncates to the last whole-record boundary; garbage is never
        replayed toward a peer."""
        for name in sorted(os.listdir(self.path)):
            d = os.path.join(self.path, name)
            if not os.path.isdir(d):
                continue
            peer_id = urllib.parse.unquote(name)
            log = self._log(peer_id)
            with log.lock:
                self._open_locked(peer_id, log, scan=True)

    def close(self) -> None:
        with self._mu:
            peers = list(self._peers.values())
        for log in peers:
            with log.lock:
                if log.fh is not None:
                    try:
                        if log.unsynced and \
                                self.storage_config.fsync != "never":
                            # pilint: allow-blocking(close-boundary flush: batch-mode appends owe one fsync before the handle drops, same contract as the fragment WAL close)
                            os.fsync(log.fh.fileno())
                    except OSError:
                        pass
                    log.fh.close()
                    log.fh = None

    def _log(self, peer_id: str) -> _PeerLog:
        with self._mu:
            log = self._peers.get(peer_id)
            if log is None:
                log = self._peers[peer_id] = _PeerLog()
            return log

    def _open_locked(self, peer_id: str, log: _PeerLog, scan: bool) -> None:
        """Open (creating) the peer's log + cursor. Must hold log.lock."""
        if self.path is None or log.fh is not None:
            return
        d = os.path.join(self.path, _peer_dirname(peer_id))
        os.makedirs(d, exist_ok=True)
        log.path = os.path.join(d, "log")
        log.cursor_path = os.path.join(d, "cursor")
        cursor = 0
        if os.path.exists(log.cursor_path):
            try:
                with open(log.cursor_path) as f:
                    cursor = int(f.read().strip() or 0)
            except (OSError, ValueError):
                cursor = 0  # re-deliver from 0: replay is idempotent
        size = os.path.getsize(log.path) if os.path.exists(log.path) else 0
        cursor = min(cursor, size)
        valid = cursor
        if scan and size > cursor:
            # Bounded chunked scan shared with the CDC change log
            # (storage/logscan.py): one reader, one set of torn-tail
            # semantics — a record spanning a chunk boundary is extended
            # by the next read, and whatever tail remains at EOF is torn
            # and truncated to the last whole-record boundary.
            from ..storage.logscan import scan_log

            now = self.clock()

            def note(rec):
                log.pending += 1
                key = (rec.index, rec.shard)
                log.shards[key] = log.shards.get(key, 0) + 1
                if rec.marker or now - rec.created > self.config.hint_ttl:
                    self._needs_sync.add(key)

            res = scan_log(log.path, decode_records, start=cursor,
                           on_record=note)
            if res.truncated:
                with self._mu:
                    self.counters["hints_truncated"] += 1
            size = res.valid
        log.size = size
        log.cursor = min(cursor, log.size)
        log.fh = open(log.path, "ab")

    # -------------------------------------------------------------- append

    def add(self, peer_id: str, index: str, shard: int,
            records: Optional[List[Tuple[object, bytes]]]) -> bool:
        """Append the captured op batch for one write as hints toward
        `peer_id`. `records` is [(fragment, ops_bytes), ...] from the
        coordinator's local apply (core/fragment.capture_hint_ops); empty
        or None degrades to a marker hint: no replayable payload, but the
        (index, shard) is flagged for priority anti-entropy.

        Returns True when the hint is DURABLE per the [storage] fsync
        policy (the caller counts the owner as hinted-not-applied either
        way; False means the miss is covered only by the sweep)."""
        now = self.clock()
        recs = []
        if records and self.path is not None:
            recs = [HintRecord(now, f.index, f.field, f.view, f.shard, ops)
                    for f, ops in records if ops]
        if not recs:
            # No replayable payload (coordinator holds no local replica of
            # the shard, or a pathless store has nowhere durable to put
            # one): flag the shard for priority anti-entropy instead.
            with self._mu:
                self.counters["hints_markers"] += 1
                self._needs_sync.add((index, shard))
            if self.path is None:
                return False
            recs = [HintRecord(now, index, "", "", shard, b"")]
        log = self._log(peer_id)
        encoded = []
        for r in recs:
            b = encode_record(r)
            if len(b) - _HEAD.size > _MAX_RECORD:
                # decode_records treats an implausible body length as a
                # torn tail, so appending this record would WEDGE the
                # peer's drain forever (cursor can never pass it, and
                # the FIFO pre-check would queue every later write
                # behind it). Refuse the whole write's batch up front;
                # the sweep repairs every shard it touched.
                with self._mu:
                    self.counters["hints_overflow"] += 1
                    for rr in recs:
                        self._needs_sync.add((rr.index, rr.shard))
                return False
            encoded.append(b)
        payload = b"".join(encoded)
        with log.lock:
            self._open_locked(peer_id, log, scan=False)
            budget = self.config.hint_max_bytes
            if budget and log.size - log.cursor + len(payload) > budget:
                with self._mu:
                    self.counters["hints_overflow"] += 1
                    for r in recs:
                        self._needs_sync.add((r.index, r.shard))
                return False
            try:
                failpoints.fire("hint-append")
                log.fh.write(payload)
                log.fh.flush()
                self._fsync_locked(log)
            except OSError:
                with self._mu:
                    self.counters["append_errors"] += 1
                    for r in recs:
                        self._needs_sync.add((r.index, r.shard))
                return False
            log.size += len(payload)
            for r in recs:
                log.pending += 1
                key = (r.index, r.shard)
                log.shards[key] = log.shards.get(key, 0) + 1
        with self._mu:
            self.counters["hints_appended"] += len(recs)
            self.counters["bytes_appended"] += len(payload)
        return True

    def _fsync_locked(self, log: _PeerLog) -> None:
        """[storage] fsync policy applied to the hint log: `always` syncs
        per append, `batch` every fsync-batch-ops appends (the ack may
        ride up to N-1 page-cache hints across a power loss — same
        contract as the WAL), `never` leaves it to the page cache."""
        if log.fh is None:
            return
        mode = self.storage_config.fsync
        if mode == "always":
            # pilint: allow-blocking(hint durability is ordered with the write ack, exactly like the WAL fsync the hint stands in for)
            os.fsync(log.fh.fileno())
            log.unsynced = 0
        elif mode != "never":
            log.unsynced += 1
            if log.unsynced >= self.storage_config.fsync_batch_ops:
                # pilint: allow-blocking(batch-mode sync point, one fsync per N acked hints)
                os.fsync(log.fh.fileno())
                log.unsynced = 0

    # ------------------------------------------------------------ queries

    def pending(self, peer_id: str) -> int:
        with self._mu:
            log = self._peers.get(peer_id)
        if log is None:
            return 0
        with log.lock:
            return log.pending

    def peers_with_pending(self) -> List[str]:
        with self._mu:
            peers = list(self._peers.items())
        out = []
        for pid, log in peers:
            with log.lock:
                if log.pending:
                    out.append(pid)
        return out

    def priority_shards(self) -> Set[Tuple[str, int]]:
        """(index, shard) pairs the anti-entropy syncer should visit
        FIRST: shards with undelivered hints toward any peer, plus shards
        whose hints expired / overflowed / degraded to markers."""
        with self._mu:
            out = set(self._needs_sync)
            peers = list(self._peers.values())
        for log in peers:
            with log.lock:
                out.update(k for k, n in log.shards.items() if n > 0)
        return out

    def note_synced(self, index: str, shard: int) -> None:
        """The anti-entropy syncer repaired this shard wholesale: the
        sweep-priority flag is settled. Pending per-peer hint records
        stay — replaying them is idempotent and cheaper than surgically
        dropping mid-log records."""
        with self._mu:
            self._needs_sync.discard((index, shard))

    def prune(self, peer_id: str) -> None:
        """Drop all hint state for a node removed from the cluster."""
        with self._mu:
            log = self._peers.pop(peer_id, None)
        if log is None:
            return
        with log.lock:
            if log.fh is not None:
                log.fh.close()
                log.fh = None
            for p in (log.path, log.cursor_path):
                if p:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass

    # ------------------------------------------------------------ delivery

    def deliver_once(self, cluster, client, logger=None) -> int:
        """One delivery sweep: for every peer with pending hints whose
        breaker admits a request (cluster/health.py — an elapsed backoff
        makes this attempt the half-open probe), replay up to
        deliver-batch-bytes of records in order, checkpoint the cursor,
        and compact a fully-drained log. Returns records delivered."""
        if not self._deliver_mu.acquire(blocking=False):
            return 0  # another sweep is mid-flight; it owns the cursors
        try:
            delivered = 0
            for peer_id in self.peers_with_pending():
                node = cluster.node_by_id(peer_id)
                if node is None:
                    # Departed the membership: its hints are undeliverable.
                    self.prune(peer_id)
                    continue
                if not cluster.health.allow_request(peer_id):
                    continue
                # pilint: allow-blocking(_deliver_mu is a try-acquire single-flight busy flag, not a data lock: contenders return 0 immediately, so nothing can queue behind the replay's network sends)
                delivered += self._deliver_peer(peer_id, node, cluster.health,
                                                client, logger)
            return delivered
        finally:
            self._deliver_mu.release()

    def _deliver_peer(self, peer_id: str, node, health, client,
                      logger) -> int:
        from ..server.client import ClientError

        log = self._log(peer_id)
        with log.lock:
            self._open_locked(peer_id, log, scan=False)
            start = log.cursor
            remaining = max(0, log.size - start)
            data = b""
            if log.path and remaining and os.path.exists(log.path):
                with open(log.path, "rb") as f:
                    f.seek(start)
                    data = f.read(self.config.deliver_batch_bytes)
                    if not next(iter(decode_records(data)), None) and \
                            len(data) < remaining:
                        # One record bigger than the batch budget: read it
                        # whole rather than stalling the drain forever.
                        f.seek(start)
                        data = f.read(remaining)
        # Parse + send OUTSIDE the lock: appends land behind `start` and
        # are untouched; this store's single delivery thread owns the
        # cursor, so nothing else advances it concurrently.
        now = self.clock()
        cursor = start
        done: List[HintRecord] = []
        sent = 0
        for rec, end in decode_records(data):
            if rec.marker or now - rec.created > self.config.hint_ttl:
                if not rec.marker:
                    with self._mu:
                        self.counters["hints_expired"] += 1
                        self._needs_sync.add((rec.index, rec.shard))
                cursor = start + end
                done.append(rec)
                continue
            try:
                failpoints.fire("hint-deliver",
                                target=getattr(node, "uri", None))
                client.send_hint_ops(node, rec.index, rec.field, rec.view,
                                     rec.shard, rec.ops)
            except (ClientError, OSError) as e:
                status = getattr(e, "status", 0)
                if 400 <= status < 500:
                    # Deterministic rejection (field/index deleted since
                    # the hint was written): unreplayable, skip past it;
                    # transport success for the breaker.
                    health.record_success(peer_id)
                    with self._mu:
                        self.counters["hints_rejected"] += 1
                        self._needs_sync.add((rec.index, rec.shard))
                    cursor = start + end
                    done.append(rec)
                    continue
                health.record_failure(peer_id)
                with self._mu:
                    self.counters["deliver_errors"] += 1
                if logger is not None:
                    logger.error("hint delivery to %s failed at %s/%s: %s",
                                 peer_id, rec.index, rec.shard, e)
                break  # keep order: retry from this record next sweep
            health.record_success(peer_id)
            sent += 1
            with self._mu:
                self.counters["hints_delivered"] += 1
                self.counters["bytes_delivered"] += rec.size
                # A drained shard still gets ONE priority sweep: the
                # per-peer FIFO covers writes that SAW the pending
                # backlog, but a write racing the very first in-flight
                # failing forward can slip a newer op to the peer before
                # the hint lands behind it — replaying that hint would
                # then resurrect stale state. The verifying sweep (block
                # checksums; a no-op when nothing diverged) closes that
                # window at priority order instead of the full walk.
                self._needs_sync.add((rec.index, rec.shard))
            cursor = start + end
            done.append(rec)
        if not done:
            return 0
        with log.lock:
            log.cursor = cursor
            for rec in done:
                log.pending = max(0, log.pending - 1)
                key = (rec.index, rec.shard)
                n = log.shards.get(key, 0) - 1
                if n <= 0:
                    log.shards.pop(key, None)
                else:
                    log.shards[key] = n
            self._checkpoint_locked(log)
            if log.pending == 0 and log.cursor >= log.size and log.size:
                self._compact_locked(log)
                with self._mu:
                    self.counters["drains"] += 1
                if logger is not None:
                    logger.info("hint log for %s drained", peer_id)
        return sent

    def _checkpoint_locked(self, log: _PeerLog) -> None:
        if not log.cursor_path:
            return
        tmp = log.cursor_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(str(log.cursor))
            # pilint: allow-blocking(cursor checkpoint is ordered with the delivery it acknowledges; a stale cursor only re-delivers idempotent records)
            os.replace(tmp, log.cursor_path)
        except OSError:
            # A lost checkpoint re-delivers from the old cursor: replay
            # is idempotent, so this is latency, not corruption.
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _compact_locked(self, log: _PeerLog) -> None:
        """Fully-drained log: reset to empty instead of growing forever.
        Appends hold the same lock, so no record can land mid-reset."""
        if log.fh is not None:
            log.fh.close()
        try:
            if log.path:
                with open(log.path, "wb"):
                    pass
        except OSError:
            pass
        log.fh = open(log.path, "ab") if log.path else None
        log.size = 0
        log.cursor = 0
        log.unsynced = 0
        self._checkpoint_locked(log)

    # ----------------------------------------------------------- testing

    def records(self, peer_id: str) -> List[HintRecord]:
        """Undelivered records for one peer (tests + diagnostics)."""
        with self._mu:
            log = self._peers.get(peer_id)
        if log is None:
            return []
        with log.lock:
            if not log.path or not os.path.exists(log.path):
                return []
            with open(log.path, "rb") as f:
                f.seek(log.cursor)
                data = f.read()
        return [rec for rec, _ in decode_records(data)]

    # -------------------------------------------------------- inspection

    def snapshot(self) -> dict:
        """Counters + per-peer pending state for /debug/vars
        (`replication` group) and diagnostics."""
        with self._mu:
            counters = dict(self.counters)
            needs = len(self._needs_sync)
            peers = list(self._peers.items())
        per_peer = {}
        for pid, log in peers:
            with log.lock:
                if log.pending or log.size > log.cursor:
                    per_peer[pid] = {
                        "pending": log.pending,
                        "bytes": max(0, log.size - log.cursor),
                    }
        return {
            "writeConsistency": self.config.write_consistency,
            "peers": per_peer,
            "needsSyncShards": needs,
            **counters,
        }
