"""Online elastic rebalance: live shard migration with routing epochs.

Replaces the stop-the-world resizeJob (cluster/resize.py, the port of
reference cluster.go:1080-1423) as the default membership-change path:
the cluster keeps serving reads AND writes while shards move.

Protocol, per shard a node is gaining (receiver-driven):

  begin      the source streams a point-in-time base of each fragment
             (the roaring container section, serialized off-lock — the
             reader-tolerant form the background snapshotter relies on)
             plus the WAL position the base corresponds to; replaying
             ops past that position over the base is idempotent, so the
             handoff needs only a brief mutex hold for flush+position.
  catch-up   the receiver repeatedly pulls the WAL tail appended since
             its last position (the OP_BULK/point-op codec from the
             ingest pipeline IS the wire format) and replays it, until
             one round ships fewer than `catchup-threshold-bytes` or
             `max-catchup-rounds` is exhausted.
  freeze     once every gaining replica reports ready, the coordinator
             freezes the shard on every node streaming one of its
             fragments: writes to those fragments raise ShardMovedError
             (callers re-route/wait within the `cutover-pause-max`
             window — nothing is acked into a doomed copy), while READS
             keep serving from the frozen, fully-current source until
             the commit (the gainer has not drained the final tail yet).
  finalize   each gainer drains the now-frozen final tail, seals the
             fragment (cache rebuild + snapshot), and acks.
  commit     the coordinator broadcasts `cutover-commit` with a bumped
             routing epoch: every node's placement for that shard flips
             to the new topology. Reads/writes for every OTHER shard
             never left the old owners — a half-migrated cluster serves
             no holes.

Membership itself flips only at job completion (`rebalance-complete`),
when nodes GC fragments they no longer own — guarded by the routing
epoch: a read forwarded under a stale epoch gets a 409 and one
re-route, never an empty result from a GC'd shard.

The job is resumable: the coordinator checkpoints committed shards to
`<data_dir>/.rebalance.json` after every cutover, and a restarted
coordinator re-issues instructions for the remainder instead of
restarting from zero.

Dependency-light on purpose: this module reaches the holder/client only
through the server object handed in at runtime, so config and framing
are importable from both client and handler without cycles.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .. import failpoints
from ..errors import FragmentNotFoundError, PilosaError
from .node import Node


@dataclass
class RebalanceConfig:
    """The `[rebalance]` config section (TOML + env + CLI, config.py)."""

    # Live migration (routing epochs + WAL catch-up) vs the legacy
    # stop-the-world resizeJob. Offline mode survives as an escape hatch;
    # everything below only applies online.
    online: bool = True
    # Concurrent per-shard migration streams one receiving node runs.
    max_concurrent_streams: int = 2
    # Receiver-side throttle on migration traffic; 0 = unthrottled.
    max_bytes_per_sec: float = 0.0
    # Cutover readiness: a catch-up round shipping at most this many WAL
    # bytes means the receiver is close enough to freeze.
    catchup_threshold_bytes: int = 65536
    # Catch-up rounds before the receiver declares ready regardless (the
    # post-freeze final drain then carries whatever tail remains).
    max_catchup_rounds: int = 16
    # How long a write blocked by a mid-cutover shard re-routes/waits for
    # the commit before surfacing a clean error; freeze->commit windows
    # longer than this count as cutover_pause_overruns.
    cutover_pause_max: float = 2.0
    # Follower resize watchdog (legacy path): a node stuck RESIZING this
    # long probes the coordinator and reverts to NORMAL on the old
    # topology if the coordinator is gone or no longer resizing.
    follower_timeout: float = 30.0

    def validate(self) -> "RebalanceConfig":
        if self.max_concurrent_streams < 1:
            raise ValueError("rebalance.max-concurrent-streams must be >= 1")
        if self.max_bytes_per_sec < 0:
            raise ValueError("rebalance.max-bytes-per-sec must be >= 0")
        if self.catchup_threshold_bytes < 0:
            raise ValueError("rebalance.catchup-threshold-bytes must be >= 0")
        if self.max_catchup_rounds < 1:
            raise ValueError("rebalance.max-catchup-rounds must be >= 1")
        if self.cutover_pause_max < 0:
            raise ValueError("rebalance.cutover-pause-max must be >= 0")
        if self.follower_timeout <= 0:
            raise ValueError("rebalance.follower-timeout must be > 0")
        return self


# ------------------------------------------------------------------ framing

_FRAME_HEADER = struct.Struct("<I")


def pack_framed(header: dict, payload: bytes = b"") -> bytes:
    """Binary migration frame: <u32 header_len><json header><raw payload>.
    The base/delta payloads are raw storage bytes — base64-in-JSON would
    inflate a fragment stream by a third for nothing."""
    h = json.dumps(header).encode()
    return _FRAME_HEADER.pack(len(h)) + h + payload


def unpack_framed(data: bytes) -> Tuple[dict, bytes]:
    if len(data) < _FRAME_HEADER.size:
        raise PilosaError("truncated migration frame: missing header length")
    (n,) = _FRAME_HEADER.unpack_from(data, 0)
    end = _FRAME_HEADER.size + n
    if len(data) < end:
        raise PilosaError("truncated migration frame: short header")
    try:
        header = json.loads(data[_FRAME_HEADER.size:end])
    except ValueError as e:
        raise PilosaError(f"corrupt migration frame header: {e}") from None
    return header, data[end:]


# ------------------------------------------------------------------ stats


class RebalanceStats:
    """Counters + cutover-pause samples shared by the coordinator,
    receiver, and source roles of one node. Surfaces as the `rebalance`
    group in /debug/vars and as diagnostics aggregates."""

    _PAUSE_WINDOW = 512

    def __init__(self, clock=None):
        self.clock = clock or time.monotonic
        self._mu = threading.Lock()
        self.counters: Dict[str, int] = {
            "jobs_started": 0,
            "jobs_completed": 0,
            "jobs_aborted": 0,
            "jobs_resumed": 0,
            "fragments_moved": 0,
            "fragments_skipped": 0,  # source had no data (404 on begin)
            "bytes_streamed": 0,
            "catchup_rounds": 0,
            "catchup_restarts": 0,  # source snapshot invalidated a session
            "shards_cut_over": 0,
            "cutover_pause_overruns": 0,  # freeze->commit > cutover-pause-max
            "stale_epoch_reroutes": 0,
            # Reverse migration (abort with full restore, docs/rebalance.md)
            "jobs_revert_started": 0,
            "jobs_reverted": 0,
            "shards_reverted": 0,
        }
        self.fragments_pending = 0
        self._pauses: deque = deque(maxlen=self._PAUSE_WINDOW)
        self._freeze_at: Dict[Tuple[str, int], float] = {}

    def add(self, key: str, n: int = 1) -> None:
        with self._mu:
            self.counters[key] += n

    def set_pending(self, n: int) -> None:
        with self._mu:
            self.fragments_pending = n

    def add_pending(self, n: int) -> None:
        with self._mu:
            self.fragments_pending = max(0, self.fragments_pending + n)

    def note_freeze(self, index: str, shard: int) -> None:
        """A shard froze on this node (source side): the write-pause
        window opens now and closes when the cutover commit lands."""
        with self._mu:
            self._freeze_at[(index, shard)] = self.clock()

    def note_commit(self, index: str, shard: int,
                    pause_cap: float = 0.0) -> None:
        with self._mu:
            t0 = self._freeze_at.pop((index, shard), None)
            if t0 is None:
                return
            pause = self.clock() - t0
            self._pauses.append(pause)
            if pause_cap and pause > pause_cap:
                self.counters["cutover_pause_overruns"] += 1

    def _pause_quantile(self, q: float) -> Optional[float]:
        # Must hold _mu.
        if not self._pauses:
            return None
        ordered = sorted(self._pauses)
        return ordered[min(len(ordered) - 1, int(len(ordered) * q))]

    def snapshot(self) -> dict:
        with self._mu:
            p50 = self._pause_quantile(0.50)
            p99 = self._pause_quantile(0.99)
            out = dict(self.counters)
            out["fragments_pending"] = self.fragments_pending
            out["cutover_pause_ms_p50"] = (
                round(p50 * 1e3, 3) if p50 is not None else None)
            out["cutover_pause_ms_p99"] = (
                round(p99 * 1e3, 3) if p99 is not None else None)
            return out


def _retry_transport(fn, attempts: int = 6, backoff: float = 0.05):
    """Run `fn` retrying TRANSPORT failures (connect errors / 5xx) with
    small exponential backoff — a migration must ride out a brown-out on
    a peer link instead of aborting the whole job on one dropped
    connection. Application errors (4xx) pass straight through: they are
    deterministic and a retry would just repeat them."""
    from ..server.client import ClientError

    delay = backoff
    for attempt in range(attempts):
        try:
            return fn()
        except ClientError as e:
            if 400 <= e.status < 500:
                raise
            if attempt == attempts - 1:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


class _Throttle:
    """Token-bucket pacing for migration streams (max-bytes-per-sec).
    Shared by every stream of one instruction, so the knob bounds the
    NODE's migration ingress, not each stream's."""

    def __init__(self, rate: float):
        self.rate = rate
        self._mu = threading.Lock()
        self._debt = 0.0
        self._last = time.monotonic()

    def consume(self, nbytes: int) -> None:
        if self.rate <= 0 or nbytes <= 0:
            return
        with self._mu:
            now = time.monotonic()
            self._debt = max(0.0, self._debt - (now - self._last))
            self._last = now
            self._debt += nbytes / self.rate
            wait = self._debt
        if wait > 0.001:
            time.sleep(min(wait, 5.0))


# ------------------------------------------------------------- source side


class _Session:
    __slots__ = ("frag", "pos", "seq", "index", "field", "view", "shard",
                 "created")

    def __init__(self, frag, pos, seq, index, field, view, shard, created):
        self.frag = frag
        self.pos = pos
        self.seq = seq
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.created = created


class MigrationSource:
    """Source-side session registry behind /internal/migrate/*.

    Sessions pin their fragment's snapshot policy (Fragment._migrating)
    so the WAL layout the positions refer to stays put; an inline
    snapshot that slips through anyway (replica restore) bumps
    _snapshot_seq and the next delta answers `restart` instead of
    returning bytes from the wrong file layout.
    """

    SESSION_TTL = 600.0

    def __init__(self, server):
        self.server = server
        self._mu = threading.Lock()
        self._sessions: Dict[str, _Session] = {}

    # -------------------------------------------------------------- begin

    def begin(self, index: str, field: str, view: str, shard: int):
        """Start one fragment's migration stream. Returns (header, data):
        the serialized container base plus the WAL position it matches."""
        failpoints.fire("migrate-begin")
        frag = self.server.holder.fragment(index, field, view, shard)
        if frag is None:
            raise FragmentNotFoundError(f"{index}/{field}/{view}/{shard}")
        if frag.quarantined:
            # Same refusal as the legacy shard-ship endpoint: installing a
            # quarantined (empty, degraded) copy and then GC'ing the
            # healthy replicas would be permanent loss.
            raise PilosaError(
                "fragment is quarantined pending repair; refusing to serve "
                "as a migration source"
            )
        with frag._mu:
            frag._migrating += 1
            storage = frag.storage
            # Copy-on-write handoff (the snapshotter's off-lock serialize
            # discipline, Bitmap.cow_clone): the clone is frozen at
            # exactly the WAL position below, so the base + replayed tail
            # is complete — serializing the LIVE bitmap off-lock instead
            # can tear a racing container insert (stale header n drops
            # the tail value, and replay never restores an OLD bit).
            snap = storage.cow_clone()
            if frag._wal is not None:
                frag._wal.flush()
                pos = os.fstat(frag._wal.fileno()).st_size
            else:
                pos = None  # pathless fragment: no WAL, no deltas
            seq = frag._snapshot_seq
        try:
            data = snap.to_bytes()
        except Exception:
            self._release_fragment(frag)
            raise
        finally:
            with frag._mu:
                storage.cow_release()
        sid = uuid.uuid4().hex
        with self._mu:
            self._expire_locked()
            self._sessions[sid] = _Session(
                frag, pos, seq, index, field, view, shard, time.monotonic())
        return {"session": sid, "pos": pos, "seq": seq}, data

    # -------------------------------------------------------------- delta

    def delta(self, session_id: str, from_pos: Optional[int] = None):
        """WAL bytes appended since `from_pos` (the RECEIVER tracks its
        position and sends it, so a retried pull whose previous response
        was lost in transit re-reads the same chunk instead of skipping
        it — replay is idempotent, a skip is a lost write). Answers
        {"restart": true} when a snapshot rewrote the file under the
        session (positions no longer mean anything)."""
        failpoints.fire("migrate-delta")
        s = self._get(session_id)
        # Activity refreshes the TTL: it guards ORPHANED sessions (a dead
        # receiver), not long-running ones — a throttled multi-GiB stream
        # legitimately outlives any absolute age.
        s.created = time.monotonic()
        frag = s.frag
        pos = s.pos if from_pos is None else int(from_pos)
        if pos is None:
            return {"restart": False, "pos": None}, b""
        with frag._mu:
            if frag._snapshot_seq != s.seq:
                return {"restart": True}, b""
            if frag._wal is None:
                return {"restart": True}, b""
            frag._wal.flush()
            cur = os.fstat(frag._wal.fileno()).st_size
        if cur <= pos:
            return {"restart": False, "pos": pos}, b""
        # Read off-lock: the WAL is append-only, so [pos, cur) is stable —
        # unless a snapshot replaced the inode mid-read, which the seq
        # re-check below turns into a clean restart instead of shipping
        # bytes from the wrong layout.
        with open(frag.path, "rb") as f:
            f.seek(pos)
            data = f.read(cur - pos)
        with frag._mu:
            if frag._snapshot_seq != s.seq:
                return {"restart": True}, b""
        s.pos = cur
        return {"restart": False, "pos": cur}, data

    # ------------------------------------------------------------- freeze

    def freeze(self, index: str, shard: int) -> dict:
        """Freeze the shard on this source: every fragment of (index,
        shard) stops accepting writes (ShardMovedError; a write caught
        here re-routes/waits for the commit, so it is never acked into a
        doomed copy). The final WAL tails stay readable through the open
        sessions — frozen, hence complete. Routing deliberately does NOT
        flip here: reads keep serving from this fully-current frozen
        copy until the cutover COMMIT, because the gainer has not
        drained the final tail yet — flipping reads at freeze served
        counts missing up to a threshold's worth of acked writes."""
        frozen = 0
        t0 = time.monotonic()
        for frag in self._shard_fragments(index, shard):
            with frag._mu:
                if frag._wal is not None:
                    frag._wal.flush()
                frag._moved = True
            frozen += 1
        stats = getattr(self.server, "rebalance_stats", None)
        if stats is not None:
            stats.note_freeze(index, shard)
        return {"frozen": frozen,
                "freezeMs": round((time.monotonic() - t0) * 1e3, 3)}

    def unfreeze(self, keep=()) -> int:
        """Thaw frozen fragments after an abort: shards whose cutover
        never committed revert to this node, and a lingering _moved flag
        would leave them permanently write-dead. `keep` lists committed
        (index, shard) pairs that stay frozen (their data moved)."""
        keep = {(i, int(s)) for i, s in keep}
        thawed = 0
        for index in list(self.server.holder.indexes.values()):
            for field in list(index.fields.values()):
                for view in list(field.views.values()):
                    for frag in list(view.fragments.values()):
                        if frag._moved and (
                                frag.index, frag.shard) not in keep:
                            with frag._mu:
                                frag._moved = False
                            thawed += 1
        return thawed

    def _shard_fragments(self, index: str, shard: int):
        idx = self.server.holder.index(index)
        if idx is None:
            return []
        out = []
        for field in list(idx.fields.values()):
            for view in list(field.views.values()):
                frag = view.fragments.get(shard)
                if frag is not None:
                    out.append(frag)
        return out

    # -------------------------------------------------------------- close

    def close(self, session_ids) -> None:
        with self._mu:
            sessions = [self._sessions.pop(sid, None) for sid in session_ids]
        for s in sessions:
            if s is not None:
                self._release_fragment(s.frag)

    def abort_all(self) -> None:
        with self._mu:
            sessions, self._sessions = list(self._sessions.values()), {}
        for s in sessions:
            self._release_fragment(s.frag)

    def _get(self, session_id: str) -> _Session:
        with self._mu:
            self._expire_locked()
            s = self._sessions.get(session_id)
        if s is None:
            raise PilosaError(f"unknown migration session {session_id!r}")
        return s

    def _expire_locked(self) -> None:
        # Must hold _mu. An orphaned session (receiver died) must not pin
        # its fragment's snapshot policy forever.
        now = time.monotonic()
        for sid in [k for k, s in self._sessions.items()
                    if now - s.created > self.SESSION_TTL]:
            s = self._sessions.pop(sid)
            self._release_fragment(s.frag)

    @staticmethod
    def _release_fragment(frag) -> None:
        with frag._mu:
            frag._migrating = max(0, frag._migrating - 1)


# ----------------------------------------------------------- receiver side


class _ShardMigration:
    """Parked per-shard receiver state between `ready` and `finalize`."""

    __slots__ = ("job_id", "index", "shard", "frag_states", "coordinator")

    def __init__(self, job_id, index, shard, frag_states, coordinator):
        self.job_id = job_id
        self.index = index
        self.shard = shard
        # Per-fragment stream state: [field, view, frag, session, pos,
        # source_uri] — sources may differ per fragment.
        self.frag_states = frag_states
        self.coordinator = coordinator  # (node_id, uri) from the instruction


class RebalanceReceiver:
    """Gaining-node side: streams bases, replays catch-up tails, drains
    the frozen final delta on finalize, seals, and reports per-shard
    progress to the coordinator."""

    _RESTART_LIMIT = 3

    def __init__(self, server):
        self.server = server
        self._mu = threading.Lock()
        self._shards: Dict[Tuple[str, int], _ShardMigration] = {}
        self._cancelled: Set[str] = set()

    @property
    def _cfg(self) -> RebalanceConfig:
        return self.server.rebalance_config

    @property
    def _stats(self) -> RebalanceStats:
        return self.server.rebalance_stats

    # -------------------------------------------------------- instruction

    def handle_instruction(self, msg: dict) -> None:
        """Entry point for a `rebalance-instruction` message (runs on a
        daemon thread spawned by receive_message)."""
        server = self.server
        job_id = msg.get("jobID", "")
        with self._mu:
            # A fresh instruction restarts the job on this node — e.g. a
            # resumed job reusing the jobID of one this node saw aborted.
            self._cancelled.discard(job_id)
        server.holder.apply_schema(msg.get("schema", []))
        for index_name, max_shard in msg.get("maxShards", {}).items():
            idx = server.holder.index(index_name)
            if idx is not None:
                idx.set_remote_max_shard(max_shard)
        node_uris = msg.get("nodeURIs", {})
        moves = msg.get("moves", [])
        # Replies route to the coordinator the INSTRUCTION names: in a
        # static cluster a non-coordinator node may not have learned the
        # coordinator's flag yet (it arrives via monitor merge), and a
        # self-delivered ready would silently vanish.
        coordinator = (msg.get("coordinatorID", ""),
                       msg.get("coordinatorURI", ""))
        throttle = _Throttle(self._cfg.max_bytes_per_sec)
        sem = threading.Semaphore(self._cfg.max_concurrent_streams)
        self._stats.add_pending(sum(len(m.get("fragments", [])) for m in moves))
        for entry in moves:
            threading.Thread(
                target=self._migrate_shard,
                args=(job_id, entry, node_uris, throttle, sem, coordinator),
                name=f"migrate-{entry.get('index')}-{entry.get('shard')}",
                daemon=True,
            ).start()

    def _migrate_shard(self, job_id, entry, node_uris, throttle, sem,
                       coordinator) -> None:
        index, shard = entry["index"], int(entry["shard"])
        server = self.server
        accounted = {"n": 0}  # fragments already resolved (404 skips)
        with sem:
            if job_id in self._cancelled:
                return
            try:
                frag_states = self._stream_shard(
                    job_id, index, shard, node_uris,
                    entry.get("fragments", []), throttle, accounted)
            except Exception as e:
                self._stats.add_pending(
                    -(len(entry.get("fragments", [])) - accounted["n"]))
                self._notify_coordinator({
                    "type": "rebalance-shard-failed", "jobID": job_id,
                    "index": index, "shard": shard,
                    "nodeID": server.node.id, "error": str(e),
                }, coordinator)
                return
        with self._mu:
            self._shards[(index, shard)] = _ShardMigration(
                job_id, index, shard, frag_states, coordinator)
        self._notify_coordinator({
            "type": "rebalance-shard-ready", "jobID": job_id,
            "index": index, "shard": shard, "nodeID": server.node.id,
        }, coordinator)

    def _stream_shard(self, job_id, index, shard, node_uris, fragments,
                      throttle, accounted):
        """Base + catch-up for every fragment of one shard. Returns the
        parked [field, view, frag, session, pos, source_uri] states."""
        from ..server.client import ClientError

        client = self.server.client
        cfg = self._cfg
        frag_states = []
        for f in fragments:
            field, view = f["field"], f["view"]
            source = f["sourceNodeID"]
            source_uri = node_uris.get(source, source)
            try:
                hdr, data = _retry_transport(lambda: client.migrate_begin(
                    source_uri, index, field, view, shard))
            except ClientError as e:
                if e.status == 404:
                    # The source simply has no data for this fragment
                    # (fragment_sources enumerates the schema cartesian);
                    # nothing to move. Counted in `accounted` so a later
                    # shard failure doesn't subtract it from the pending
                    # gauge a second time.
                    self._stats.add("fragments_skipped")
                    self._stats.add_pending(-1)
                    accounted["n"] += 1
                    continue
                raise
            throttle.consume(len(data))
            self._stats.add("bytes_streamed", len(data))
            frag = self._local_fragment(index, field, view, shard)
            frag.migrate_install(data)
            # [field, view, frag, session, next WAL position to pull,
            # source uri] — the receiver owns the cursor so retried
            # pulls re-read, and each fragment remembers its source.
            frag_states.append([field, view, frag, hdr["session"],
                                hdr.get("pos"), source_uri])
        # Catch-up rounds across the shard's fragments until one round's
        # total tail is under the cutover threshold.
        for _ in range(cfg.max_catchup_rounds):
            if job_id in self._cancelled:
                raise PilosaError(f"rebalance job {job_id} aborted")
            total = 0
            for fs in frag_states:
                total += self._pull_delta(fs, index, shard, throttle)
            self._stats.add("catchup_rounds")
            if total <= cfg.catchup_threshold_bytes:
                break
        return frag_states

    def _pull_delta(self, fs, index, shard, throttle) -> int:
        """One delta pull + replay for one fragment; transparently redoes
        begin when the source's file layout changed (bounded restarts)."""
        from ..server.client import ClientError

        client = self.server.client
        field, view, frag, session, pos, source_uri = fs
        for attempt in range(self._RESTART_LIMIT + 1):
            hdr, data = _retry_transport(
                lambda s=session, p=pos: client.migrate_delta(
                    source_uri, s, from_pos=p))
            if not hdr.get("restart"):
                if data:
                    throttle.consume(len(data))
                    self._stats.add("bytes_streamed", len(data))
                    frag.migrate_apply_ops(data)
                fs[4] = hdr.get("pos", pos)
                return len(data)
            # Source snapshot invalidated the session: start this
            # fragment over from a fresh base. Closing the dead session
            # is best-effort — it expires on the source's TTL anyway.
            self._stats.add("catchup_restarts")
            try:
                client.migrate_close(source_uri, [session])
            except ClientError:
                pass
            hdr, data = _retry_transport(lambda: client.migrate_begin(
                source_uri, index, field, view, shard))
            throttle.consume(len(data))
            self._stats.add("bytes_streamed", len(data))
            frag.migrate_install(data)
            fs[3] = session = hdr["session"]
            fs[4] = pos = hdr.get("pos")
        raise PilosaError(
            f"migration of {index}/{field}/{view}/{shard} restarted "
            f"{self._RESTART_LIMIT + 1} times without converging"
        )

    def _local_fragment(self, index, field, view, shard):
        fld = self.server.holder.field(index, field)
        if fld is None:
            raise FragmentNotFoundError(f"{index}/{field} (schema not applied)")
        v = fld.create_view_if_not_exists(view)
        return v.create_fragment_if_not_exists(shard, broadcast=False)

    # ----------------------------------------------------------- finalize

    def handle_finalize(self, msg: dict) -> None:
        """Coordinator says the shard froze at the source: drain the
        final (now-static) tail, seal, flip local routing, ack."""
        from ..server.client import ClientError

        index, shard = msg["index"], int(msg["shard"])
        job_id = msg.get("jobID", "")
        with self._mu:
            st = self._shards.pop((index, shard), None)
        if st is None:
            return  # not ours / already finalized
        client = self.server.client
        try:
            for fs in st.frag_states:
                hdr, data = _retry_transport(
                    lambda s=fs[3], p=fs[4], u=fs[5]: client.migrate_delta(
                        u, s, from_pos=p))
                if hdr.get("restart"):
                    # The final drain has no base to restart from — a
                    # snapshot slipping past the migration pin here means
                    # sealing would silently drop the tail. Fail the
                    # shard; the job aborts clean (or resumes) instead.
                    raise PilosaError(
                        f"final drain of {index}/shard {shard} invalidated "
                        "by a source snapshot")
                if data:
                    self._stats.add("bytes_streamed", len(data))
                    fs[2].migrate_apply_ops(data)
                fs[2].migrate_seal()
        except (ClientError, PilosaError, OSError) as e:
            self._stats.add_pending(-len(st.frag_states))
            self._notify_coordinator({
                "type": "rebalance-shard-failed", "jobID": job_id,
                "index": index, "shard": shard,
                "nodeID": self.server.node.id, "error": str(e),
            }, st.coordinator)
            return
        # Session close is best-effort (sources expire sessions on TTL):
        # a close lost to a flaky link must not fail an already-drained,
        # already-sealed shard.
        self._close_sessions(st)
        self._stats.add("fragments_moved", len(st.frag_states))
        self._stats.add_pending(-len(st.frag_states))
        if msg.get("revert"):
            # Reverse migration (docs/rebalance.md): the shard's data
            # just streamed BACK to this prior owner. Thaw the local
            # fragments (frozen since the forward cutover — the freeze
            # is what made the copy byte-faithful) and flip routing back
            # to the prior topology for this shard.
            for fs in st.frag_states:
                fs[2]._moved = False
            self.server.cluster.revert_cutover(index, shard)
        else:
            self.server.cluster.apply_cutover(index, shard)
        self._notify_coordinator({
            "type": "rebalance-shard-done", "jobID": job_id,
            "index": index, "shard": shard, "nodeID": self.server.node.id,
        }, st.coordinator)

    def handle_abort(self, msg: dict) -> None:
        job_id = msg.get("jobID", "")
        with self._mu:
            self._cancelled.add(job_id)
            parked = [st for st in self._shards.values()
                      if st.job_id == job_id]
            for st in parked:
                self._shards.pop((st.index, st.shard), None)
        for st in parked:
            self._stats.add_pending(-len(st.frag_states))
            self._close_sessions(st)

    def _close_sessions(self, st: _ShardMigration) -> None:
        """Best-effort session close, grouped per source node."""
        from ..server.client import ClientError

        by_source: Dict[str, List[str]] = {}
        for fs in st.frag_states:
            by_source.setdefault(fs[5], []).append(fs[3])
        for source_uri, sessions in by_source.items():
            try:
                self.server.client.migrate_close(source_uri, sessions)
            except (ClientError, PilosaError):
                pass

    def _notify_coordinator(self, msg: dict, coordinator) -> None:
        """Deliver a progress message to the coordinator the instruction
        named (with transport retries: a ready/done message lost to a
        brown-out would stall the whole job)."""
        from ..server.client import ClientError

        server = self.server
        coord_id, coord_uri = coordinator
        try:
            if not coord_id or coord_id == server.node.id:
                server.receive_message(msg)
            else:
                target = Node(id=coord_id, uri=coord_uri or coord_id)
                _retry_transport(
                    lambda: server.client.send_message(target, msg))
        except (ClientError, PilosaError) as e:
            server.logger.error(
                "rebalance: cannot reach coordinator with %s: %s",
                msg.get("type"), e)


# --------------------------------------------------------- coordinator side


class RebalanceJob:
    def __init__(self, job_id: str, new_nodes: List[Node],
                 moves: Dict[str, List[dict]],
                 committed: Optional[Set[Tuple[str, int]]] = None,
                 attempt: int = 0, revert: bool = False):
        self.id = job_id
        # Reverse-migration job (docs/rebalance.md): moves stream
        # committed shards from the TARGET owners back to the PRIOR
        # owners, `committed` counts shards already flipped BACK, and
        # completion fully reverts routing instead of committing the
        # target topology. new_nodes still names the target membership —
        # the URI pool for reaching the reverse-stream sources.
        self.revert = revert
        # Delivery attempt (bumped per resume): rides instruction
        # messages so a re-sent instruction for a resumed job is not
        # swallowed by the receivers' duplicate-delivery dedupe.
        self.attempt = attempt
        # Set by _complete: a straggler shard_committed racing completion
        # must not re-persist the checkpoint after _clear_state removed it
        # (a resurrected stale checkpoint makes a restarted coordinator
        # spuriously resume a finished job).
        self.finalized = False
        self.new_nodes = new_nodes
        # node_id -> [{index, shard, fragments: [{field, view,
        # sourceNodeID}]}] — sources are PER FRAGMENT (source_ok may
        # steer different fragments of one shard to different replicas).
        self.moves = moves
        # (index, shard) -> set of gaining node ids still owing progress.
        self.gainers: Dict[Tuple[str, int], Set[str]] = {}
        # (index, shard) -> every distinct source node streaming any of
        # its fragments; ALL of them freeze at cutover (an unfrozen
        # stream source could take a write after its final drain).
        self.sources: Dict[Tuple[str, int], Set[str]] = {}
        for node_id, entries in moves.items():
            for e in entries:
                key = (e["index"], int(e["shard"]))
                self.gainers.setdefault(key, set()).add(node_id)
                srcs = self.sources.setdefault(key, set())
                for f in e.get("fragments", []):
                    srcs.add(f["sourceNodeID"])
        self.ready: Dict[Tuple[str, int], Set[str]] = {}
        self.done: Dict[Tuple[str, int], Set[str]] = {}
        self.committed: Set[Tuple[str, int]] = set(committed or ())
        self.frozen: Set[Tuple[str, int]] = set()
        # Revert jobs only: shards whose forward cutover is still in
        # force (routing to the target owners). Shrinks as reverse
        # cutovers flip shards back; THIS set is what the checkpoint
        # persists, so a resumed revert re-reverts exactly what's left.
        self.revert_remaining: Set[Tuple[str, int]] = set()
        self.lock = threading.Lock()

    def pending_shards(self) -> List[Tuple[str, int]]:
        return sorted(k for k in self.gainers if k not in self.committed)


class RebalanceCoordinator:
    """Coordinator role of the online rebalance. One job at a time, like
    the legacy ResizeCoordinator; the job checkpoint under the data dir
    makes a crashed/restarted coordinator resume instead of restart."""

    STATE_FILE = ".rebalance.json"

    def __init__(self, server):
        self.server = server
        self.job: Optional[RebalanceJob] = None
        # Autoscaler contract (cluster/autoscale.py): set before an
        # autoscale-initiated begin() so EVERY abort path of that job —
        # operator abort, shard failure, instruction delivery failure —
        # escalates to a reverse migration instead of leaving mixed
        # routing behind. Cleared when the job (or its revert) finishes.
        self.revert_on_abort = False
        self._lock = threading.Lock()
        # Serializes checkpoint writes: concurrent shard_done handlers
        # racing tmp+rename on the same path would FileNotFoundError.
        self._persist_mu = threading.Lock()

    @property
    def _stats(self) -> RebalanceStats:
        return self.server.rebalance_stats

    def _state_path(self) -> Optional[str]:
        if not self.server.data_dir:
            return None
        return os.path.join(self.server.data_dir, self.STATE_FILE)

    # -------------------------------------------------------------- begin

    def begin(self, new_nodes: List[Node],
              resume_committed: Optional[Set[Tuple[str, int]]] = None,
              job_id: Optional[str] = None, attempt: int = 0) -> None:
        from .resize import fragment_sources

        server = self.server
        cluster = server.cluster
        with self._lock:
            if self.job is not None:
                raise PilosaError("a rebalance job is already running")
            from .node import Cluster

            old = Cluster(
                node=cluster.node, nodes=list(cluster.nodes),
                replica_n=cluster.replica_n, partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            new = Cluster(
                node=cluster.node,
                nodes=sorted(new_nodes, key=lambda n: n.id),
                replica_n=cluster.replica_n, partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            schema = server.holder.schema()
            max_shards = {
                name: idx.max_shard()
                for name, idx in server.holder.indexes.items()
            }
            quarantined = {
                (f.index, f.field, f.view, f.shard)
                for f in server.holder.quarantined_fragments()
            }

            def source_ok(node_id, index, field, view, shard):
                if node_id in cluster.unavailable:
                    return False
                if node_id == server.node.id and (
                        (index, field, view, shard) in quarantined):
                    return False
                return True

            sources = fragment_sources(
                old, new, schema, max_shards, source_ok=source_ok)
            committed = set(resume_committed or ())
            moves: Dict[str, List[dict]] = {}
            for node_id, frag_list in sources.items():
                per_shard: Dict[Tuple[str, int], dict] = {}
                for f in frag_list:
                    key = (f["index"], int(f["shard"]))
                    if key in committed:
                        continue  # resumed: this shard already cut over
                    entry = per_shard.setdefault(key, {
                        "index": f["index"], "shard": int(f["shard"]),
                        "fragments": [],
                    })
                    # Source rides per FRAGMENT: source_ok may steer
                    # different fragments of one shard to different
                    # replicas (e.g. one fragment quarantined locally).
                    entry["fragments"].append(
                        {"field": f["field"], "view": f["view"],
                         "sourceNodeID": f["sourceNodeID"]})
                if per_shard:
                    moves[node_id] = [per_shard[k] for k in sorted(per_shard)]
            job = RebalanceJob(
                job_id or uuid.uuid4().hex[:8], new.nodes, moves,
                committed=committed, attempt=attempt)
            self.job = job

        self._stats.add("jobs_started")
        if resume_committed is not None:
            self._stats.add("jobs_resumed")

        if not job.gainers and not committed:
            # Nothing to move (identical placement / empty holder):
            # commit the membership change immediately.
            self._complete(job)
            return

        cluster.begin_rebalance(job.new_nodes, committed=committed)
        participants = set(job.moves)
        for srcs in job.sources.values():
            participants |= srcs
        participants = sorted(participants)
        for nid in participants:
            cluster.health.set_copy_grace(nid)
        self._persist(job)
        begin_msg = {
            "type": "rebalance-begin", "jobID": job.id,
            "attempt": job.attempt,
            "nodes": [n.to_dict() for n in cluster.nodes],
            "newNodes": [n.to_dict() for n in job.new_nodes],
            "participants": participants,
            "committed": sorted([list(k) for k in committed]),
            "epoch": cluster.routing_epoch,
        }
        self._broadcast_all(begin_msg)
        node_uris = {n.id: n.uri for n in cluster.nodes}
        node_uris.update({n.id: n.uri for n in job.new_nodes})
        for node_id, entries in job.moves.items():
            msg = {
                "type": "rebalance-instruction", "jobID": job.id,
                "attempt": job.attempt,
                "coordinatorID": cluster.node.id,
                "coordinatorURI": cluster.node.uri,
                # The snapshot fragment_sources planned the moves against
                # — recomputing here could drift (a field created
                # mid-begin would appear with no corresponding moves).
                "schema": schema,
                "maxShards": max_shards,
                "nodeURIs": node_uris,
                "moves": entries,
            }
            try:
                self._send(node_id, msg)
            except PilosaError as e:
                self.abort(f"cannot deliver rebalance instruction to "
                           f"{node_id}: {e}")
                return

        if not job.pending_shards():
            # Resume found every shard already committed: finish up.
            self._complete(job)

    def resume(self) -> bool:
        """Pick a checkpointed job back up (coordinator restart, or an
        operator retry after an abort that had already committed
        cutovers). Returns False when there is nothing to resume. A
        revert checkpoint resumes the REVERSE migration: the remaining
        still-committed shards stream back until placement is fully
        restored."""
        path = self._state_path()
        if not path or not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                state = json.load(f)
            new_nodes = [Node.from_dict(n) for n in state["newNodes"]]
            committed = {(i, int(s)) for i, s in state.get("committed", [])}
        except (OSError, ValueError, KeyError) as e:
            self.server.logger.error(
                "rebalance: unreadable checkpoint %s: %s", path, e)
            return False
        if state.get("revert"):
            self.server.logger.info(
                "rebalance: resuming REVERT job %s (%d shards still on "
                "target owners)", state.get("jobID"), len(committed))
            self.begin_revert(new_nodes, committed,
                              job_id=state.get("jobID"),
                              attempt=int(state.get("attempt", 0)) + 1)
            return True
        self.server.logger.info(
            "rebalance: resuming job %s (%d shards already committed)",
            state.get("jobID"), len(committed))
        self.begin(new_nodes, resume_committed=committed,
                   job_id=state.get("jobID"),
                   attempt=int(state.get("attempt", 0)) + 1)
        return True

    def begin_revert(self, target_nodes: List[Node],
                     still_committed: Set[Tuple[str, int]],
                     job_id: Optional[str] = None, attempt: int = 0) -> None:
        """Reverse migration (docs/rebalance.md): an aborted job left
        `still_committed` shards routed to the TARGET owners. Stream
        each one's fragments from the target owners back to the prior
        owners (the same freeze -> final-drain -> seal machinery as the
        forward direction, run against the inverted placement diff),
        flip its routing back per shard, and finish by dropping the
        overrides entirely — zero mixed routing, zero _moved freezes,
        byte-identical fragments on the restored owners."""
        from .resize import fragment_sources
        from .node import Cluster

        server = self.server
        cluster = server.cluster
        remaining = {(i, int(s)) for i, s in still_committed}
        with self._lock:
            if self.job is not None:
                raise PilosaError("a rebalance job is already running")
            prior = Cluster(
                node=cluster.node, nodes=list(cluster.nodes),
                replica_n=cluster.replica_n, partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            target = Cluster(
                node=cluster.node,
                nodes=sorted(target_nodes, key=lambda n: n.id),
                replica_n=cluster.replica_n, partition_n=cluster.partition_n,
                hasher=cluster.hasher,
            )
            schema = server.holder.schema()
            max_shards = {
                name: idx.max_shard()
                for name, idx in server.holder.indexes.items()
            }
            # The inverted placement diff: who gains each fragment going
            # target -> prior, restricted to the shards actually cut
            # over. A never-moved shard's fragments never left the prior
            # owners, so streaming them would pull from target owners
            # that may hold no data at all.
            sources = fragment_sources(target, prior, schema, max_shards)
            moves: Dict[str, List[dict]] = {}
            for node_id, frag_list in sources.items():
                per_shard: Dict[Tuple[str, int], dict] = {}
                for f in frag_list:
                    key = (f["index"], int(f["shard"]))
                    if key not in remaining:
                        continue
                    entry = per_shard.setdefault(key, {
                        "index": f["index"], "shard": int(f["shard"]),
                        "fragments": [],
                    })
                    entry["fragments"].append(
                        {"field": f["field"], "view": f["view"],
                         "sourceNodeID": f["sourceNodeID"]})
                if per_shard:
                    moves[node_id] = [per_shard[k] for k in sorted(per_shard)]
            job = RebalanceJob(
                job_id or uuid.uuid4().hex[:8], target.nodes, moves,
                attempt=attempt, revert=True)
            job.revert_remaining = set(remaining)
            self.job = job

        self._stats.add("jobs_revert_started")
        # A restarted coordinator rebuilt its membership from the
        # persisted PRIOR topology with no overrides: reinstall the
        # mixed-routing state the abort left (next=target, migrated=
        # remaining) so per-shard reverse flips have something to flip.
        if cluster.next_nodes is None:
            cluster.begin_rebalance(job.new_nodes, committed=remaining)
        self._persist(job)
        participants = set(job.moves)
        for srcs in job.sources.values():
            participants |= srcs
        participants = sorted(participants)
        begin_msg = {
            "type": "rebalance-begin", "jobID": job.id,
            "attempt": job.attempt, "revert": True,
            "nodes": [n.to_dict() for n in cluster.nodes],
            "newNodes": [n.to_dict() for n in job.new_nodes],
            "participants": participants,
            "committed": sorted([list(k) for k in remaining]),
            "epoch": cluster.routing_epoch,
        }
        self._broadcast_all(begin_msg)
        # Shards whose owner sets don't differ between the two
        # placements (possible at small replica overlaps) need no
        # stream: their data never moved, so routing flips back now.
        for key in sorted(remaining - set(job.gainers)):
            cluster.revert_cutover(key[0], key[1])
            with job.lock:
                job.revert_remaining.discard(key)
            self._stats.add("shards_reverted")
            self._persist(job)
            self._broadcast_all({
                "type": "cutover-revert", "jobID": job.id,
                "index": key[0], "shard": key[1],
                "epoch": cluster.routing_epoch,
            })
        node_uris = {n.id: n.uri for n in cluster.nodes}
        node_uris.update({n.id: n.uri for n in job.new_nodes})
        for node_id, entries in job.moves.items():
            msg = {
                "type": "rebalance-instruction", "jobID": job.id,
                "attempt": job.attempt,
                "coordinatorID": cluster.node.id,
                "coordinatorURI": cluster.node.uri,
                "schema": schema,
                "maxShards": max_shards,
                "nodeURIs": node_uris,
                "moves": entries,
            }
            try:
                self._send(node_id, msg)
            except PilosaError as e:
                self.abort(f"cannot deliver revert instruction to "
                           f"{node_id}: {e}")
                return
        if not job.pending_shards():
            self._complete_revert(job)

    # ----------------------------------------------------------- progress

    def shard_ready(self, msg: dict) -> None:
        from ..server.client import ClientError

        job = self._job_for(msg)
        if job is None:
            return
        key = (msg["index"], int(msg["shard"]))
        with job.lock:
            job.ready.setdefault(key, set()).add(msg.get("nodeID", ""))
            all_ready = job.ready[key] >= job.gainers.get(key, set())
            if not all_ready or key in job.frozen:
                return
            job.frozen.add(key)
        # Every gaining replica is converged: freeze the shard at EVERY
        # node streaming one of its fragments (an unfrozen stream source
        # could still take a write after its final drain), then tell the
        # gainers to drain the final tails.
        for source_id in sorted(job.sources.get(key, ())):
            source = self._node_uri(job, source_id)
            try:
                if source_id == self.server.node.id:
                    self.server.migration_source.freeze(key[0], key[1])
                else:
                    _retry_transport(
                        lambda s=source: self.server.client.migrate_freeze(
                            s, key[0], key[1]))
            except (ClientError, PilosaError) as e:
                self.abort(f"freeze of {key[0]}/shard {key[1]} on "
                           f"{source_id} failed: {e}")
                return
        for node_id in sorted(job.gainers.get(key, ())):
            try:
                self._send(node_id, {
                    "type": "rebalance-finalize", "jobID": job.id,
                    "index": key[0], "shard": key[1],
                    "revert": job.revert,
                })
            except PilosaError as e:
                self.abort(f"cannot deliver finalize for {key} to "
                           f"{node_id}: {e}")
                return

    def shard_done(self, msg: dict) -> None:
        job = self._job_for(msg)
        if job is None:
            return
        key = (msg["index"], int(msg["shard"]))
        with job.lock:
            job.done.setdefault(key, set()).add(msg.get("nodeID", ""))
            if job.done[key] < job.gainers.get(key, set()):
                return
            if key in job.committed:
                return
            job.committed.add(key)
            job.revert_remaining.discard(key)
            all_done = not job.pending_shards()
        cluster = self.server.cluster
        if job.revert:
            # Reverse migration: the shard's data is back on its prior
            # owners — flip routing BACK and tell everyone.
            cluster.revert_cutover(key[0], key[1])
            self._stats.add("shards_reverted")
            self._persist(job)
            self._broadcast_all({
                "type": "cutover-revert", "jobID": job.id,
                "index": key[0], "shard": key[1],
                "epoch": cluster.routing_epoch,
            })
            if all_done:
                self._complete_revert(job)
            return
        cluster.apply_cutover(key[0], key[1])
        # Close the write-pause sample when the COORDINATOR was the
        # shard's source: the broadcast below skips self, so the
        # 'cutover-commit' receive path never runs here (no-op when this
        # node recorded no freeze for the shard).
        self._stats.note_commit(
            key[0], key[1],
            pause_cap=self.server.rebalance_config.cutover_pause_max)
        self._stats.add("shards_cut_over")
        self._persist(job)
        self._broadcast_all({
            "type": "cutover-commit", "jobID": job.id,
            "index": key[0], "shard": key[1],
            "epoch": cluster.routing_epoch,
        })
        if all_done:
            self._complete(job)

    def shard_failed(self, msg: dict) -> None:
        job = self._job_for(msg)
        if job is None:
            return
        self.abort(
            f"node {msg.get('nodeID')} failed migrating "
            f"{msg.get('index')}/shard {msg.get('shard')}: "
            f"{msg.get('error')}"
        )

    def _job_for(self, msg: dict) -> Optional[RebalanceJob]:
        with self._lock:
            job = self.job
        if job is None or (msg.get("jobID") and msg["jobID"] != job.id):
            return None
        return job

    # ----------------------------------------------------- complete/abort

    def _complete(self, job: RebalanceJob) -> None:
        with self._lock:
            if self.job is not job:
                return
            self.job = None
            job.finalized = True
            self.revert_on_abort = False
        server = self.server
        cluster = server.cluster
        old_nodes = list(cluster.nodes)
        cluster.commit_topology(job.new_nodes)
        cluster.health.clear_copy_grace()
        live = {n.id for n in cluster.nodes}
        cluster.health.prune_absent(live)
        for nid in [k for k in server._probe_failures if k not in live]:
            del server._probe_failures[nid]
        server.topology.save(cluster.nodes)
        self._clear_state()
        self._stats.add("jobs_completed")
        msg = {
            "type": "rebalance-complete", "jobID": job.id,
            "attempt": job.attempt,
            "nodes": [n.to_dict() for n in job.new_nodes],
            "epoch": cluster.routing_epoch,
        }
        self._broadcast_all(msg, extra_nodes=old_nodes)
        # Post-cutover GC, epoch-guarded: the routing epoch advanced with
        # the commit, so a read forwarded under the old epoch 409s and
        # re-routes instead of reading the GC'd hole.
        from .topology import HolderCleaner

        removed = HolderCleaner(server).clean_holder()
        if removed:
            server.logger.info(
                "rebalance %s: holder cleaner removed %d fragments",
                job.id, len(removed))
        # Thaw fragments still frozen after the cleaner: with replicas>=2
        # the coordinator can be a migration SOURCE for a shard it keeps
        # owning as a replica — the cleaner keeps that fragment, and a
        # lingering _moved flag would leave it permanently write-dead.
        # (Followers do the same in _adopt_committed_topology.)
        thawed = server.migration_source.unfreeze(keep=())
        if thawed:
            server.logger.info(
                "rebalance %s: thawed %d frozen fragments", job.id, thawed)
        server.logger.info("rebalance job %s complete: %d nodes, epoch %d",
                           job.id, len(cluster.nodes), cluster.routing_epoch)

    def _complete_revert(self, job: RebalanceJob) -> None:
        """Reverse migration finished: every committed shard streamed
        back and flipped. Drop the overrides entirely (full revert to
        the prior topology), thaw everything, clear the checkpoint, and
        broadcast the same rebalance-abort-with-empty-committed the
        followers' full-revert path already handles."""
        with self._lock:
            if self.job is not job:
                return
            self.job = None
            job.finalized = True
            self.revert_on_abort = False
        server = self.server
        cluster = server.cluster
        server.rebalance_receiver.handle_abort(
            {"jobID": job.id, "committed": []})
        server.migration_source.abort_all()
        server.migration_source.unfreeze(keep=())
        cluster.abort_rebalance(committed=set())
        cluster.health.clear_copy_grace()
        self._clear_state()
        self._stats.add("jobs_reverted")
        self._broadcast_all({
            "type": "rebalance-abort", "jobID": job.id,
            "attempt": job.attempt,
            "reason": "reverse migration complete",
            "committed": [],
        }, extra_nodes=job.new_nodes)
        # Members drop fragments for shards they no longer own on the
        # restored topology (the forward copies on surviving members);
        # epoch-guarded like every post-routing-change GC.
        from .topology import HolderCleaner

        removed = HolderCleaner(server).clean_holder()
        if removed:
            server.logger.info(
                "revert %s: holder cleaner removed %d fragments",
                job.id, len(removed))
        server.logger.info(
            "rebalance job %s fully reverted: placement restored, epoch %d",
            job.id, cluster.routing_epoch)

    def abort(self, reason: str, revert: bool = False) -> None:
        """Abort the running job. With revert=False (operator default),
        committed cutovers keep their mixed routing and resume()
        finishes the job FORWARD. With revert=True (the autoscaler's
        contract: an aborted scale job must leave no trace), a reverse
        migration starts immediately after the abort settles, streaming
        committed shards back until the prior placement is fully
        restored."""
        with self._lock:
            job, self.job = self.job, None
            # An autoscale job's abort always reverts (no operator to
            # resume it forward); consult the flag under the lock so a
            # racing begin() can't re-arm it mid-abort.
            revert = revert or self.revert_on_abort
        if job is None:
            return
        server = self.server
        server.logger.error("rebalance job %s aborted: %s", job.id, reason)
        self._stats.add("jobs_aborted")
        if job.revert:
            # Aborting a revert job: per-shard reverse flips already
            # applied stand; what's left stays on the target owners
            # (mixed routing) and the revert checkpoint lets resume()
            # finish the restore.
            with job.lock:
                still = set(job.revert_remaining)
        else:
            still = set(job.committed)
        committed = sorted([list(k) for k in still])
        # The coordinator never receives its own broadcast: apply the
        # local side of the abort here too (it may be a source with
        # frozen fragments, and a receiver with parked streams).
        server.rebalance_receiver.handle_abort(
            {"jobID": job.id, "committed": committed})
        server.migration_source.abort_all()
        server.migration_source.unfreeze(keep=still)
        reverted = server.cluster.abort_rebalance(committed=still)
        server.cluster.health.clear_copy_grace()
        if reverted:
            job.finalized = True
            self._clear_state()
        else:
            # Cutovers already committed cannot be un-committed without a
            # reverse migration: keep the mixed routing AND the checkpoint
            # so resume() can finish the job (forward, or by completing
            # the revert).
            self._persist(job)
            server.logger.error(
                "rebalance job %s aborted after %d cutovers: mixed routing "
                "kept; resume() finishes the job %s",
                job.id, len(still),
                "revert" if job.revert or revert else "forward")
        self._broadcast_all({
            "type": "rebalance-abort", "jobID": job.id,
            "attempt": job.attempt, "reason": reason,
            "committed": committed,
        }, extra_nodes=job.new_nodes)
        if revert and not reverted and not job.revert:
            # Full-restore contract: stream every committed shard back.
            # Runs AFTER the abort broadcast so every node has settled
            # into the mixed-routing state the reverse job starts from.
            self.begin_revert(job.new_nodes, still,
                              attempt=job.attempt + 1)

    # ------------------------------------------------------------ helpers

    def _persist(self, job: RebalanceJob) -> None:
        path = self._state_path()
        if not path:
            return
        with self._persist_mu:
            if job.finalized:
                return
            with job.lock:
                state = {
                    "jobID": job.id,
                    "attempt": job.attempt,
                    "newNodes": [n.to_dict() for n in job.new_nodes],
                    "committed": sorted([list(k) for k in job.committed]),
                }
                if job.revert:
                    # A revert checkpoint records what still needs to
                    # flip BACK (shrinking), not what flipped forward:
                    # resume() re-reverts exactly the remainder.
                    state["revert"] = True
                    state["committed"] = sorted(
                        [list(k) for k in job.revert_remaining])
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
            # pilint: allow-blocking(_persist_mu exists only to serialize this tiny checkpoint write; no query-path lock is held)
            os.replace(tmp, path)

    def _clear_state(self) -> None:
        path = self._state_path()
        if not path:
            return
        # Hold _persist_mu so an in-flight _persist finishes its write
        # BEFORE the remove (and any later one sees job.finalized): the
        # checkpoint cannot be resurrected after this returns.
        with self._persist_mu:
            if os.path.exists(path):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def _node_uri(self, job: RebalanceJob, node_id: str) -> str:
        for n in list(self.server.cluster.nodes) + list(job.new_nodes):
            if n.id == node_id:
                return n.uri
        return node_id

    def _send(self, node_id: str, msg: dict) -> None:
        """Deliver a job message to one node (self-delivery dispatches
        through receive_message, which threads the heavy handlers)."""
        server = self.server
        if node_id == server.node.id:
            server.receive_message(msg)
            return
        job = self.job
        target = None
        pool = list(server.cluster.nodes)
        if job is not None:
            pool += list(job.new_nodes)
        for n in pool:
            if n.id == node_id:
                target = n
                break
        if target is None:
            raise PilosaError(f"unknown rebalance target node {node_id}")
        _retry_transport(lambda: server.client.send_message(target, msg))

    def _broadcast_all(self, msg: dict, extra_nodes=()) -> None:
        """Broadcast to the union of current members, the job's target
        membership, and `extra_nodes` — mid-job the joiner is not in
        cluster.nodes yet, and at completion the leaver already isn't."""
        from ..server.client import ClientError

        server = self.server
        seen = {server.node.id}
        job = self.job
        pool = list(server.cluster.nodes) + list(extra_nodes)
        if job is not None:
            pool += list(job.new_nodes)
        for node in pool:
            if node.id in seen:
                continue
            seen.add(node.id)
            try:
                _retry_transport(
                    lambda n=node: server.client.send_message(n, msg),
                    attempts=3)
            except (ClientError, PilosaError) as e:
                server.logger.error(
                    "rebalance broadcast %s to %s failed: %s",
                    msg.get("type"), node.id, e)
