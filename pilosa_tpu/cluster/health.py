"""Per-peer fault-tolerance state: circuit breakers, retry budget, hedging.

Replaces the binary ``Cluster.unavailable`` set with structured per-peer
health shared by the executor (routing, replica retries, hedged reads),
the member monitor (probe damping), the internal client, and the syncer.
Three mechanisms, modeled on the Finagle/Envoy outlier-ejection designs:

  circuit breaker   CLOSED -> OPEN after `breaker_failures` consecutive
                    transport failures; OPEN -> HALF_OPEN once an
                    exponentially-growing backoff elapses; exactly ONE
                    request is admitted as the half-open probe, and its
                    outcome decides re-close vs re-open (doubled backoff).
                    While OPEN, routing skips the peer entirely, so a
                    dead peer costs zero connect timeouts between probes.

  retry budget      a token bucket gating the executor's replica re-map:
                    each successful remote request refills `retry_refill`
                    tokens (capped at `retry_budget`), each re-mapped
                    shard batch spends one. During a brown-out the budget
                    drains and further retries fail cleanly instead of
                    amplifying load onto the surviving replicas.

  hedged reads      after a per-peer hedge delay (fixed, or the rolling
                    p99 of that peer's recent latencies) the same shard
                    batch is fired at a replica and the first good
                    response wins. Hedge volume is capped at
                    `hedge_max_fraction` of remote traffic.

Dependency-light on purpose (stdlib only): the executor and Cluster use
it without pulling in networking, and tests inject a fake clock.
"""

from __future__ import annotations

import threading
from collections import deque
from collections.abc import MutableSet
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

# Breaker states (names surface in /debug/vars and diagnostics).
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


@dataclass
class ResilienceConfig:
    """The `[resilience]` config section (TOML + env + CLI, config.py)."""

    # Consecutive transport failures before the breaker opens. The default
    # of 1 preserves the reference's mark-dead-on-first-failure routing
    # (executor.go:1498-1508); raise it on lossy networks where a single
    # failed dial is weak evidence.
    breaker_failures: int = 1
    # OPEN -> HALF_OPEN delay: starts at `breaker_backoff` seconds and
    # doubles on every failed half-open probe, capped at the max.
    breaker_backoff: float = 1.0
    breaker_backoff_max: float = 30.0
    # A half-open probe that never reports (caller died mid-request) is
    # treated as failed after this long, so a lost probe cannot wedge the
    # breaker HALF_OPEN forever.
    probe_ttl: float = 60.0
    # Retry token bucket: capacity, and tokens refilled per successful
    # remote request. 0 capacity disables gating (unlimited retries).
    retry_budget: float = 10.0
    retry_refill: float = 0.1
    # Hedged remote reads: fixed delay in seconds, or 0 for the rolling
    # per-peer p99; volume capped at a fraction of remote requests
    # (0 disables hedging entirely).
    hedge_delay: float = 0.0
    hedge_max_fraction: float = 0.05
    # Floor/fallback for the adaptive delay: used while a peer has too few
    # latency samples for a meaningful p99, and as the minimum even after.
    hedge_min_delay: float = 0.02
    # Device-plane breakers (parallel/device_health.py, docs/
    # fault-tolerance.md). Consecutive engine-dispatch failures (any
    # signature) before the PLANE breaker opens and the engine demotes to
    # host execution; the OPEN -> HALF_OPEN backoff doubles per failed
    # probe like the peer breaker, capped at the max. `probe_ttl` above is
    # shared: a claimed device probe that never reports expires the same
    # way a lost peer probe does.
    device_breaker_failures: int = 3
    device_breaker_backoff: float = 2.0
    device_breaker_backoff_max: float = 60.0
    # Consecutive failures of ONE query signature's fused program before
    # that signature alone is quarantined to the per-shard XLA walk.
    device_sig_failures: int = 2
    device_sig_backoff: float = 10.0
    # Collective-plane breakers (parallel/device_health.py
    # CollectivePlaneHealth, docs/multichip.md): consecutive collective
    # failures — barrier timeouts, descriptor-broadcast losses — before
    # the plane (or one mesh slice) stops being offered queries and
    # full-index reads fall back to the HTTP fan-out instantly instead
    # of waiting out a barrier per query. OPEN -> HALF_OPEN doubles from
    # `collective-breaker-backoff` per failed probe, capped at the max;
    # `probe_ttl` above is shared.
    collective_breaker_failures: int = 2
    collective_breaker_backoff: float = 2.0
    collective_breaker_backoff_max: float = 60.0

    def validate(self) -> "ResilienceConfig":
        if self.breaker_failures < 1:
            raise ValueError("resilience.breaker-failures must be >= 1")
        if self.breaker_backoff <= 0:
            raise ValueError("resilience.breaker-backoff must be > 0")
        if self.breaker_backoff_max < self.breaker_backoff:
            raise ValueError(
                "resilience.breaker-backoff-max must be >= breaker-backoff")
        if not 0.0 <= self.hedge_max_fraction <= 1.0:
            raise ValueError(
                "resilience.hedge-max-fraction must be in [0, 1]")
        if self.retry_budget < 0 or self.retry_refill < 0:
            raise ValueError("resilience retry knobs must be >= 0")
        if self.device_breaker_failures < 1 or self.device_sig_failures < 1:
            raise ValueError(
                "resilience.device-breaker-failures / device-sig-failures "
                "must be >= 1")
        if self.device_breaker_backoff <= 0 or self.device_sig_backoff <= 0:
            raise ValueError("resilience device backoffs must be > 0")
        if self.device_breaker_backoff_max < self.device_breaker_backoff:
            raise ValueError(
                "resilience.device-breaker-backoff-max must be >= "
                "device-breaker-backoff")
        if self.collective_breaker_failures < 1:
            raise ValueError(
                "resilience.collective-breaker-failures must be >= 1")
        if self.collective_breaker_backoff <= 0:
            raise ValueError(
                "resilience.collective-breaker-backoff must be > 0")
        if self.collective_breaker_backoff_max < self.collective_breaker_backoff:
            raise ValueError(
                "resilience.collective-breaker-backoff-max must be >= "
                "collective-breaker-backoff")
        return self


# Rolling latency window per peer: enough samples for a stable p99
# without unbounded growth under heavy traffic.
_LATENCY_WINDOW = 128
# Minimum samples before the adaptive p99 is trusted over the floor.
_MIN_SAMPLES = 8


class _Peer:
    __slots__ = (
        "state", "consec_failures", "opened_at", "backoff", "probe_at",
        "latencies", "open_count",
    )

    def __init__(self):
        self.state = CLOSED
        self.consec_failures = 0
        self.opened_at = 0.0
        self.backoff = 0.0  # current OPEN -> HALF_OPEN delay
        self.probe_at = 0.0  # when the half-open probe was claimed
        self.latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self.open_count = 0


class HealthRegistry:
    """Thread-safe per-peer breaker/budget/latency state for one node's
    view of its cluster. `clock` is injectable for deterministic tests."""

    # A peer under migration copy load (cluster/rebalance.py participants)
    # gets this multiplier on breaker_failures before its breaker opens —
    # slow responses while streaming gigabytes are expected load, not
    # death, and marking a joining node dead mid-copy aborts the join.
    COPY_GRACE_MULT = 4
    COPY_GRACE_TTL = 600.0

    def __init__(self, config: Optional[ResilienceConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        import time

        self.config = config or ResilienceConfig()
        self.clock = clock or time.monotonic
        self._mu = threading.Lock()
        self._peers: Dict[str, _Peer] = {}
        # node id -> grace deadline (clock units). Set by the rebalance
        # coordinator's begin broadcast, cleared at complete/abort; the
        # TTL bounds a lost clear.
        self._copy_grace: Dict[str, float] = {}
        # Retry token bucket (one bucket per node, not per peer: the thing
        # being protected is the SURVIVORS' aggregate load).
        self._retry_tokens = float(self.config.retry_budget)
        self.counters: Dict[str, int] = {
            "requests": 0,
            "breaker_opened": 0,
            "breaker_closed": 0,
            "breaker_short_circuits": 0,  # sends skipped while OPEN
            "half_open_probes": 0,
            "retries_spent": 0,
            "retries_denied": 0,
            "hedges_fired": 0,
            "hedges_won": 0,
            "hedges_suppressed": 0,
        }

    def configure(self, config: ResilienceConfig,
                  clock: Optional[Callable[[], float]] = None) -> None:
        """Install server config onto a registry built with defaults
        (Cluster constructs one eagerly so library use needs no wiring)."""
        with self._mu:
            self.config = config
            if clock is not None:
                self.clock = clock
            self._retry_tokens = float(config.retry_budget)

    def _peer(self, node_id: str) -> _Peer:
        p = self._peers.get(node_id)
        if p is None:
            p = self._peers[node_id] = _Peer()
        return p

    # ------------------------------------------------------------- breaker

    def is_down(self, node_id: str) -> bool:
        """True while the peer's breaker is not CLOSED. Routing excludes
        down peers; re-admission happens only through a half-open probe
        (allow_request) or an explicit force_up (member monitor)."""
        with self._mu:
            p = self._peers.get(node_id)
            return p is not None and p.state != CLOSED

    def down_ids(self) -> List[str]:
        with self._mu:
            return [nid for nid, p in self._peers.items() if p.state != CLOSED]

    def allow_request(self, node_id: str) -> bool:
        """Breaker gate for one outbound request to `node_id`.

        CLOSED -> True. OPEN with backoff elapsed -> atomically claims the
        HALF_OPEN probe slot and returns True (this request IS the probe);
        the caller must report the outcome via record_success /
        record_failure. OPEN within backoff, or HALF_OPEN with a live
        probe in flight -> False (skip, zero connect attempts)."""
        now = self.clock()
        with self._mu:
            p = self._peers.get(node_id)
            if p is None or p.state == CLOSED:
                return True
            if p.state == HALF_OPEN and now - p.probe_at > self.config.probe_ttl:
                # The claimed probe never reported: count it failed.
                self._reopen(p, now)
            if p.state == OPEN and now - p.opened_at >= p.backoff:
                p.state = HALF_OPEN
                p.probe_at = now
                self.counters["half_open_probes"] += 1
                return True
            self.counters["breaker_short_circuits"] += 1
            return False

    def probe_due(self, node_id: str) -> bool:
        """Like allow_request but WITHOUT claiming the probe slot: a
        side-effect-free check for inspection (tests, tooling). The
        member monitor deliberately does NOT gate its probes on this —
        its consecutive-failure streak feeds coordinator failover, which
        must keep counting while a dead coordinator's breaker backs off."""
        now = self.clock()
        with self._mu:
            p = self._peers.get(node_id)
            if p is None or p.state == CLOSED:
                return True
            if p.state == HALF_OPEN:
                return now - p.probe_at > self.config.probe_ttl
            return now - p.opened_at >= p.backoff

    def record_success(self, node_id: str,
                       latency: Optional[float] = None) -> None:
        """A request to the peer completed: close a half-open breaker,
        reset failure streaks, refill the retry budget, record latency."""
        with self._mu:
            self.counters["requests"] += 1
            p = self._peer(node_id)
            p.consec_failures = 0
            if p.state != CLOSED:
                p.state = CLOSED
                p.backoff = 0.0
                self.counters["breaker_closed"] += 1
            if latency is not None:
                p.latencies.append(latency)
            cap = float(self.config.retry_budget)
            if cap:
                self._retry_tokens = min(
                    cap, self._retry_tokens + self.config.retry_refill)

    def set_copy_grace(self, node_id: str,
                       ttl: Optional[float] = None) -> None:
        """Mark a peer as a live-migration participant: its breaker needs
        COPY_GRACE_MULT x the usual consecutive failures to open, and the
        member monitor damps its probe threshold the same way."""
        with self._mu:
            self._copy_grace[node_id] = self.clock() + (
                ttl if ttl is not None else self.COPY_GRACE_TTL)

    def clear_copy_grace(self, node_id: Optional[str] = None) -> None:
        with self._mu:
            if node_id is None:
                self._copy_grace.clear()
            else:
                self._copy_grace.pop(node_id, None)

    def in_copy_grace(self, node_id: str) -> bool:
        with self._mu:
            return self._grace_active(node_id)

    def _grace_active(self, node_id: str) -> bool:
        # Must hold _mu.
        deadline = self._copy_grace.get(node_id)
        if deadline is None:
            return False
        if self.clock() > deadline:
            del self._copy_grace[node_id]
            return False
        return True

    def record_failure(self, node_id: str) -> None:
        """A transport-level failure (connect/5xx/corrupt body) talking to
        the peer: advance the breaker. A failed half-open probe re-opens
        with doubled backoff; `breaker_failures` consecutive failures open
        a closed breaker (scaled up while the peer is under migration
        copy-load grace)."""
        now = self.clock()
        with self._mu:
            p = self._peer(node_id)
            p.consec_failures += 1
            threshold = self.config.breaker_failures
            if self._grace_active(node_id):
                threshold *= self.COPY_GRACE_MULT
            if p.state == HALF_OPEN:
                self._reopen(p, now)
            elif p.state == CLOSED and (
                p.consec_failures >= threshold
            ):
                p.state = OPEN
                p.opened_at = now
                p.backoff = self.config.breaker_backoff
                p.open_count += 1
                self.counters["breaker_opened"] += 1

    def _reopen(self, p: _Peer, now: float) -> None:
        # Must hold _mu. Failed half-open probe: back off harder.
        p.state = OPEN
        p.opened_at = now
        p.backoff = min(
            max(p.backoff, self.config.breaker_backoff) * 2,
            self.config.breaker_backoff_max,
        )
        p.open_count += 1
        self.counters["breaker_opened"] += 1

    def force_down(self, node_id: str) -> None:
        """Open the peer's breaker NOW (mark_unavailable compat: the
        member monitor or an operator declared it dead)."""
        now = self.clock()
        with self._mu:
            p = self._peer(node_id)
            if p.state == CLOSED:
                p.state = OPEN
                p.opened_at = now
                p.backoff = self.config.breaker_backoff
                p.open_count += 1
                self.counters["breaker_opened"] += 1
            elif p.state == HALF_OPEN:
                self._reopen(p, now)
            # Already OPEN: leave opened_at/backoff alone — re-marking a
            # known-dead peer must not postpone its next probe.

    def force_up(self, node_id: str) -> None:
        """Close the peer's breaker NOW (mark_available compat: a live
        /status probe is direct evidence of recovery)."""
        with self._mu:
            p = self._peers.get(node_id)
            if p is None:
                return
            p.consec_failures = 0
            if p.state != CLOSED:
                p.state = CLOSED
                p.backoff = 0.0
                self.counters["breaker_closed"] += 1

    def prune(self, node_id: str) -> None:
        """Drop all state for a removed node, so a later re-add with the
        same id starts with a clean slate."""
        with self._mu:
            self._peers.pop(node_id, None)
            self._copy_grace.pop(node_id, None)

    def prune_absent(self, live_ids) -> None:
        """Drop state for peers no longer in the membership (wholesale
        cluster-status replacement, resize completion)."""
        live = set(live_ids)
        with self._mu:
            for nid in [n for n in self._peers if n not in live]:
                del self._peers[nid]
            for nid in [n for n in self._copy_grace if n not in live]:
                del self._copy_grace[nid]

    # -------------------------------------------------------- retry budget

    def try_spend_retry(self) -> bool:
        """Spend one retry token. False means the budget is exhausted and
        the caller should fail cleanly instead of re-mapping onto
        survivors. A zero-capacity budget disables gating."""
        with self._mu:
            if not self.config.retry_budget:
                self.counters["retries_spent"] += 1
                return True
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                self.counters["retries_spent"] += 1
                return True
            self.counters["retries_denied"] += 1
            return False

    def retry_tokens(self) -> float:
        with self._mu:
            return self._retry_tokens

    # ------------------------------------------------------------- hedging

    def hedge_enabled(self) -> bool:
        return self.config.hedge_max_fraction > 0.0

    def hedge_delay(self, node_id: str) -> float:
        """Seconds to wait on the primary before firing the hedge: the
        configured fixed delay, or the peer's rolling p99 (floored)."""
        if self.config.hedge_delay > 0:
            return self.config.hedge_delay
        with self._mu:
            p = self._peers.get(node_id)
            if p is None or len(p.latencies) < _MIN_SAMPLES:
                return self.config.hedge_min_delay
            ordered = sorted(p.latencies)
            p99 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
        return max(p99, self.config.hedge_min_delay)

    def allow_hedge(self) -> bool:
        """Volume cap: hedges may be at most `hedge_max_fraction` of
        remote requests. Counts the hedge when allowed."""
        with self._mu:
            frac = self.config.hedge_max_fraction
            if frac <= 0.0:
                return False
            budget = frac * max(self.counters["requests"], 1)
            if self.counters["hedges_fired"] + 1 > max(budget, 1):
                self.counters["hedges_suppressed"] += 1
                return False
            self.counters["hedges_fired"] += 1
            return True

    def note_hedge_won(self) -> None:
        with self._mu:
            self.counters["hedges_won"] += 1

    # ---------------------------------------------------------- inspection

    def state(self, node_id: str) -> str:
        with self._mu:
            p = self._peers.get(node_id)
            return p.state if p is not None else CLOSED

    def snapshot(self) -> dict:
        """Counters + per-peer state for /debug/vars and diagnostics."""
        with self._mu:
            peers = {}
            for nid, p in self._peers.items():
                peers[nid] = {
                    "state": p.state,
                    "consecFailures": p.consec_failures,
                    "backoff": round(p.backoff, 3),
                    "openCount": p.open_count,
                    "latencySamples": len(p.latencies),
                }
            now = self.clock()
            return {
                "peers": peers,
                "retryTokens": round(self._retry_tokens, 2)
                if self.config.retry_budget else None,
                "copyGracePeers": sorted(
                    nid for nid, dl in self._copy_grace.items() if now <= dl
                ),
                **dict(self.counters),
            }


class DownView(MutableSet):
    """Set-like facade over the registry's breaker state, kept as
    ``Cluster.unavailable`` so every existing membership check, test, and
    the reference-shaped routing code keep working: `id in unavailable`
    means "breaker not closed", `add`/`discard` force the breaker."""

    def __init__(self, health: HealthRegistry):
        self._health = health

    def __contains__(self, node_id) -> bool:
        return self._health.is_down(node_id)

    def __iter__(self):
        return iter(self._health.down_ids())

    def __len__(self) -> int:
        return len(self._health.down_ids())

    def add(self, node_id) -> None:
        self._health.force_down(node_id)

    def discard(self, node_id) -> None:
        self._health.force_up(node_id)

    def __repr__(self) -> str:  # debugging aid
        return f"DownView({set(self._health.down_ids())!r})"
