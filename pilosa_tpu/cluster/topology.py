"""Topology persistence + post-resize holder cleanup.

Port of the reference's `.topology` checkpoint (cluster.go:1442-1580) and
holderCleaner (holder.go:777-835): the node set survives restarts, and
after a resize each node garbage-collects fragments for shards it no
longer owns.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from .node import Node


class Topology:
    def __init__(self, path: Optional[str]):
        self.path = path
        self.node_ids: List[str] = []
        # Full node records (id + uri) so a restarting coordinator can dial
        # prior members to solicit rejoins instead of wedging in STARTING
        # (the reference recovers via memberlist re-join events,
        # cluster.go:1615 nodeJoin; without gossip we must dial out).
        self.nodes: List[Node] = []

    @classmethod
    def load(cls, path: Optional[str]) -> "Topology":
        t = cls(path)
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            t.node_ids = data.get("nodeIDs", [])
            t.nodes = [Node.from_dict(n) for n in data.get("nodes", [])]
            if t.node_ids and not t.nodes:
                # Legacy topology format persisted only nodeIDs. In static
                # mode node id == URI (server._join_cluster), so the ids are
                # dialable and STARTING recovery (_solicit_topology_members)
                # keeps working for clusters whose checkpoint predates the
                # full-record format.
                t.nodes = [Node(id=nid, uri=nid) for nid in t.node_ids]
        return t

    def save(self, nodes: List[Node]) -> None:
        self.node_ids = [n.id for n in nodes]
        self.nodes = list(nodes)
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "nodeIDs": self.node_ids,
                    "nodes": [n.to_dict() for n in nodes],
                },
                f,
            )
        os.replace(tmp, self.path)

    def contains_id(self, node_id: str) -> bool:
        return node_id in self.node_ids


class HolderCleaner:
    """Removes fragments this node no longer owns (holder.go:777-835)."""

    def __init__(self, server):
        self.server = server

    def clean_holder(self) -> List[str]:
        cluster = self.server.cluster
        holder = self.server.holder
        removed: List[str] = []
        for index_name in holder.index_names():
            idx = holder.index(index_name)
            # Pin the shard-space width BEFORE dropping fragments: the
            # index's max shard is derived from local fragments, so GC'ing
            # a handed-off tail shard would silently shrink this node's
            # view of the index and full-index queries would stop fanning
            # out to it (a hole served with no error).
            idx.set_remote_max_shard(idx.max_shard())
            for field in idx.fields.values():
                for view in field.views.values():
                    for shard in list(view.fragments):
                        if cluster.owns_shard(cluster.node.id, index_name, shard):
                            continue
                        frag = view.fragments.pop(shard)
                        frag.close()
                        if frag.path and os.path.exists(frag.path):
                            os.remove(frag.path)
                        cache = frag.cache_path()
                        if cache and os.path.exists(cache):
                            os.remove(cache)
                        removed.append(f"{index_name}/{field.name}/{view.name}/{shard}")
        return removed
