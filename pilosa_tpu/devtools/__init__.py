"""Development-time instrumentation (never imported by serving code).

lockcheck.py is the runtime half of the invariant tooling: pilint
(tools/pilint) proves lexical rules; lockcheck proves the dynamic ones —
lock-order inversions, blocking syscalls made while a lock is held, and
thread joins under a lock. See docs/static-analysis.md.
"""
