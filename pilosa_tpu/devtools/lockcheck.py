"""Runtime lock-order / blocking-under-lock checker (opt-in).

Python ships neither a race detector nor `go vet`; this is the fraction
of both that pilosa-tpu's concurrency rules actually need, cheap enough
to run under the whole tier-1 suite:

  (a) lock-order inversion: every acquisition of lock B while holding
      lock A records the edge A->B in a global directed graph over lock
      *instances*; a new edge that closes a cycle is a potential
      deadlock, reported with both acquisition sites.
  (b) blocking call under a lock: deny-listed blocking primitives
      (time.sleep, os.fsync/fdatasync/replace/rename, socket connect)
      called while the thread holds any instrumented lock — the
      off-lock serialization rules from docs/durability.md and
      docs/tiered-storage.md, enforced at runtime.
  (c) thread join under a lock: Thread.join while holding a lock wedges
      every other user of that lock behind an unbounded wait.

Activation: set PILOSA_TPU_LOCKCHECK=1 and call install() before the
code under test constructs its locks (tests/conftest.py does this for
the whole suite). install() monkeypatches threading.Lock/RLock — the
repo constructs locks exclusively via those module attributes — so
default threading.Condition/Event/Queue objects are instrumented too.

Suppression shares pilint's annotation grammar: a deny-listed call whose
source line (or the line above) carries `# pilint: allow-blocking(reason)`
is not a finding. Lock-order cycles have no annotation escape — order
them or fix them.

Schedule perturbation (opt-in, PILOSA_TPU_LOCKCHECK_SCHED=<seed>): the
lock proxies inject tiny seeded randomized yields at acquire boundaries,
widening the interleavings the instrumented chaos smokes explore beyond
what the OS scheduler happens to pick. Every yield decision is drawn
from ONE seeded PRNG serialized behind the checker's raw lock, so a
given acquire sequence replays deterministically under the same seed
(tests/test_lockcheck.py proves it); the yield sleeps through the
ORIGINAL time.sleep, so the perturbation can never self-report as a
blocking-under-lock finding.

Stdlib-only, and all checker state lives at module level guarded by a
RAW (_thread.allocate_lock) lock so the checker cannot deadlock with or
instrument itself.
"""

from __future__ import annotations

import _thread
import json
import os
import re
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_ANNOT_RE = re.compile(r"#\s*pilint:\s*allow-blocking\(([^)]+)\)")

# ----------------------------------------------------------------- state

_glock = _thread.allocate_lock()  # guards everything below
_installed = False
_uid_counter = [0]
_sites: Dict[int, str] = {}  # lock uid -> creation site "file:line"
_succ: Dict[int, Set[int]] = {}  # instance lock-order graph
_edge_sites: Dict[Tuple[int, int], Tuple[str, str]] = {}  # edge -> acquire sites
_findings: List[dict] = []
_finding_keys: Set[tuple] = set()
_tls = threading.local()
_annot_cache: Dict[str, Set[int]] = {}  # filename -> annotated line numbers

_orig: Dict[str, object] = {}

# Schedule perturbation: seeded RNG + decision trace (for deterministic-
# replay assertions), armed by configure_sched(). Probability and sleep
# ceiling are deliberately tiny — the point is nudging interleavings,
# not slowing the suite.
_sched: Dict[str, object] = {"rng": None, "trace": []}
_SCHED_YIELD_P = 0.25
_SCHED_MAX_SLEEP = 0.0005
_SCHED_TRACE_CAP = 20000

_SKIP_FILES = (os.sep + "devtools" + os.sep + "lockcheck",
               os.sep + "threading.py")
_STDLIB_DIR = os.path.dirname(os.__file__)


def _caller_site(extra_skip: Tuple[str, ...] = ()) -> str:
    """file:line of the nearest frame outside lockcheck/threading."""
    f = sys._getframe(1)
    skip = _SKIP_FILES + extra_skip
    while f is not None:
        fn = f.f_code.co_filename
        if not any(s in fn for s in skip):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _is_stdlib(filename: str) -> bool:
    return filename.startswith(_STDLIB_DIR) or "site-packages" in filename


def _blocking_call_stack() -> Tuple[Optional[Tuple[str, int]], list]:
    """(site, frames) for a deny-listed call: `site` is the nearest frame
    outside the stdlib (the repo line to blame — a connect fired deep in
    http.client should point at the send_message caller, not socket.py);
    `frames` is every (file, line) up-stack, so annotation checks can
    honor an allow-blocking carried by ANY caller: the frame holding the
    lock takes responsibility for blocking work in its callees."""
    site: Optional[Tuple[str, int]] = None
    frames: list = []
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not any(s in fn for s in _SKIP_FILES):
            frames.append((fn, f.f_lineno))
            if site is None and not _is_stdlib(fn):
                site = (fn, f.f_lineno)
        f = f.f_back
    if site is None and frames:
        site = frames[0]
    return site, frames


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site_annotated(filename: str, lineno: int) -> bool:
    """Shared escape hatch: `# pilint: allow-blocking(reason)` on the
    call line or the line above suppresses the runtime finding too."""
    lines = _annot_cache.get(filename)
    if lines is None:
        lines = set()
        try:
            with open(filename, "r", encoding="utf-8", errors="replace") as f:
                for i, text in enumerate(f, start=1):
                    if _ANNOT_RE.search(text):
                        lines.add(i)
                        lines.add(i + 1)  # applies to the line below too
        except OSError:
            pass
        _annot_cache[filename] = lines
    return lineno in lines


def _record(kind: str, key: tuple, detail: dict) -> None:
    with _glock:
        if key in _finding_keys:
            return
        _finding_keys.add(key)
        _findings.append({"kind": kind, **detail})


# -------------------------------------------------------- order tracking


def _find_path(src: int, dst: int) -> Optional[List[int]]:
    """DFS path src->dst in the instance graph (caller holds _glock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _succ.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(proxy) -> None:
    held = _held()
    if held:
        my_site = None
        for h in held:
            edge = (h._uid, proxy._uid)
            with _glock:
                if edge in _edge_sites:
                    continue
                if my_site is None:
                    my_site = _caller_site()
                _edge_sites[edge] = (h._last_acquire or h._site, my_site)
                _succ.setdefault(h._uid, set()).add(proxy._uid)
                # Does the new edge close a cycle? (path new -> ... -> held)
                path = _find_path(proxy._uid, h._uid)
            if path is not None:
                cycle = path  # proxy ... h; the new edge closes it
                cycle_sites = tuple(sorted(_sites.get(u, "?") for u in cycle))
                _record(
                    "lock-order-cycle",
                    ("cycle", cycle_sites),
                    {
                        "locks": [_sites.get(u, "?") for u in cycle],
                        "closing_edge": {
                            "held": _sites.get(h._uid, "?"),
                            "held_acquired_at": _edge_sites[edge][0],
                            "acquiring": _sites.get(proxy._uid, "?"),
                            "acquired_at": _edge_sites[edge][1],
                        },
                    },
                )
        proxy._last_acquire = my_site or proxy._last_acquire
    held.append(proxy)


def _note_released(proxy) -> None:
    held = _held()
    # Release order is usually LIFO but the checker must not assume it.
    for i in range(len(held) - 1, -1, -1):
        if held[i] is proxy:
            del held[i]
            return


# ---------------------------------------------------- schedule perturbation


def configure_sched(seed: Optional[int]) -> None:
    """Arm (or, with None, disarm) the acquire-boundary perturbation.
    Re-arming with the same seed restarts the decision sequence — the
    deterministic-replay contract."""
    import random

    with _glock:
        _sched["rng"] = None if seed is None else random.Random(int(seed))
        _sched["trace"] = []


def sched_trace():
    """The (yielded, delay) decision sequence drawn so far — what the
    determinism test asserts replays exactly under one seed."""
    with _glock:
        return list(_sched["trace"])


def _sched_yield() -> None:
    """Maybe sleep a tiny seeded-random interval before an acquire. The
    draw is serialized behind the checker lock (one global sequence);
    the sleep itself happens OUTSIDE it, through the original
    time.sleep so the deny-list wrapper never sees it."""
    with _glock:
        rng = _sched["rng"]
        if rng is None:
            return
        r = rng.random()
        yielded = r < _SCHED_YIELD_P
        delay = (r / _SCHED_YIELD_P) * _SCHED_MAX_SLEEP if yielded else 0.0
        trace = _sched["trace"]
        trace.append((yielded, round(delay, 7)))
        if len(trace) > _SCHED_TRACE_CAP:
            del trace[: _SCHED_TRACE_CAP // 2]
    if yielded:
        sleep = _orig.get("time.sleep") or time.sleep
        sleep(delay)


# ----------------------------------------------------------- lock proxies


class _LockProxy:
    """Instrumented non-reentrant lock. Quacks enough like thread.lock for
    threading.Condition (which falls back to acquire/release when the
    _release_save protocol is absent — absent here on purpose, so the
    fallback routes through our bookkeeping)."""

    _kind = "Lock"

    def __init__(self, inner):
        self._inner = inner
        with _glock:
            _uid_counter[0] += 1
            self._uid = _uid_counter[0]
        self._site = _caller_site()
        self._last_acquire: Optional[str] = None
        with _glock:
            _sites[self._uid] = f"{self._kind}@{self._site}"

    def acquire(self, blocking=True, timeout=-1):
        _sched_yield()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquired(self)
        return ok

    def release(self):
        self._inner.release()
        _note_released(self)

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # os.register_at_fork handlers call this on stdlib locks
        # (concurrent.futures.thread registers one at import time).
        self._inner._at_fork_reinit()
        self._last_acquire = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockcheck {self._kind} {self._site}>"


class _RLockProxy(_LockProxy):
    """Instrumented reentrant lock. Re-acquisition by the owner adds no
    order edges (depth bookkeeping only). Implements the Condition
    protocol (_release_save/_acquire_restore/_is_owned) so Condition
    waits keep the held-stack honest."""

    _kind = "RLock"

    def __init__(self, inner):
        super().__init__(inner)
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        me = _thread.get_ident()
        if self._owner == me:
            # Reentrant re-acquire: no perturbation (the owner cannot
            # contend with itself) and no order edges.
            self._inner.acquire(blocking, timeout)
            self._count += 1
            return True
        _sched_yield()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._count = 1
            _note_acquired(self)
        return ok

    def release(self):
        if self._owner != _thread.get_ident():
            # Delegate the error to the real lock.
            self._inner.release()
            return
        self._count -= 1
        last = self._count == 0
        if last:
            self._owner = None
        self._inner.release()
        if last:
            _note_released(self)

    def _release_save(self):
        # Bookkeeping BEFORE the inner release (mirroring release()):
        # once _release_save() returns the lock is free, and a concurrent
        # acquire() would race our owner/count writes — a late
        # `self._owner = None` stomps the new owner's claim and strands
        # the lock in its held stack.
        saved_count = self._count
        self._owner = None
        self._count = 0
        _note_released(self)
        state = self._inner._release_save()
        return (state, saved_count)

    def _acquire_restore(self, saved):
        state, count = saved
        self._inner._acquire_restore(state)
        self._owner = _thread.get_ident()
        self._count = count
        _note_acquired(self)

    def _is_owned(self):
        return self._owner == _thread.get_ident()

    def _at_fork_reinit(self):
        super()._at_fork_reinit()
        self._owner = None
        self._count = 0


# ------------------------------------------------------ deny-list wrappers


def _check_blocking(name: str, extra: Optional[dict] = None) -> None:
    if not getattr(_tls, "held", None):
        return
    site, frames = _blocking_call_stack()
    if any(_site_annotated(fn, ln) for fn, ln in frames):
        return
    site_s = f"{site[0]}:{site[1]}" if site else "<unknown>"
    kind = "join-under-lock" if name == "Thread.join" else "blocking-under-lock"
    detail = {"call": name, "site": site_s, "held": [p._site for p in _held()]}
    if extra:
        detail.update(extra)
    _record(kind, (kind, name, site_s), detail)


def _blocking_wrapper(name: str, fn):
    def wrapper(*args, **kwargs):
        _check_blocking(name)
        return fn(*args, **kwargs)

    wrapper.__name__ = getattr(fn, "__name__", name)
    wrapper.__lockcheck_wrapped__ = fn
    return wrapper


def _join_wrapper(orig_join):
    def join(self, timeout=None):
        _check_blocking("Thread.join", {"thread": self.name})
        return orig_join(self, timeout)

    join.__lockcheck_wrapped__ = orig_join
    return join


# --------------------------------------------------------------- lifecycle


def install() -> None:
    """Patch threading/time/os/socket. Idempotent; reversed by
    uninstall(). Must run before the code under test constructs locks."""
    global _installed
    if _installed:
        return
    _installed = True

    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["time.sleep"] = time.sleep
    _orig["os.fsync"] = os.fsync
    _orig["os.fdatasync"] = getattr(os, "fdatasync", None)
    _orig["os.replace"] = os.replace
    _orig["os.rename"] = os.rename
    _orig["socket.connect"] = socket.socket.connect
    _orig["Thread.join"] = threading.Thread.join

    raw_lock, raw_rlock = threading.Lock, threading.RLock
    threading.Lock = lambda: _LockProxy(raw_lock())
    threading.RLock = lambda: _RLockProxy(raw_rlock())
    time.sleep = _blocking_wrapper("time.sleep", time.sleep)
    os.fsync = _blocking_wrapper("os.fsync", os.fsync)
    if _orig["os.fdatasync"] is not None:
        os.fdatasync = _blocking_wrapper("os.fdatasync", os.fdatasync)
    os.replace = _blocking_wrapper("os.replace", os.replace)
    os.rename = _blocking_wrapper("os.rename", os.rename)

    def _connect(self, address, _orig_connect=socket.socket.connect):
        _check_blocking("socket.connect")
        return _orig_connect(self, address)

    socket.socket.connect = _connect
    threading.Thread.join = _join_wrapper(threading.Thread.join)

    seed = os.environ.get("PILOSA_TPU_LOCKCHECK_SCHED")
    if seed:
        try:
            n = int(seed)
        except ValueError:
            # Non-numeric value (someone treated the knob as a boolean
            # toggle): derive a stable seed instead of crashing install()
            # after the monkey-patches are already applied — the run
            # stays deterministic for that spelling.
            import zlib

            n = zlib.crc32(seed.encode("utf-8"))
        configure_sched(n)


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    time.sleep = _orig["time.sleep"]
    os.fsync = _orig["os.fsync"]
    if _orig["os.fdatasync"] is not None:
        os.fdatasync = _orig["os.fdatasync"]
    os.replace = _orig["os.replace"]
    os.rename = _orig["os.rename"]
    socket.socket.connect = _orig["socket.connect"]
    threading.Thread.join = _orig["Thread.join"]
    configure_sched(None)


def active() -> bool:
    return _installed


def reset() -> None:
    """Drop findings + order graph (NOT the installed patches)."""
    with _glock:
        _findings.clear()
        _finding_keys.clear()
        _succ.clear()
        _edge_sites.clear()


def findings() -> List[dict]:
    with _glock:
        return [dict(f) for f in _findings]


def report() -> str:
    fs = sorted(findings(), key=lambda f: (f["kind"], json.dumps(f, sort_keys=True)))
    if not fs:
        return "lockcheck: 0 findings"
    lines = []
    for f in fs:
        if f["kind"] == "lock-order-cycle":
            lines.append(
                "lock-order-cycle: " + " -> ".join(f["locks"])
                + f" (closing edge: {f['closing_edge']['held_acquired_at']}"
                + f" then {f['closing_edge']['acquired_at']})")
        elif f["kind"] == "blocking-under-lock":
            lines.append(
                f"blocking-under-lock: {f['call']} at {f['site']} holding "
                + ", ".join(f["held"]))
        else:
            lines.append(
                f"join-under-lock: join({f['thread']}) at {f['site']} "
                "holding " + ", ".join(f["held"]))
    lines.append(f"lockcheck: {len(fs)} finding(s)")
    return "\n".join(lines)


def write_report(path: str) -> None:
    """Deterministic JSON report (the conftest hook calls this at session
    end so an outer process can assert on the findings)."""
    fs = sorted(findings(), key=lambda f: (f["kind"], json.dumps(f, sort_keys=True)))
    payload = {"findings": fs, "count": len(fs)}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    replace = _orig.get("os.replace", os.replace)
    replace(tmp, path)
