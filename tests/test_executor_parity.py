"""Case-for-case mirrors of reference executor tests not already covered by
test_executor.py (model: /root/reference/executor_test.go).

Each test names the reference test it mirrors; bit patterns and expected
results are kept identical so behavior parity is checkable line by line.
"""

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.errors import PilosaError, QueryError
from pilosa_tpu.executor import Executor
from pilosa_tpu.translate import TranslateStore


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    e = Executor(holder, translate_store=TranslateStore().open(), workers=0)
    yield e
    e.close()  # releases the engine's gather pool (thread-leak guard)


def set_bit(holder, index, field, row, col):
    idx = holder.create_index_if_not_exists(index)
    fld = idx.create_field_if_not_exists(field)
    fld.set_bit(row, col)


def columns(res):
    return res.columns().tolist()


def test_old_pql_rejected(holder, ex):
    """TestExecutor_Execute_OldPQL (executor_test.go:379): the surveyed
    reference dropped pre-v1 syntax; SetBit must fail as an unknown call."""
    set_bit(holder, "i", "f", 1, 0)
    with pytest.raises(PilosaError, match="unknown call: SetBit"):
        ex.execute("i", "SetBit(f=1, row=11, col=1)")


def test_empty_intersect_difference_error_empty_union_ok(holder, ex):
    """TestExecutor_Execute_Empty_{Intersect,Difference,Union}
    (executor_test.go:163-237)."""
    set_bit(holder, "i", "general", 10, 1)
    with pytest.raises(PilosaError):
        ex.execute("i", "Intersect()")
    with pytest.raises(PilosaError):
        ex.execute("i", "Difference()")
    res = ex.execute("i", "Union()")[0]
    assert columns(res) == []


def test_xor_exact_columns(holder, ex):
    """TestExecutor_Execute_Xor (executor_test.go:238)."""
    for row, col in [(10, 0), (10, SHARD_WIDTH + 1), (10, SHARD_WIDTH + 2),
                     (11, 2), (11, SHARD_WIDTH + 2)]:
        set_bit(holder, "i", "general", row, col)
    res = ex.execute("i", "Xor(Row(general=10), Row(general=11))")[0]
    assert columns(res) == [0, 2, SHARD_WIDTH + 1]


def test_topn_fill(holder, ex):
    """TestExecutor_Execute_TopN_fill (executor_test.go:594): row 0's count
    in shard 0 alone doesn't beat row 1; phase 2 must refetch exact counts
    across shards."""
    for row, col in [(0, 0), (0, 1), (0, 2), (0, SHARD_WIDTH),
                     (1, SHARD_WIDTH + 2), (1, SHARD_WIDTH)]:
        set_bit(holder, "i", "f", row, col)
    pairs = ex.execute("i", "TopN(f, n=1)")[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 4)]


def test_topn_fill_small(holder, ex):
    """TestExecutor_Execute_TopN_fill_small (executor_test.go:618): row 0
    has one bit per shard (5 shards); per-shard candidates are the local
    leaders, the global winner only emerges from the phase-2 refetch."""
    bits = [(0, 0), (0, SHARD_WIDTH), (0, 2 * SHARD_WIDTH), (0, 3 * SHARD_WIDTH),
            (0, 4 * SHARD_WIDTH),
            (1, 0), (1, 1),
            (2, SHARD_WIDTH), (2, SHARD_WIDTH + 1),
            (3, 2 * SHARD_WIDTH), (3, 2 * SHARD_WIDTH + 1),
            (4, 3 * SHARD_WIDTH), (4, 3 * SHARD_WIDTH + 1)]
    for row, col in bits:
        set_bit(holder, "i", "f", row, col)
    pairs = ex.execute("i", "TopN(f, n=1)")[0]
    assert [(p.id, p.count) for p in pairs] == [(0, 5)]


def test_set_value_ok_and_errors(holder, ex):
    """TestExecutor_Execute_SetValue (executor_test.go:393-470), including
    exact error-message parity."""
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("f", FieldOptions(type="int", min=0, max=50))
    idx.create_field_if_not_exists("xxx")
    ex.execute("i", "SetValue(col=10, f=25)")
    ex.execute("i", "SetValue(col=100, f=10)")
    f = idx.field("f")
    assert f.value(10) == (25, True)
    assert f.value(100) == (10, True)

    with pytest.raises(PilosaError, match=r"SetValue\(\) column field 'col' required"):
        ex.execute("i", "SetValue(invalid_column_name=10, f=100)")
    with pytest.raises(PilosaError, match=r"SetValue\(\) column field 'col' required"):
        ex.execute("i", 'SetValue(invalid_column_name="bad_column", f=100)')
    with pytest.raises(PilosaError, match="invalid bsigroup value type"):
        ex.execute("i", 'SetValue(col=10, f="hello")')


def test_set_column_attrs_excludes_field(holder, ex):
    """TestExecutor_SetColumnAttrs_ExcludeField (executor_test.go:1265):
    the field arg named in Set() must not leak into column attrs."""
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("f")
    ex.execute("i", "Set(10, f=1)")
    ex.execute("i", "SetColumnAttrs(10, foo='bar')")
    assert idx.column_attr_store.attrs(10) == {"foo": "bar"}
    ex.execute("i", "Set(20, f=10)")
    ex.execute("i", "SetColumnAttrs(20, foo='bar')")
    assert idx.column_attr_store.attrs(20) == {"foo": "bar"}


TIME_CLEAR_CASES = [
    ("Y", [3, 4, 5, 6]),
    ("M", [3, 4, 6]),
    ("D", [3, 4, 5, 6]),
    ("H", [3, 4, 5, 6, 7]),
    ("YM", [3, 4, 5, 6]),
    ("YMD", [3, 4, 5, 6]),
    ("YMDH", [3, 4, 5, 6, 7]),
    ("MD", [3, 4, 5, 6]),
    ("MDH", [3, 4, 5, 6, 7]),
    ("DH", [3, 4, 5, 6, 7]),
]


@pytest.mark.parametrize("quantum,expected", TIME_CLEAR_CASES)
def test_time_clear_quantums(holder, ex, quantum, expected):
    """TestExecutor_Time_Clear_Quantums (executor_test.go:1315): Clear()
    must remove the column from every quantum view, and Range() results
    depend on which quantum granularities exist."""
    index_name = quantum.lower()
    idx = holder.create_index_if_not_exists(index_name)
    idx.create_field_if_not_exists(
        "f", FieldOptions(type="time", time_quantum=quantum)
    )
    ex.execute(index_name, """
        Set(2, f=1, 1999-12-31T00:00)
        Set(3, f=1, 2000-01-01T00:00)
        Set(4, f=1, 2000-01-02T00:00)
        Set(5, f=1, 2000-02-01T00:00)
        Set(6, f=1, 2001-01-01T00:00)
        Set(7, f=1, 2002-01-01T02:00)
        Set(2, f=1, 1999-12-30T00:00)
        Set(2, f=1, 2002-02-01T00:00)
        Set(2, f=10, 2001-01-01T00:00)
    """)
    ex.execute(index_name, "Clear( 2, f=1)")
    res = ex.execute(index_name, "Range(f=1, 1999-12-31T00:00, 2002-01-01T03:00)")[0]
    assert columns(res) == expected, quantum


def test_translate_does_not_abort_valid_writes(holder, ex):
    """Reference translateCall ignores FieldArg errors (executor.go:1600);
    'Set(1, f=1) Clear(2)' applies the Set, then rejects only the Clear at
    execution time."""
    idx = holder.create_index_if_not_exists("i")
    idx.create_field_if_not_exists("f")
    with pytest.raises(PilosaError):
        ex.execute("i", "Set(1, f=1)\nClear(2)")
    res = ex.execute("i", "Count(Row(f=1))")
    assert res == [1]


def test_empty_key_not_translated(holder, ex):
    """Empty string keys are skipped by translation (callArgString != ""
    guard, executor.go:1613) and rejected downstream — no phantom id."""
    holder.create_index_if_not_exists("k", IndexOptions(keys=True)) \
        .create_field_if_not_exists("f")
    with pytest.raises(PilosaError):
        ex.execute("k", 'Set("", f=1)')
