"""Multi-host collective backend: REAL multi-process jax.distributed.

Two OS processes each own half the shards, join one jax.distributed job
(CPU backend, 2 virtual devices per process), build globally-sharded plane
arrays from process-local data, and produce identical all-reduced counts —
the TPU-native analog of the reference's cross-host scatter-gather RPC
(executor.go:1393-1440), with the reduce riding XLA collectives instead of
Python. Run as subprocesses because jax.distributed binds one process_id
per OS process.
"""

import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    )
    coordinator, n_proc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    # The axon TPU plugin overrides JAX_PLATFORMS; the config API wins.
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.parallel import distributed as dist

    assert dist.initialize(coordinator, n_proc, pid)

    assert jax.process_count() == n_proc, jax.process_count()
    assert jax.device_count() == 2 * n_proc

    # 8 shards, 64 words per plane; shard s has popcount (s+1) in row 0 and
    # bit pattern overlapping row 1 only on even shards.
    n_shards, w = 8, 64
    padded, lo, hi = dist.process_shard_slots(n_shards)
    assert padded == 8
    a_local = np.zeros((hi - lo, w), dtype=np.uint32)
    b_local = np.zeros((hi - lo, w), dtype=np.uint32)
    for s in range(lo, hi):
        a_local[s - lo, 0] = (1 << (s + 1)) - 1     # popcount s+1
        b_local[s - lo, 0] = 0xFFFFFFFF if s % 2 == 0 else 0

    mesh = dist.global_mesh()
    A = dist.make_global_planes(a_local, padded, mesh)
    B = dist.make_global_planes(b_local, padded, mesh)

    total = dist.global_count(A)
    want_total = sum(s + 1 for s in range(n_shards))
    assert total == want_total, (total, want_total)

    inter = dist.global_and_count(A, B)
    want_inter = sum(s + 1 for s in range(n_shards) if s % 2 == 0)
    assert inter == want_inter, (inter, want_inter)
    print(f"WORKER_OK pid={pid} total={total} inter={inter}")
""")


@pytest.mark.parametrize("n_proc", [2])
def test_two_process_global_mesh_counts(tmp_path, n_proc):
    import os

    port = free_port()
    coordinator = f"localhost:{port}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(n_proc), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for pid in range(n_proc)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "WORKER_OK" in out
    # Every process materialized the same all-reduced scalars.
    totals = {line for _, out, _ in outs for line in out.splitlines()
              if "WORKER_OK" in line}
    assert len({t.split("total=")[1] for t in totals}) == 1


def test_collective_count_endpoint(tmp_path):
    """Leader-driven collective count through the real server/API on a
    single-process job (the degenerate case: no peers to broadcast to, the
    local mesh is the global mesh). Cross-checks against the PQL path."""
    import json
    import urllib.request

    from pilosa_tpu.server.client import InternalClient
    from pilosa_tpu.server.server import Server

    s = Server(data_dir=str(tmp_path / "n0"), cache_flush_interval=0)
    s.open()
    try:
        client = InternalClient()
        h = f"localhost:{s.port}"
        client.create_index(h, "cc")
        client.create_field(h, "cc", "f")
        from pilosa_tpu.constants import SHARD_WIDTH

        for col in [1, 5, SHARD_WIDTH + 3]:
            client.query(h, "cc", f"Set({col}, f=7)")
            client.query(h, "cc", f"Set({col}, f=9)")
        client.query(h, "cc", f"Set(2, f=9)")

        req = urllib.request.Request(
            f"http://{h}/internal/collective/count",
            data=json.dumps({"index": "cc", "field": "f", "rows": [7]}).encode(),
            method="POST",
        )
        got = json.load(urllib.request.urlopen(req))["count"]
        assert got == 3
        # Intersect of two rows across the mesh.
        req = urllib.request.Request(
            f"http://{h}/internal/collective/count",
            data=json.dumps({"index": "cc", "field": "f", "rows": [7, 9]}).encode(),
            method="POST",
        )
        assert json.load(urllib.request.urlopen(req))["count"] == 3
        want = client.query(h, "cc", "Count(Intersect(Row(f=7), Row(f=9)))")
        assert want["results"][0] == 3
    finally:
        s.close()


def test_single_process_degenerates_to_local(monkeypatch):
    """initialize() without a coordinator is a no-op and the helpers work
    on the local (virtual 8-device) mesh."""
    from pilosa_tpu.parallel import distributed as dist

    monkeypatch.delenv("PILOSA_JAX_COORDINATOR", raising=False)
    assert not dist.initialize()
    n_shards = 8
    padded, lo, hi = dist.process_shard_slots(n_shards)
    assert lo == 0 and hi == padded >= n_shards
    planes = np.zeros((hi - lo, 16), dtype=np.uint32)
    planes[3, 0] = 0b1011
    A = dist.make_global_planes(planes, padded)
    assert dist.global_count(A) == 3
