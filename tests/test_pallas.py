"""Pallas kernel tests (interpret mode on CPU; real kernels on TPU).

Oracle: numpy popcount over the same data.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk


def np_popcount(x):
    return int(np.unpackbits(np.ascontiguousarray(x).view(np.uint8)).sum())


RNG = np.random.default_rng(5)


def rand_plane(n=32768):
    return RNG.integers(0, 1 << 32, n, dtype=np.uint32)


def test_batched_gather_expr_count():
    # (U, S, W) stack; queries gather leaf pairs and count the intersection.
    import jax.numpy as jnp

    u, s, w, q = 5, 3, 256, 7
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    ia = RNG.integers(0, u, q).astype(np.int32)
    ib = RNG.integers(0, u, q).astype(np.int32)

    def expr(planes):
        return jnp.bitwise_and(planes[0], planes[1])

    got = np.asarray(
        pk.batched_gather_expr_count(jnp.asarray(stacked), (ia, ib), expr)
    )
    want = np.array(
        [np_popcount(stacked[ia[i]] & stacked[ib[i]]) for i in range(q)]
    )
    np.testing.assert_array_equal(got, want)


def test_batched_gather_expr_count_three_leaves():
    import jax.numpy as jnp

    u, s, w, q = 4, 2, 128, 5
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    idxs = tuple(RNG.integers(0, u, q).astype(np.int32) for _ in range(3))

    def expr(planes):
        return jnp.bitwise_or(
            jnp.bitwise_and(planes[0], planes[1]),
            jnp.bitwise_and(planes[2], jnp.bitwise_not(planes[0])),
        )

    got = np.asarray(pk.batched_gather_expr_count(jnp.asarray(stacked), idxs, expr))
    want = np.array([
        np_popcount(
            (stacked[idxs[0][i]] & stacked[idxs[1][i]])
            | (stacked[idxs[2][i]] & ~stacked[idxs[0][i]])
        )
        for i in range(q)
    ])
    np.testing.assert_array_equal(got, want)


def test_batched_gather_expr_count_w_chunked(monkeypatch):
    """When the leaf blocks exceed the VMEM budget the W axis chunks
    (grid (Q, n_wb) with accumulated partials) — results must not change."""
    import jax.numpy as jnp

    monkeypatch.setattr(pk, "_GATHER_VMEM_BUDGET", 2 * 2 * 4 * 256 * 4 // 2)
    u, s, w, q = 6, 4, 1024, 5  # forces wc < w under the tiny budget
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    ia = RNG.integers(0, u, q).astype(np.int32)
    ib = RNG.integers(0, u, q).astype(np.int32)

    def expr(planes):
        return jnp.bitwise_and(planes[0], planes[1])

    got = np.asarray(pk.batched_gather_expr_count(jnp.asarray(stacked), (ia, ib), expr))
    want = np.array([np_popcount(stacked[ia[i]] & stacked[ib[i]]) for i in range(q)])
    np.testing.assert_array_equal(got, want)


def test_batched_gather_expr_count_wide_shard_axis():
    """256-shard geometry (the bench_big TPU shape, W scaled down so
    interpret mode stays fast): per-query gather blocks span a wide S
    axis and must still count exactly."""
    import jax.numpy as jnp

    u, s, w, q = 4, 256, 256, 6
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    ia = RNG.integers(0, u, q).astype(np.int32)
    ib = RNG.integers(0, u, q).astype(np.int32)

    def expr(planes):
        return jnp.bitwise_and(planes[0], planes[1])

    got = np.asarray(pk.batched_gather_expr_count(jnp.asarray(stacked), (ia, ib), expr))
    want = np.array([np_popcount(stacked[ia[i]] & stacked[ib[i]]) for i in range(q)])
    np.testing.assert_array_equal(got, want)
