"""Pallas kernel tests (interpret mode on CPU; real kernels on TPU).

Oracle: numpy popcount over the same data.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import pallas_kernels as pk


def np_popcount(x):
    return int(np.unpackbits(np.ascontiguousarray(x).view(np.uint8)).sum())


RNG = np.random.default_rng(5)


def rand_plane(n=32768):
    return RNG.integers(0, 1 << 32, n, dtype=np.uint32)


def test_fused_intersection_count():
    a, b = rand_plane(), rand_plane()
    assert int(pk.fused_intersection_count(a, b)) == np_popcount(a & b)


def test_fused_intersection_count_nonaligned():
    # Width not a multiple of the VMEM block: padding must not change counts.
    a, b = rand_plane(1000), rand_plane(1000)
    assert int(pk.fused_intersection_count(a, b)) == np_popcount(a & b)


def test_fused_nary_count_tree():
    a, b, c = rand_plane(4096), rand_plane(4096), rand_plane(4096)
    # (a & b) | (c &~ a)
    tape = (
        (pk.OP_AND, 0, 1),      # slot 3 = a & b
        (pk.OP_ANDNOT, 2, 0),   # slot 4 = c &~ a
        (pk.OP_OR, 3, 4),       # slot 5
    )
    got = int(pk.fused_nary_count(tape, a, b, c))
    want = np_popcount((a & b) | (c & ~a))
    assert got == want


def test_fused_nary_count_xor():
    a, b = rand_plane(4096), rand_plane(4096)
    got = int(pk.fused_nary_count(((pk.OP_XOR, 0, 1),), a, b))
    assert got == np_popcount(a ^ b)


def test_topn_filter_counts():
    rows = np.stack([rand_plane(16384) for _ in range(6)])
    filt = rand_plane(16384)
    got = np.asarray(pk.topn_filter_counts(rows, filt))
    want = [np_popcount(r & filt) for r in rows]
    assert got.tolist() == want


def test_topn_filter_counts_multiblock():
    rows = np.stack([rand_plane(pk.BLOCK * 2) for _ in range(3)])
    filt = rand_plane(pk.BLOCK * 2)
    got = np.asarray(pk.topn_filter_counts(rows, filt))
    want = [np_popcount(r & filt) for r in rows]
    assert got.tolist() == want


def test_batched_gather_expr_count():
    # (U, S, W) stack; queries gather leaf pairs and count the intersection.
    import jax.numpy as jnp

    u, s, w, q = 5, 3, 256, 7
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    ia = RNG.integers(0, u, q).astype(np.int32)
    ib = RNG.integers(0, u, q).astype(np.int32)

    def expr(planes):
        return jnp.bitwise_and(planes[0], planes[1])

    got = np.asarray(
        pk.batched_gather_expr_count(jnp.asarray(stacked), (ia, ib), expr)
    )
    want = np.array(
        [np_popcount(stacked[ia[i]] & stacked[ib[i]]) for i in range(q)]
    )
    np.testing.assert_array_equal(got, want)


def test_batched_gather_expr_count_three_leaves():
    import jax.numpy as jnp

    u, s, w, q = 4, 2, 128, 5
    stacked = RNG.integers(0, 1 << 32, (u, s, w), dtype=np.uint32)
    idxs = tuple(RNG.integers(0, u, q).astype(np.int32) for _ in range(3))

    def expr(planes):
        return jnp.bitwise_or(
            jnp.bitwise_and(planes[0], planes[1]),
            jnp.bitwise_and(planes[2], jnp.bitwise_not(planes[0])),
        )

    got = np.asarray(pk.batched_gather_expr_count(jnp.asarray(stacked), idxs, expr))
    want = np.array([
        np_popcount(
            (stacked[idxs[0][i]] & stacked[idxs[1][i]])
            | (stacked[idxs[2][i]] & ~stacked[idxs[0][i]])
        )
        for i in range(q)
    ])
    np.testing.assert_array_equal(got, want)
