"""Executor tests driving full PQL strings on a single in-process node
(model: /root/reference/executor_test.go, which uses test.MustRunCluster).
Bit patterns deliberately span shards (SHARD_WIDTH+x) to exercise the
map/reduce path."""

import pytest

from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.translate import TranslateStore


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    yield h
    h.close()


@pytest.fixture
def ex(holder):
    e = Executor(holder, translate_store=TranslateStore().open(), workers=0)
    yield e
    e.close()  # releases the engine's gather pool (thread-leak guard)


def setup_index(holder, name="i", keys=False):
    idx = holder.create_index_if_not_exists(name, IndexOptions(keys=keys))
    idx.create_field_if_not_exists("f")
    idx.create_field_if_not_exists("g")
    return idx


def test_row_and_set(holder, ex):
    setup_index(holder)
    res = ex.execute("i", "Set(3, f=10)")
    assert res == [True]
    res = ex.execute("i", "Set(3, f=10)")
    assert res == [False]  # already set
    ex.execute("i", f"Set({SHARD_WIDTH + 1}, f=10)")
    row = ex.execute("i", "Row(f=10)")[0]
    assert list(row.columns()) == [3, SHARD_WIDTH + 1]


def test_clear(holder, ex):
    setup_index(holder)
    ex.execute("i", "Set(3, f=10)")
    assert ex.execute("i", "Clear(3, f=10)") == [True]
    assert ex.execute("i", "Clear(3, f=10)") == [False]
    assert list(ex.execute("i", "Row(f=10)")[0].columns()) == []


def test_intersect_cross_shard(holder, ex):
    setup_index(holder)
    for col in [1, 100, SHARD_WIDTH, SHARD_WIDTH + 2]:
        ex.execute("i", f"Set({col}, f=10)")
    for col in [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH]:
        ex.execute("i", f"Set({col}, g=20)")
    row = ex.execute("i", "Intersect(Row(f=10), Row(g=20))")[0]
    assert list(row.columns()) == [1, SHARD_WIDTH + 2]
    assert ex.execute("i", "Count(Intersect(Row(f=10), Row(g=20)))") == [2]


def test_union_difference_xor(holder, ex):
    setup_index(holder)
    for col in [0, 2, SHARD_WIDTH]:
        ex.execute("i", f"Set({col}, f=1)")
    for col in [2, 3]:
        ex.execute("i", f"Set({col}, g=2)")
    assert list(ex.execute("i", "Union(Row(f=1), Row(g=2))")[0].columns()) == [0, 2, 3, SHARD_WIDTH]
    assert list(ex.execute("i", "Difference(Row(f=1), Row(g=2))")[0].columns()) == [0, SHARD_WIDTH]
    assert list(ex.execute("i", "Xor(Row(f=1), Row(g=2))")[0].columns()) == [0, 3, SHARD_WIDTH]


def test_count(holder, ex):
    setup_index(holder)
    for col in [1, 2, SHARD_WIDTH + 5]:
        ex.execute("i", f"Set({col}, f=7)")
    assert ex.execute("i", "Count(Row(f=7))") == [3]


def test_topn_two_phase_cross_shard(holder, ex):
    setup_index(holder)
    # Row 10: 2 bits in shard 0, 2 bits in shard 1 (total 4).
    # Row 20: 3 bits in shard 0 (total 3). Row 30: 1 bit.
    for col in [0, 1, SHARD_WIDTH, SHARD_WIDTH + 1]:
        ex.execute("i", f"Set({col}, f=10)")
    for col in [2, 3, 4]:
        ex.execute("i", f"Set({col}, f=20)")
    ex.execute("i", "Set(5, f=30)")
    pairs = ex.execute("i", "TopN(f, n=2)")[0]
    assert [(p.id, p.count) for p in pairs] == [(10, 4), (20, 3)]
    pairs = ex.execute("i", "TopN(f)")[0]
    assert [(p.id, p.count) for p in pairs] == [(10, 4), (20, 3), (30, 1)]


def test_topn_with_src(holder, ex):
    setup_index(holder)
    for col in [0, 1, 2]:
        ex.execute("i", f"Set({col}, f=10)")
    for col in [1, 2, 3, 4]:
        ex.execute("i", f"Set({col}, f=20)")
    for col in [0, 1]:
        ex.execute("i", f"Set({col}, g=5)")
    pairs = ex.execute("i", "TopN(f, Row(g=5), n=2)")[0]
    assert [(p.id, p.count) for p in pairs] == [(10, 2), (20, 1)]


def test_topn_with_src_batched_matches_fallback(holder, ex):
    """Phase-1-with-src runs as ONE batched device program across shards
    (union of per-shard cache candidates -> engine.topn_shard_counts ->
    per-shard heap replay). Results must be identical to the per-fragment
    fallback path (forced by pretending the engine can't compile src)."""
    import numpy as np

    setup_index(holder)
    rng = np.random.default_rng(17)
    fld = holder.index("i").field("f")
    g = holder.index("i").field("g")
    n_rows, n_shards = 24, 3
    rows, cols = [], []
    for row in range(n_rows):
        for s in range(n_shards):
            c = rng.choice(4096, size=64 + row, replace=False)
            rows.extend([row] * len(c))
            cols.extend(int(s * SHARD_WIDTH + x) for x in c)
    fld.import_bits(rows, cols)
    gc = [int(s * SHARD_WIDTH + x)
          for s in range(n_shards) for x in rng.choice(4096, 1500, replace=False)]
    g.import_bits([3] * len(gc), gc)

    q = "TopN(f, Row(g=3), n=7, threshold=2)"
    got = [(p.id, p.count) for p in ex.execute("i", q)[0]]

    real_supports = ex.engine.supports
    src_ast = None

    def no_src_supports(call, *a, **kw):
        # Refuse only the src Row so the executor takes the per-fragment
        # fallback; the phase-2 refetch path is disabled the same way.
        if call.name == "Row" and call.args.get("g") is not None:
            return False
        return real_supports(call, *a, **kw)

    ex.engine.supports = no_src_supports
    try:
        want = [(p.id, p.count) for p in ex.execute("i", q)[0]]
    finally:
        ex.engine.supports = real_supports
    assert got == want and got, (got, want)


def _force_fallback_topn(ex, q, src_field="g"):
    """Run `q` with the engine refusing the src Row, forcing the
    per-fragment TopN fallback (the semantic oracle for the batched path)."""
    real_supports = ex.engine.supports

    def no_src_supports(call, *a, **kw):
        if call.name == "Row" and call.args.get(src_field) is not None:
            return False
        return real_supports(call, *a, **kw)

    ex.engine.supports = no_src_supports
    try:
        return [(p.id, p.count) for p in ex.execute("i", q)[0]]
    finally:
        ex.engine.supports = real_supports


def test_topn_tanimoto_batched_matches_fallback(holder, ex):
    """Tanimoto TopN (the ChEMBL workload, docs/examples.md:321-328) rides
    the batched device path: the coefficient is a pure function of
    (cache_count, inter_count, src_count), all produced by ONE
    topn_shard_counts program — results must equal the per-fragment
    fallback (fragment.go:1008-1027 semantics)."""
    import numpy as np

    setup_index(holder)
    rng = np.random.default_rng(23)
    fld = holder.index("i").field("f")
    g = holder.index("i").field("g")
    n_rows, n_shards = 20, 3
    rows, cols = [], []
    for row in range(n_rows):
        for s in range(n_shards):
            c = rng.choice(2048, size=32 + 8 * row, replace=False)
            rows.extend([row] * len(c))
            cols.extend(int(s * SHARD_WIDTH + x) for x in c)
    fld.import_bits(rows, cols)
    gc = [int(s * SHARD_WIDTH + x)
          for s in range(n_shards) for x in rng.choice(2048, 300, replace=False)]
    g.import_bits([3] * len(gc), gc)

    for extra in ("", ", threshold=60"):
        # An explicit threshold must not prune tanimoto candidates
        # (reference fragment.go:909-920 branches on tanimoto before
        # minThreshold; only the heap-full early-exit, fragment.go:976-981,
        # consults it). Batched and fallback paths must agree either way.
        for thr in (5, 25, 60):
            q = f"TopN(f, Row(g=3), n=10, tanimotoThreshold={thr}{extra})"
            got = [(p.id, p.count) for p in ex.execute("i", q)[0]]
            want = _force_fallback_topn(ex, q)
            assert got == want, (thr, extra, got, want)
    # At least one threshold must produce hits or the parity is vacuous.
    assert _force_fallback_topn(ex, "TopN(f, Row(g=3), n=10, tanimotoThreshold=5)")


def test_topn_attr_filter_with_src_batched_matches_fallback(holder, ex):
    """Attr-filtered TopN WITH a src bitmap goes through the batched
    phase-1 path (attr filtering is a host-side candidate check; only
    surviving candidates ride the device program)."""
    import numpy as np

    setup_index(holder)
    rng = np.random.default_rng(31)
    fld = holder.index("i").field("f")
    g = holder.index("i").field("g")
    for row in range(12):
        c = rng.choice(2048, size=64, replace=False)
        fld.import_bits([row] * len(c), [int(x) for x in c])
        ex.execute("i", f'SetRowAttrs(f, {row}, category="{"even" if row % 2 == 0 else "odd"}")')
    gc = [int(x) for x in rng.choice(2048, 500, replace=False)]
    g.import_bits([3] * len(gc), gc)

    q = 'TopN(f, Row(g=3), n=6, attrName="category", attrValues=["even"])'
    got = [(p.id, p.count) for p in ex.execute("i", q)[0]]
    want = _force_fallback_topn(ex, q)
    assert got == want and got, (got, want)
    assert all(r % 2 == 0 for r, _ in got)

    # Explicit ids + attr filter: the batched phase-2 path prefilters rows
    # against the attr store before they join the device program.
    q2 = ('TopN(f, Row(g=3), ids=[0,1,2,3,4,5], '
          'attrName="category", attrValues=["even"])')
    got2 = [(p.id, p.count) for p in ex.execute("i", q2)[0]]
    want2 = _force_fallback_topn(ex, q2)
    assert got2 == want2 and got2, (got2, want2)
    assert {r for r, _ in got2} <= {0, 2, 4}


def test_topn_tanimoto_over_100_rejected(holder, ex):
    setup_index(holder)
    ex.execute("i", "Set(1, f=10)")
    ex.execute("i", "Set(1, g=3)")
    from pilosa_tpu.errors import QueryError

    with pytest.raises(QueryError):
        ex.execute("i", "TopN(f, Row(g=3), n=5, tanimotoThreshold=101)")


def test_sum_min_max(holder, ex):
    idx = setup_index(holder)
    idx.create_field_if_not_exists("v", FieldOptions(type="int", min=-10, max=1000))
    ex.execute("i", "SetValue(col=1, v=5)")
    ex.execute("i", "SetValue(col=2, v=-10)")
    ex.execute("i", f"SetValue(col={SHARD_WIDTH + 3}, v=1000)")
    ex.execute("i", "Set(1, f=1)")
    ex.execute("i", "Set(2, f=1)")
    assert ex.execute("i", "Sum(field=v)")[0].to_dict() == {"value": 995, "count": 3}
    assert ex.execute("i", "Min(field=v)")[0].to_dict() == {"value": -10, "count": 1}
    assert ex.execute("i", "Max(field=v)")[0].to_dict() == {"value": 1000, "count": 1}
    # Filtered by Row(f=1) → columns 1, 2.
    assert ex.execute("i", "Sum(Row(f=1), field=v)")[0].to_dict() == {"value": -5, "count": 2}
    assert ex.execute("i", "Max(Row(f=1), field=v)")[0].to_dict() == {"value": 5, "count": 1}


def test_bsi_range_queries(holder, ex):
    idx = setup_index(holder)
    idx.create_field_if_not_exists("v", FieldOptions(type="int", min=0, max=100))
    for col, val in [(1, 10), (2, 20), (3, 30), (SHARD_WIDTH + 4, 40)]:
        ex.execute("i", f"SetValue(col={col}, v={val})")
    assert list(ex.execute("i", "Range(v == 20)")[0].columns()) == [2]
    assert list(ex.execute("i", "Range(v != 20)")[0].columns()) == [1, 3, SHARD_WIDTH + 4]
    assert list(ex.execute("i", "Range(v < 30)")[0].columns()) == [1, 2]
    assert list(ex.execute("i", "Range(v <= 30)")[0].columns()) == [1, 2, 3]
    assert list(ex.execute("i", "Range(v > 20)")[0].columns()) == [3, SHARD_WIDTH + 4]
    assert list(ex.execute("i", "Range(15 < v < 35)")[0].columns()) == [2, 3]
    assert list(ex.execute("i", "Range(v >< [20, 40])")[0].columns()) == [2, 3, SHARD_WIDTH + 4]
    assert list(ex.execute("i", "Range(v != null)")[0].columns()) == [1, 2, 3, SHARD_WIDTH + 4]
    # Out of range → empty.
    assert list(ex.execute("i", "Range(v == 999)")[0].columns()) == []
    # Full-range collapse to not-null.
    assert list(ex.execute("i", "Range(v < 999)")[0].columns()) == [1, 2, 3, SHARD_WIDTH + 4]


def test_time_range(holder, ex):
    idx = holder.create_index_if_not_exists("t")
    idx.create_field_if_not_exists("f", FieldOptions(type="time", time_quantum="YMDH"))
    ex.execute("t", "Set(1, f=1, 2010-01-01T00:00)")
    ex.execute("t", "Set(2, f=1, 2010-01-02T00:00)")
    ex.execute("t", "Set(3, f=1, 2010-02-01T00:00)")
    row = ex.execute("t", "Range(f=1, 2010-01-01T00:00, 2010-01-03T00:00)")[0]
    assert list(row.columns()) == [1, 2]
    row = ex.execute("t", "Range(f=1, 2009-12-01T00:00, 2010-03-01T00:00)")[0]
    assert list(row.columns()) == [1, 2, 3]
    # Standard view still has all bits.
    assert list(ex.execute("t", "Row(f=1)")[0].columns()) == [1, 2, 3]


def test_time_range_fast_path_matches_fallback(holder, ex):
    """Time-quantum Range compiles onto the engine fast path (union over
    time-view leaves, ONE device program across shards) — results must be
    identical to the per-shard per-view merge fallback
    (executor.py:_execute_time_range_shard), incl. composed in Intersect
    and as a Count input."""
    idx = holder.create_index_if_not_exists("tt")
    idx.create_field_if_not_exists("f", FieldOptions(type="time", time_quantum="YMD"))
    idx.create_field_if_not_exists("g")
    for day in range(1, 9):
        for col in (day, SHARD_WIDTH + day, 100 + day):
            ex.execute("tt", f"Set({col}, f=1, 2018-03-{day:02d}T00:00)")
    for col in (2, 3, 103, SHARD_WIDTH + 4):
        ex.execute("tt", f"Set({col}, g=9)")

    queries = [
        "Range(f=1, 2018-03-02T00:00, 2018-03-06T00:00)",
        "Count(Range(f=1, 2018-03-02T00:00, 2018-03-06T00:00))",
        "Intersect(Range(f=1, 2018-03-01T00:00, 2018-03-08T00:00), Row(g=9))",
        "Count(Union(Range(f=1, 2018-03-01T00:00, 2018-03-03T00:00), Row(g=9)))",
    ]

    def run_all():
        out = []
        for q in queries:
            r = ex.execute("tt", q)[0]
            out.append(list(r.columns()) if hasattr(r, "columns") else r)
        return out

    got = run_all()
    real_supports = ex.engine.supports

    def no_range_supports(call, *a, **kw):
        if call.name == "Range":
            return False
        return real_supports(call, *a, **kw)

    ex.engine.supports = no_range_supports
    try:
        want = run_all()
    finally:
        ex.engine.supports = real_supports
    assert got == want, (got, want)
    assert got[1] == 12  # 4 days (end-exclusive) x 3 cols: non-vacuous

    # supports() with the index is exact: a non-time field refuses (the
    # fallback returns an empty Row there; claiming support would raise).
    from pilosa_tpu.pql.parser import parse

    bad = parse("Range(g=1, 2018-03-01T00:00, 2018-03-02T00:00)").calls[0]
    assert not ex.engine.supports(bad, "tt")
    good = parse("Range(f=1, 2018-03-01T00:00, 2018-03-02T00:00)").calls[0]
    assert ex.engine.supports(good, "tt")
    assert not ex.engine.supports(good)  # syntactic-only: refused


def test_row_attrs(holder, ex):
    setup_index(holder)
    ex.execute("i", 'SetRowAttrs(f, 10, foo="bar", count=123)')
    ex.execute("i", "Set(1, f=10)")
    row = ex.execute("i", "Row(f=10)")[0]
    assert row.attrs == {"foo": "bar", "count": 123}
    row = ex.execute("i", "Row(f=10)", opt=ExecOptions(exclude_row_attrs=True))[0]
    assert row.attrs == {}


def test_column_attrs(holder, ex):
    setup_index(holder)
    ex.execute("i", 'SetColumnAttrs(7, name="alice")')
    assert holder.index("i").column_attr_store.attrs(7) == {"name": "alice"}


def test_topn_attr_filter(holder, ex):
    setup_index(holder)
    for col in range(4):
        ex.execute("i", f"Set({col}, f=10)")
    for col in range(2):
        ex.execute("i", f"Set({col}, f=20)")
    ex.execute("i", 'SetRowAttrs(f, 10, category="x")')
    ex.execute("i", 'SetRowAttrs(f, 20, category="y")')
    pairs = ex.execute("i", 'TopN(f, n=5, attrName="category", attrValues=["y"])')[0]
    assert [(p.id, p.count) for p in pairs] == [(20, 2)]


def test_key_translation(holder, ex):
    idx = holder.create_index_if_not_exists("k", IndexOptions(keys=True))
    idx.create_field_if_not_exists("f", FieldOptions(keys=True))
    ex.execute("k", 'Set("alice", f="red")')
    ex.execute("k", 'Set("bob", f="red")')
    row = ex.execute("k", 'Row(f="red")')[0]
    assert sorted(row.keys) == ["alice", "bob"]
    pairs = ex.execute("k", "TopN(f, n=1)")[0]
    assert pairs[0].key == "red"
    assert pairs[0].count == 2


def test_error_on_unknown_field(holder, ex):
    setup_index(holder)
    with pytest.raises(Exception):
        ex.execute("i", "Row(nosuch=1)")


def test_write_limit(holder, ex):
    setup_index(holder)
    ex.max_writes_per_request = 2
    with pytest.raises(Exception):
        ex.execute("i", "Set(1, f=1) Set(2, f=1) Set(3, f=1)")


def test_durability_across_reopen(holder, ex, tmp_path):
    setup_index(holder)
    ex.execute("i", "Set(3, f=10)")
    ex.execute("i", f"Set({SHARD_WIDTH + 7}, f=10)")
    holder.reopen()
    ex2 = Executor(holder, translate_store=TranslateStore().open(), workers=0)
    try:
        assert list(ex2.execute("i", "Row(f=10)")[0].columns()) == [3, SHARD_WIDTH + 7]
    finally:
        ex2.close()


def test_topn_chunked_matches_single_chunk(holder, ex, monkeypatch):
    """A tiny PILOSA_TOPN_CHUNK_BYTES forces the TopN phases through many
    small device chunks; results must equal the single-chunk run (the
    chunk bound exists so 256-shard stacks don't build 16 GiB programs)."""
    import numpy as np

    setup_index(holder)
    rng = np.random.default_rng(23)
    fld = holder.index("i").field("f")
    g = holder.index("i").field("g")
    n_rows, n_shards = 40, 2
    rows, cols = [], []
    for row in range(n_rows):
        for s in range(n_shards):
            c = rng.choice(4096, size=32 + row, replace=False)
            rows.extend([row] * len(c))
            cols.extend(int(s * SHARD_WIDTH + x) for x in c)
    fld.import_bits(rows, cols)
    gc = [int(s * SHARD_WIDTH + x)
          for s in range(n_shards) for x in rng.choice(4096, 1200, replace=False)]
    g.import_bits([3] * len(gc), gc)

    q = "TopN(f, Row(g=3), n=6)"
    want = [(p.id, p.count) for p in ex.execute("i", q)[0]]
    assert want, "TopN returned nothing; test data broken"

    # 16 rows per chunk at 2 shards x 128 KiB planes.
    monkeypatch.setenv("PILOSA_TOPN_CHUNK_BYTES", str(16 * 2 * 32768 * 4))
    from pilosa_tpu import executor as ex_mod

    assert ex_mod._topn_chunk(n_shards) == 16
    got = [(p.id, p.count) for p in ex.execute("i", q)[0]]
    assert got == want, (got, want)
