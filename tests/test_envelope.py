"""Private-plane protobuf envelope: round-trips + reference wire pinning.

The golden byte strings are hand-derived from the REFERENCE schema
(/root/reference/internal/private.proto field numbers and
/root/reference/broadcast.go:52-69 type-byte order), not from our
generated code — so a regression in either the .proto port or the
type-byte table fails against independently-computed bytes.
"""

import json

import pytest

from pilosa_tpu.server.proto import envelope as env


ROUND_TRIP_CASES = [
    {"type": "create-shard", "index": "i", "shard": 7, "field": "f"},
    {"type": "create-index", "index": "i", "options": {"keys": True}},
    {"type": "delete-index", "index": "i"},
    {
        "type": "create-field", "index": "i", "field": "f",
        "options": {"type": "int", "cacheType": "", "cacheSize": 0,
                    "min": -10, "max": 100, "timeQuantum": "", "keys": False},
    },
    {"type": "delete-field", "index": "i", "field": "f"},
    {"type": "create-view", "index": "i", "field": "f", "view": "standard"},
    {"type": "delete-view", "index": "i", "field": "f", "view": "v2"},
    {
        "type": "cluster-status", "state": "NORMAL",
        "nodes": [
            {"id": "node0", "uri": "h0:101", "isCoordinator": True},
            {"id": "node1", "uri": "h1:102", "isCoordinator": False,
             "processIdx": 0},
        ],
    },
    {
        "type": "resize-instruction", "jobID": "00c0ffee", "nodeID": "n1",
        "coordinatorID": "n0", "coordinatorURI": "h0:101",
        "schema": [{
            "name": "i", "options": {"keys": False},
            "fields": [{
                "name": "f",
                "options": {"type": "set", "cacheType": "ranked",
                            "cacheSize": 50000, "min": 0, "max": 0,
                            "timeQuantum": "", "keys": False},
                "views": [{"name": "standard"}],
            }],
        }],
        "sources": [{"sourceNodeID": "n0", "index": "i", "field": "f",
                     "view": "standard", "shard": 3}],
        "nodeURIs": {"n0": "h0:101", "n1": "h1:102"},
        "maxShards": {"i": 9},
    },
    {"type": "resize-complete", "jobID": "00c0ffee", "nodeID": "n1"},
    {"type": "set-coordinator", "nodeID": "node1"},
    {"type": "node-state", "nodeID": "node0", "state": "READY"},
    {"type": "recalculate-caches"},
    {"type": "node-join",
     "node": {"id": "n2", "uri": "h2:103", "isCoordinator": False}},
    {"type": "node-leave", "nodeID": "n2"},
    {
        "type": "node-status",
        "node": {"id": "n0", "uri": "h0:101", "isCoordinator": True},
        "maxShards": {"i": 4},
        "schema": [{"name": "i", "options": {"keys": True}, "fields": []}],
    },
]


@pytest.mark.parametrize(
    "msg", ROUND_TRIP_CASES, ids=[m["type"] for m in ROUND_TRIP_CASES])
def test_round_trip(msg):
    buf = env.encode_message(msg)
    assert buf[0] != env.TYPE_JSON_EXT, "mapped types must ride protobuf"
    got = env.decode_message(buf)
    for key, want in msg.items():
        assert got[key] == want, f"{key}: {got[key]!r} != {want!r}"


def test_update_coordinator_decodes():
    # Reference UpdateCoordinatorMessage (type byte 11) must decode to the
    # same dispatch as set-coordinator, not raise on an unknown type.
    from pilosa_tpu.server.proto import private_pb2 as pb

    m = pb.UpdateCoordinatorMessage()
    m.New.ID = "n9"
    got = env.decode_message(
        bytes([env.TYPE_UPDATE_COORDINATOR]) + m.SerializeToString())
    assert got == {"type": "set-coordinator", "nodeID": "n9"}


def test_node_update_event_decodes_as_update_not_leave():
    # Reference nodeUpdate (event.go:23) must never decode as a leave.
    from pilosa_tpu.server.proto import private_pb2 as pb

    m = pb.NodeEventMessage()
    m.Event = env.EVENT_UPDATE
    m.Node.ID = "n1"
    got = env.decode_message(bytes([env.TYPE_NODE_EVENT]) + m.SerializeToString())
    assert got["type"] == "node-update" and got["node"]["id"] == "n1"


def test_json_extension_frame():
    msg = {"type": "collective-exec", "seq": 3, "descriptor": {"x": [1, 2]}}
    buf = env.encode_message(msg)
    assert buf[0] == env.TYPE_JSON_EXT
    assert env.decode_message(buf) == msg


def test_golden_node_state_bytes():
    # broadcast.go: messageTypeNodeState = 12; NodeStateMessage{NodeID=1,
    # State=2} (private.proto:102-105). Hand-encoded proto3 wire format.
    buf = env.encode_message(
        {"type": "node-state", "nodeID": "n1", "state": "READY"})
    assert buf == bytes([12]) + b"\x0a\x02n1\x12\x05READY"


def test_golden_create_view_bytes():
    # messageTypeCreateView = 5; CreateViewMessage{Index=1, Field=2,
    # View=3} (private.proto:124-128).
    buf = env.encode_message(
        {"type": "create-view", "index": "i", "field": "f", "view": "sv"})
    assert buf == bytes([5]) + b"\x0a\x01i\x12\x01f\x1a\x02sv"


def test_golden_cluster_status_bytes():
    # messageTypeClusterStatus = 7; ClusterStatus{ClusterID=1, State=2,
    # Nodes=3}, Node{ID=1, URI=2, IsCoordinator=3}, URI{Scheme=1, Host=2,
    # Port=3} (private.proto:85-99, 111-115).
    buf = env.encode_message({
        "type": "cluster-status", "state": "NORMAL",
        "nodes": [{"id": "a", "uri": "h:9", "isCoordinator": True}],
    })
    node = (b"\x0a\x01a"                       # ID="a"
            b"\x12\x0b"                        # URI, len 11
            b"\x0a\x04http\x12\x01h\x18\x09"   # Scheme/Host/Port
            b"\x18\x01")                       # IsCoordinator=true
    want = (bytes([7]) + b"\x12\x06NORMAL"
            + b"\x1a" + bytes([len(node)]) + node)
    assert buf == want


def test_reference_parser_sees_create_shard():
    # Our create-shard carries extension fields (Field=15/View=16) that a
    # reference parser must skip: re-parsing through the schema-declared
    # message yields exactly Index + Shard.
    from pilosa_tpu.server.proto import private_pb2 as pb

    buf = env.encode_message(
        {"type": "create-shard", "index": "idx", "shard": 5, "field": "f",
         "view": "standard"})
    m = pb.CreateShardMessage()
    m.ParseFromString(buf[1:])
    assert m.Index == "idx" and m.Shard == 5


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        env.decode_message(b"")
    with pytest.raises(ValueError):
        env.decode_message(bytes([200]) + b"xx")


def test_cluster_plane_over_protobuf(tmp_path, monkeypatch):
    """A live 2-node exchange with the default (protobuf) wire format:
    create-field broadcast from node0 must materialize on node1, and the
    messages on the wire must actually be envelope frames (encode_message
    is spied to prove the protobuf path carried them)."""
    import socket
    import time

    from pilosa_tpu.cluster.hash import ModHasher
    from pilosa_tpu.server.client import InternalClient
    from pilosa_tpu.server.server import Server

    monkeypatch.delenv("PILOSA_TPU_CLUSTER_JSON", raising=False)
    seen = []
    real_encode = env.encode_message
    monkeypatch.setattr(
        env, "encode_message",
        lambda msg: seen.append(msg["type"]) or real_encode(msg))

    def free_port():
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    try:
        for i, port in enumerate(ports):
            s = Server(
                data_dir=str(tmp_path / f"node{i}"), port=port,
                cluster_hosts=hosts, hasher=ModHasher(),
                cache_flush_interval=0, executor_workers=0,
            )
            s.open()
            servers.append(s)
        c = InternalClient()
        c.create_index(hosts[0], "pbix")
        c.create_field(hosts[0], "pbix", "pf")
        deadline = time.time() + 5
        while time.time() < deadline:
            if servers[1].holder.field("pbix", "pf") is not None:
                break
            time.sleep(0.05)
        assert servers[1].holder.field("pbix", "pf") is not None
        assert "create-index" in seen and "create-field" in seen
    finally:
        for s in servers:
            s.close()
