"""pmux internal transport tests (docs/transport.md).

Four tiers:
  - pure units: the host:port splitter the envelope codec shares, the
    meta/frame codec, TransportConfig validation;
  - framing over a socketpair: torn frames at EVERY boundary (header,
    mid-payload, crc) surface as typed MuxProtocolError, clean EOF as
    MuxClosed, and the combining writer really batches;
  - client/server halves over real sockets: multiplexed out-of-order
    responses, handshake rejection (version/key), demotion + fallback
    signalling, per-peer teardown isolation, and the three mux
    failpoints (mux-handshake / mux-frame-send / mux-frame-recv);
  - full 3-node clusters: serving entirely over mux, a mixed
    mux/HTTP cluster riding handshake fallback, and the seed-pinned
    chaos twin of the FAULT schedule with the transport enabled.
"""

import json
import socket
import struct
import threading
import time
import urllib.request

import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.health import ResilienceConfig
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.server.mux import (
    HEADER_LEN,
    KIND_CALL,
    KIND_HELLO_ACK,
    KIND_RESP,
    M_EPOCH,
    M_HEADERS,
    M_METHOD,
    M_PATH,
    M_STATUS,
    M_VERSION,
    MUX_VERSION,
    MuxClosed,
    MuxError,
    MuxFrameTooLarge,
    MuxProtocolError,
    MuxUnavailable,
    MuxUnsent,
    MuxServer,
    MuxTransport,
    TransportConfig,
    TransportStats,
    _FrameIO,
    _Waiter,
    _meta_to_headers,
    _req_meta,
    decode_meta,
    encode_frame,
    encode_meta,
    split_host_port,
)
from pilosa_tpu.server.server import Server

from .conftest import FakeClock
from .test_chaos import _run_chaos, free_port

# Fake peers listen directly on a free port P and advertise netloc
# localhost:(P - OFF), so the transport's (port + offset) dial lands on
# the listener. The netloc port itself is never bound.
OFF = 7


def _cfg(**kw):
    base = dict(enabled=True, port_offset=OFF, max_frames_inflight=64,
                frame_max_bytes=1 << 20, handshake_timeout=2.0)
    base.update(kw)
    return TransportConfig(**base).validate()


# ------------------------------------------------------------------ units


def test_split_host_port_ipv6():
    """The shared splitter (mux dialer + protobuf envelope codec — one
    parse, not three) handles every internal netloc shape."""
    assert split_host_port("[2001:db8::1]:10101") == ("2001:db8::1", 10101)
    assert split_host_port("[::1]") == ("::1", None)
    assert split_host_port("localhost:10101") == ("localhost", 10101)
    assert split_host_port("::1") == ("::1", None)
    assert split_host_port("2001:db8::1") == ("2001:db8::1", None)
    assert split_host_port("localhost") == ("localhost", None)
    with pytest.raises(ValueError):
        split_host_port("[::1:10101")  # unclosed bracket
    with pytest.raises(ValueError):
        split_host_port("[::1]x:1")  # junk between bracket and colon
    with pytest.raises(ValueError):
        split_host_port("host:notaport")


def test_meta_codec_roundtrip():
    fields = {M_METHOD: b"POST", M_PATH: b"/index/t/query?remote=true",
              M_EPOCH: b"7", M_HEADERS: b"", M_STATUS: b"200"}
    assert decode_meta(encode_meta(fields)) == fields
    assert decode_meta(encode_meta({})) == {}


def test_meta_codec_rejects_torn_blocks():
    good = encode_meta({M_METHOD: b"GET", M_PATH: b"/status"})
    with pytest.raises(MuxProtocolError):
        decode_meta(good[:-1])  # field overruns the block
    with pytest.raises(MuxProtocolError):
        decode_meta(good + b"\x00")  # trailing bytes after last field
    with pytest.raises(MuxProtocolError):
        decode_meta(struct.pack("!B", 2) + struct.pack("!BH", 1, 1))


def test_req_meta_headers_roundtrip():
    """Known X-Pilosa-* headers become fixed binary fields; the rest
    ride the JSON blob; the server side reconstructs the exact header
    dict Handler.dispatch expects, with the handshake key stamped in."""
    meta = _req_meta(
        "POST", "/index/t/query?remote=true", "application/json", "x-wire",
        headers={"X-Pilosa-Epoch": "9", "X-Pilosa-Trace": "abc",
                 "X-Pilosa-Deadline": "1.5", "X-Custom": "z"},
    )
    assert meta[M_EPOCH] == b"9"
    assert json.loads(meta[M_HEADERS]) == {"x-custom": "z"}
    headers = _meta_to_headers(meta, "sekrit")
    assert headers["x-pilosa-epoch"] == "9"
    assert headers["x-pilosa-trace"] == "abc"
    assert headers["x-pilosa-deadline"] == "1.5"
    assert headers["x-custom"] == "z"
    assert headers["x-pilosa-key"] == "sekrit"
    assert headers["content-type"] == "application/json"
    assert headers["accept"] == "x-wire"


def test_transport_config_validation():
    with pytest.raises(ValueError, match="port-offset"):
        TransportConfig(port_offset=0).validate()
    with pytest.raises(ValueError, match="max-frames-inflight"):
        TransportConfig(max_frames_inflight=0).validate()
    with pytest.raises(ValueError, match="frame-max-bytes"):
        TransportConfig(frame_max_bytes=1).validate()
    with pytest.raises(ValueError, match="handshake-timeout"):
        TransportConfig(handshake_timeout=0).validate()
    TransportConfig().validate()  # defaults are valid


# ------------------------------------------------- framing over socketpair


def _pair(frame_max=1 << 20):
    a, b = socket.socketpair()
    return _FrameIO(a, frame_max), _FrameIO(b, frame_max), a, b


def test_frame_roundtrip_over_socketpair():
    wio, rio, _, _ = _pair()
    try:
        meta = {M_METHOD: b"POST", M_PATH: b"/x"}
        wio.send_frame(KIND_CALL, 42, meta, b"payload-bytes")
        kind, sid, got_meta, payload = rio.read_frame()
        assert (kind, sid, got_meta, payload) == (
            KIND_CALL, 42, meta, b"payload-bytes")
    finally:
        wio.close()
        rio.close()


def test_clean_eof_is_mux_closed():
    wio, rio, _, _ = _pair()
    wio.close()
    try:
        with pytest.raises(MuxClosed):
            rio.read_frame()
    finally:
        rio.close()


def test_torn_frame_every_boundary():
    """EOF inside the header, inside the payload, and a corrupted crc
    each raise the TYPED protocol error naming the boundary."""
    frame = encode_frame(KIND_RESP, 1, {M_STATUS: b"200"}, b"0123456789")

    # 1. torn inside the fixed header
    wio, rio, a, _ = _pair()
    a.sendall(frame[:HEADER_LEN - 3])
    wio.close()
    with pytest.raises(MuxProtocolError, match="frame header"):
        rio.read_frame()
    rio.close()

    # 2. torn mid-payload (full header, partial body)
    wio, rio, a, _ = _pair()
    a.sendall(frame[:HEADER_LEN + 4])
    wio.close()
    with pytest.raises(MuxProtocolError, match="frame body"):
        rio.read_frame()
    rio.close()

    # 3. crc corruption (whole frame arrives, last payload byte flipped)
    wio, rio, a, _ = _pair()
    a.sendall(frame[:-1] + bytes([frame[-1] ^ 0xFF]))
    wio.close()
    with pytest.raises(MuxProtocolError, match="crc mismatch"):
        rio.read_frame()
    rio.close()

    # 4. header lies: length over frame-max-bytes
    wio, rio, a, _ = _pair(frame_max=4096)
    hdr = struct.pack("!IIBBHI", 1 << 20, 1, KIND_RESP, 0, 0, 0)
    a.sendall(hdr)
    with pytest.raises(MuxProtocolError, match="frame-max-bytes"):
        rio.read_frame()
    wio.close()
    rio.close()

    # 5. header lies: meta_len exceeds frame length
    wio, rio, a, _ = _pair()
    hdr = struct.pack("!IIBBHI", 4, 1, KIND_RESP, 0, 9, 0)
    a.sendall(hdr + b"abcd")
    with pytest.raises(MuxProtocolError, match="meta_len"):
        rio.read_frame()
    wio.close()
    rio.close()


def test_combining_writer_batches_queued_frames():
    """Frames queued while another thread is inside sendall ride that
    thread's NEXT combined send — the writev-style fan-out batch."""

    class GateSock:
        def __init__(self):
            self.sends = []
            self.entered = threading.Event()
            self.release = threading.Event()
            self.first = True

        def sendall(self, data):
            self.sends.append(bytes(data))
            if self.first:
                self.first = False
                self.entered.set()
                assert self.release.wait(5.0)

        def close(self):
            pass

    gate = GateSock()
    io = _FrameIO(gate, 1 << 20)
    f1 = encode_frame(KIND_CALL, 1, {}, b"one")
    f2 = encode_frame(KIND_CALL, 2, {}, b"two")
    f3 = encode_frame(KIND_CALL, 3, {}, b"three")

    t = threading.Thread(
        target=io.send_frame, args=(KIND_CALL, 1, {}, b"one"), daemon=True)
    t.start()
    assert gate.entered.wait(5.0)
    # Flusher is parked inside sendall: these two only enqueue.
    io.send_frame(KIND_CALL, 2, {}, b"two")
    io.send_frame(KIND_CALL, 3, {}, b"three")
    gate.release.set()
    t.join(5.0)
    assert not t.is_alive()
    assert gate.sends == [f1, f2 + f3], "queued frames did not combine"


def test_flush_failure_is_maybe_sent_then_unsent():
    """A sendall fault surfaces as plain MuxError (the frame may have
    ridden an earlier chunk — NOT safe to replay); once the writer is
    dead, subsequent sends never enqueue and are typed MuxUnsent."""
    a, b = socket.socketpair()
    io = _FrameIO(a, 1 << 20)
    a.close()  # next sendall raises
    with pytest.raises(MuxError) as ei:
        io.send_frame(KIND_CALL, 1, {M_METHOD: b"GET"}, b"x")
    assert not isinstance(ei.value, MuxUnsent)
    with pytest.raises(MuxUnsent, match="connection already failed"):
        io.send_frame(KIND_CALL, 2, {M_METHOD: b"GET"}, b"x")
    b.close()


def test_send_stats_only_bumped_on_successful_flush():
    """frames_sent/bytes_sent count only frames whose sendall succeeded
    — a failed flush must not inflate the wire counters."""
    stats = TransportStats()
    a, b = socket.socketpair()
    io = _FrameIO(a, 1 << 20, stats)
    io.send_frame(KIND_CALL, 1, {M_METHOD: b"GET"}, b"x")
    assert stats.snapshot()["frames_sent"] == 1
    sent_bytes = stats.snapshot()["bytes_sent"]
    a.close()
    with pytest.raises(MuxError):
        io.send_frame(KIND_CALL, 2, {M_METHOD: b"GET"}, b"x")
    snap = stats.snapshot()
    assert snap["frames_sent"] == 1 and snap["bytes_sent"] == sent_bytes
    b.close()


def test_oversized_frame_is_typed_before_enqueue():
    a, b = socket.socketpair()
    io = _FrameIO(a, 4096)
    with pytest.raises(MuxFrameTooLarge):
        io.send_frame(KIND_CALL, 1, {}, b"x" * 8192)
    # Connection stays healthy: a normal frame still goes out.
    io.send_frame(KIND_CALL, 2, {}, b"ok")
    a.close()
    b.close()


# --------------------------------------------- send-phase retry policy


class _ScriptedConn:
    """Stub _ClientConn: raises the scripted errors, then answers 200."""

    closed = False

    def __init__(self, errs):
        self.errs = list(errs)
        self.calls = 0

    def send_call(self, meta_fields, payload):
        self.calls += 1
        if self.errs:
            raise self.errs.pop(0)
        w = _Waiter()
        w.result = (KIND_RESP, {M_STATUS: b"200"}, b"ok")
        w.event.set()
        return 1, w

    def abandon(self, sid):
        pass


def test_maybe_sent_failure_is_never_silently_retried(monkeypatch):
    """The high-stakes rule: a MuxError raised AFTER the frame may have
    hit the wire (combining-writer flush fault) must surface without a
    redial — a replayed POST could double-apply a hint/cluster op the
    peer already dispatched (mirrors the HTTP non-GET policy)."""
    tr = MuxTransport(_cfg(), timeout=1.0)
    conn = _ScriptedConn([MuxError("frame send failed: injected")])
    monkeypatch.setattr(tr, "_conn", lambda netloc: conn)
    try:
        with pytest.raises(MuxError):
            tr.request("POST", "localhost:1", "/internal/hints", body=b"op")
        assert conn.calls == 1, "maybe-sent POST was silently replayed"
    finally:
        tr.close()


def test_unsent_failure_gets_single_silent_redial(monkeypatch):
    """MuxUnsent (pre-enqueue failure) is provably unsent: one silent
    retry for ANY method, the HTTP fresh-connection parity."""
    tr = MuxTransport(_cfg(), timeout=1.0)
    conn = _ScriptedConn([MuxUnsent("connection closed")])
    monkeypatch.setattr(tr, "_conn", lambda netloc: conn)
    try:
        status, data, _ = tr.request(
            "POST", "localhost:1", "/internal/hints", body=b"op")
        assert (status, data, conn.calls) == (200, b"ok", 2)
        # A persistently-unsent failure still surfaces after the one
        # retry.
        conn.errs = [MuxUnsent("connection closed")] * 2
        with pytest.raises(MuxUnsent):
            tr.request("POST", "localhost:1", "/internal/hints", body=b"op")
    finally:
        tr.close()


def test_frame_too_large_from_send_falls_back_to_http(monkeypatch):
    """When the pre-send size guard under-counts, the typed
    MuxFrameTooLarge (nothing enqueued) converts to MuxUnavailable so
    the request safely rides HTTP instead of failing."""
    tr = MuxTransport(_cfg(), timeout=1.0)
    conn = _ScriptedConn([MuxFrameTooLarge("frame of 9999 bytes exceeds")])
    monkeypatch.setattr(tr, "_conn", lambda netloc: conn)
    try:
        with pytest.raises(MuxUnavailable):
            tr.request("POST", "localhost:1", "/import", body=b"op")
        assert conn.calls == 1
    finally:
        tr.close()


# ------------------------------------- client/server halves, real sockets


class FakePeer:
    """Accepts mux connections, answers the handshake, then hands each
    connection's framer to `script`. Used to put the CLIENT half under
    misbehaving peers (torn frames, held responses, wrong versions)
    that a real MuxServer would never emit."""

    def __init__(self, script=None, ack_meta=None):
        self.sock = socket.create_server(("localhost", 0), backlog=4)
        self.port = self.sock.getsockname()[1]
        self.netloc = f"localhost:{self.port - OFF}"
        self.script = script
        self.ack_meta = ack_meta
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        io = _FrameIO(conn, 1 << 20)
        try:
            io.read_frame()  # HELLO
            ack = self.ack_meta or {
                M_VERSION: str(MUX_VERSION).encode("ascii")}
            io.send_frame(KIND_HELLO_ACK, 0, ack, b"")
            if self.script is not None:
                self.script(io)
        except (MuxError, OSError):
            pass
        finally:
            io.close()

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def _echo_script(io):
    while True:
        kind, sid, meta, payload = io.read_frame()
        io.send_frame(KIND_RESP, sid, {M_STATUS: b"200"}, payload)


def test_torn_resp_tears_down_only_that_peer():
    """A torn RESP from peer B fails B's pending streams with the typed
    protocol error and tears down B's ONE connection; peer A's live
    connection is untouched and keeps serving."""

    def torn_script(io):
        io.read_frame()  # the pending CALL
        frame = encode_frame(KIND_RESP, 1, {M_STATUS: b"200"}, b"x")
        io.sock.sendall(frame[:-1] + bytes([frame[-1] ^ 0xFF]))  # bad crc

    a, b = FakePeer(_echo_script), FakePeer(torn_script)
    tr = MuxTransport(_cfg(), timeout=10.0)
    try:
        assert tr.request("GET", a.netloc, "/s")[0:2] == (200, b"")
        conn_a = tr._conns[a.netloc]
        with pytest.raises(MuxProtocolError, match="crc mismatch"):
            tr.request("GET", b.netloc, "/s")
        assert tr.stats.snapshot()["protocol_errors"] == 1
        assert tr._conns[b.netloc].closed
        # Peer A: same connection object, still serving.
        assert tr.request("GET", a.netloc, "/s", body=b"hi")[1] == b"hi"
        assert tr._conns[a.netloc] is conn_a and not conn_a.closed
    finally:
        tr.close()
        a.close()
        b.close()


def test_pending_streams_fail_typed_on_teardown():
    """Streams parked in waiters when the connection dies get the typed
    error — nobody blocks for the full request timeout."""
    hold = threading.Event()

    def hold_then_die(io):
        io.read_frame()
        hold.wait(5.0)
        io.sock.sendall(b"\x00" * 5)  # partial header, then close

    p = FakePeer(hold_then_die)
    tr = MuxTransport(_cfg(), timeout=30.0)
    errs = []

    def call():
        try:
            tr.request("GET", p.netloc, "/s")
        except MuxError as e:
            errs.append(e)

    t = threading.Thread(target=call, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while p.netloc not in tr._conns and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # let the CALL reach the peer
        hold.set()
        t.join(5.0)
        assert not t.is_alive(), "waiter did not fail on teardown"
        assert len(errs) == 1 and isinstance(errs[0], MuxProtocolError)
    finally:
        tr.close()
        p.close()


def test_handshake_version_mismatch_demotes_with_backoff():
    clock = FakeClock()
    p = FakePeer(ack_meta={M_VERSION: b"99"})
    tr = MuxTransport(_cfg(), timeout=5.0, clock=clock.time)
    try:
        with pytest.raises(MuxUnavailable, match="version mismatch"):
            tr.request("GET", p.netloc, "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 1
        # Inside the demotion window: immediate MuxUnavailable, no dial.
        with pytest.raises(MuxUnavailable, match="demoted"):
            tr.request("GET", p.netloc, "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 1
        # Past the window the transport really re-dials (the peer still
        # speaks the wrong version, so the handshake fails AGAIN rather
        # than short-circuiting on the expired demotion entry).
        clock.advance(MuxTransport.DEMOTE_S + 0.1)
        with pytest.raises(MuxUnavailable, match="version mismatch"):
            tr.request("GET", p.netloc, "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 2
    finally:
        tr.close()
        p.close()


def test_handshake_key_mismatch_rejected_by_real_server():
    srv, netloc = _real_mux_server(key="right-key")
    tr = MuxTransport(_cfg(), key="wrong-key", timeout=5.0)
    try:
        with pytest.raises(MuxUnavailable, match="cluster key mismatch"):
            tr.request("GET", netloc, "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 1
    finally:
        tr.close()
        srv.close()


def test_nothing_listening_falls_back():
    tr = MuxTransport(_cfg(), timeout=2.0)
    port = free_port()
    try:
        with pytest.raises(MuxUnavailable):
            tr.request("GET", f"localhost:{port - OFF}", "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 1
    finally:
        tr.close()


def test_disabled_transport_is_unavailable():
    tr = MuxTransport(_cfg(enabled=False))
    try:
        with pytest.raises(MuxUnavailable, match="disabled"):
            tr.request("GET", "localhost:1", "/s")
    finally:
        tr.close()


def test_oversized_request_rides_http():
    tr = MuxTransport(_cfg(frame_max_bytes=4096))
    try:
        with pytest.raises(MuxUnavailable, match="frame-max-bytes"):
            tr.request("POST", "localhost:1", "/import", body=b"x" * 8192)
    finally:
        tr.close()


class FakeHandler:
    """Just enough of Handler.dispatch for transport-level tests."""

    def __init__(self):
        self.calls = []
        self.gate = None  # Event: when set on self, /slow waits on it

    def dispatch(self, method, path, query, body, headers=None):
        self.calls.append((method, path, query, body, dict(headers or {})))
        if path == "/slow" and self.gate is not None:
            assert self.gate.wait(10.0)
        if path == "/boom":
            raise RuntimeError("kapow")
        if path == "/big":
            return (200, "application/octet-stream", b"x" * 8192)
        if path == "/echo":
            return (200, "application/octet-stream", body, {"X-Extra": "1"})
        return (200, "application/json",
                json.dumps({"path": path}).encode("utf-8"))


def _real_mux_server(key="", config=None, handler=None):
    """MuxServer on a free port; returns (server, advertised netloc)."""
    config = config or _cfg()
    handler = handler or FakeHandler()
    for _ in range(16):
        port = free_port()
        srv = MuxServer(handler, config, key=key)
        srv.open("localhost", port - OFF)
        if srv.port is not None:
            return srv, f"localhost:{port - OFF}"
        srv.close()
    raise RuntimeError("could not bind a mux listener")


def test_mux_request_end_to_end():
    """CALL meta reconstructs the full HTTP-shaped request on the server
    (method, path, query, body, headers incl. the handshake key) and
    RESP carries status, content-type, and extra headers back."""
    h = FakeHandler()
    srv, netloc = _real_mux_server(key="k1", handler=h)
    tr = MuxTransport(_cfg(), key="k1", timeout=10.0)
    try:
        status, data, rheaders = tr.request(
            "POST", netloc, "/echo?x=1&x=2&y=z", body=b"abc",
            content_type="application/octet-stream", accept="x-wire",
            headers={"X-Pilosa-Epoch": "7", "X-Custom": "v"})
        assert (status, data) == (200, b"abc")
        assert rheaders["x-extra"] == "1"
        assert rheaders["content-type"] == "application/octet-stream"
        method, path, query, body, headers = h.calls[0]
        assert (method, path, body) == ("POST", "/echo", b"abc")
        assert query == {"x": ["1", "2"], "y": ["z"]}
        assert headers["x-pilosa-epoch"] == "7"
        assert headers["x-custom"] == "v"
        assert headers["x-pilosa-key"] == "k1"
        # Unhandled handler exception -> 500 + JSON error, like HTTP.
        status, data, _ = tr.request("GET", netloc, "/boom")
        assert status == 500 and b"kapow" in data
    finally:
        tr.close()
        srv.close()


def test_trailing_slash_path_normalized_like_http():
    """The mux server applies the HTTP server's path normalization, so
    an internal URL with a trailing slash routes identically on both
    transports."""
    h = FakeHandler()
    srv, netloc = _real_mux_server(handler=h)
    tr = MuxTransport(_cfg(), timeout=5.0)
    try:
        status, data, _ = tr.request("GET", netloc, "/echo/?x=1")
        assert status == 200
        _, path, query, _, _ = h.calls[0]
        assert path == "/echo"
        assert query == {"x": ["1"]}
    finally:
        tr.close()
        srv.close()


def test_oversized_response_fails_fast_not_timeout():
    """A response bigger than frame-max-bytes must not hang the waiter
    until timeout: the server answers with a small error RESP. A GET
    (idempotent) transparently falls back to HTTP (MuxUnavailable); a
    POST surfaces a fast 500 — the call DID run, so replaying it is
    not safe."""
    cfg = _cfg(frame_max_bytes=4096)
    h = FakeHandler()
    srv, netloc = _real_mux_server(handler=h, config=cfg)
    tr = MuxTransport(_cfg(frame_max_bytes=4096), timeout=30.0)
    try:
        start = time.monotonic()
        with pytest.raises(MuxUnavailable, match="retrying over HTTP"):
            tr.request("GET", netloc, "/big")
        status, data, _ = tr.request("POST", netloc, "/big", body=b"go")
        assert status == 500 and b"undeliverable" in data
        # A POST whose replay is harmless (PQL query forward) opts into
        # the same HTTP escape via the idempotent hint.
        with pytest.raises(MuxUnavailable, match="retrying over HTTP"):
            tr.request("POST", netloc, "/big", body=b"go", idempotent=True)
        assert time.monotonic() - start < 10.0, "waiter hung until timeout"
        # The connection survived: a fitting response still serves.
        assert tr.request("GET", netloc, "/fast")[0] == 200
    finally:
        tr.close()
        srv.close()


def test_non_ascii_cluster_key_handshake():
    """The key rides the binary meta slot as utf-8 and the server
    compares BYTES: a non-ASCII key handshakes fine (no TypeError
    crashing the connection thread), and a mismatch is a clean
    rejection + demotion."""
    srv, netloc = _real_mux_server(key="clé-秘密")
    tr = MuxTransport(_cfg(), key="clé-秘密", timeout=5.0)
    tr2 = MuxTransport(_cfg(), key="clé-秘密-wrong", timeout=5.0)
    try:
        assert tr.request("GET", netloc, "/s")[0] == 200
        with pytest.raises(MuxUnavailable, match="key mismatch"):
            tr2.request("GET", netloc, "/s")
        assert tr2.stats.snapshot()["handshake_fallbacks"] == 1
    finally:
        tr.close()
        tr2.close()
        srv.close()


def test_demotion_honored_after_waiting_on_dial_lock():
    """A thread parked on the per-netloc dial lock while another
    thread's dial fails must honor the fresh demotion instead of
    immediately re-dialing the down peer (breaker-style backoff)."""
    clock = FakeClock()
    tr = MuxTransport(_cfg(), timeout=1.0, clock=clock)
    dials = []

    def fake_dial(netloc, had_prior):
        dials.append(netloc)
        raise MuxUnavailable("should not dial")

    tr._dial = fake_dial
    netloc = "peer:1"
    lock = tr._dial_locks.setdefault(netloc, threading.Lock())
    result = {}

    def go():
        try:
            tr._conn(netloc)
        except Exception as e:  # noqa: BLE001 - recording for assert
            result["e"] = e

    lock.acquire()
    try:
        t = threading.Thread(target=go, daemon=True)
        t.start()
        # Let the worker pass the pre-lock checks and park on the lock.
        time.sleep(0.2)
        # Another thread's dial "failed": the peer is now demoted.
        with tr._mu:
            tr._demoted_until[netloc] = clock() + 5.0
    finally:
        lock.release()
    t.join(5.0)
    assert isinstance(result.get("e"), MuxUnavailable)
    assert "demoted" in str(result["e"])
    assert dials == [], "re-dialed a freshly-demoted peer"
    tr.close()


def test_multiplexed_out_of_order_responses_share_one_socket():
    """A slow and a fast request share the connection; the fast response
    overtakes the slow one and each lands on its own waiter."""
    h = FakeHandler()
    h.gate = threading.Event()
    srv, netloc = _real_mux_server(handler=h)
    tr = MuxTransport(_cfg(), timeout=10.0)
    slow_result = {}

    def slow_call():
        slow_result["r"] = tr.request("GET", netloc, "/slow")

    t = threading.Thread(target=slow_call, daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 5.0
        while not any(c[1] == "/slow" for c in h.calls):
            assert time.monotonic() < deadline, "slow call never arrived"
            time.sleep(0.01)
        # Fast request completes while /slow is parked server-side.
        assert tr.request("GET", netloc, "/fast")[0] == 200
        assert "r" not in slow_result
        h.gate.set()
        t.join(5.0)
        assert slow_result["r"][0] == 200
        snap = tr.stats.snapshot()
        assert snap["connects"] == 1, "requests did not share one socket"
        assert snap["requests_mux"] == 2
        assert snap["inflight_hwm"] >= 2
    finally:
        h.gate.set()
        tr.close()
        srv.close()


def test_inflight_cap_signals_http_fallback():
    h = FakeHandler()
    h.gate = threading.Event()
    srv, netloc = _real_mux_server(
        handler=h, config=_cfg(max_frames_inflight=1))
    tr = MuxTransport(_cfg(max_frames_inflight=1), timeout=10.0)
    t = threading.Thread(
        target=lambda: tr.request("GET", netloc, "/slow"), daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 5.0
        while not any(c[1] == "/slow" for c in h.calls):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        with pytest.raises(MuxUnavailable, match="max-frames-inflight"):
            tr.request("GET", netloc, "/fast")
    finally:
        h.gate.set()
        t.join(5.0)
        tr.close()
        srv.close()


# ----------------------------------------------------------- failpoints


def test_mux_handshake_failpoint_demotes():
    srv, netloc = _real_mux_server()
    tr = MuxTransport(_cfg(), timeout=5.0)
    try:
        failpoints.configure(f"mux-handshake@{netloc}", "drop")
        with pytest.raises(MuxUnavailable):
            tr.request("GET", netloc, "/s")
        assert tr.stats.snapshot()["handshake_fallbacks"] == 1
        assert failpoints.hits(f"mux-handshake@{netloc}") == 1
    finally:
        failpoints.reset()
        tr.close()
        srv.close()


def test_mux_frame_send_failpoint_single_retry_parity():
    """A provably-unsent send fault is retried silently ONCE (the HTTP
    fresh-connection parity); a persistent fault surfaces typed."""
    srv, netloc = _real_mux_server()
    tr = MuxTransport(_cfg(), timeout=5.0)
    try:
        # count=1: first attempt dropped, silent retry succeeds.
        failpoints.configure(f"mux-frame-send@{netloc}", "drop", count=1)
        assert tr.request("GET", netloc, "/s")[0] == 200
        assert failpoints.hits(f"mux-frame-send@{netloc}") == 2
        # Unlimited drop: both attempts fail -> typed MuxError, which the
        # client surfaces as status-0 ClientError (breaker evidence).
        failpoints.configure(f"mux-frame-send@{netloc}", "drop")
        with pytest.raises(MuxError):
            tr.request("GET", netloc, "/s")
    finally:
        failpoints.reset()
        tr.close()
        srv.close()


def test_mux_frame_recv_failpoint_tears_down_and_reconnects():
    srv, netloc = _real_mux_server()
    tr = MuxTransport(_cfg(), timeout=5.0)
    try:
        assert tr.request("GET", netloc, "/s")[0] == 200
        failpoints.configure(f"mux-frame-recv@{netloc}", "drop", count=1)
        with pytest.raises(MuxError):
            tr.request("GET", netloc, "/s")
        failpoints.reset()
        # Next request re-dials transparently.
        assert tr.request("GET", netloc, "/s")[0] == 200
        snap = tr.stats.snapshot()
        assert snap["connects"] == 1 and snap["reconnects"] == 1
    finally:
        failpoints.reset()
        tr.close()
        srv.close()


def test_client_send_failpoint_scopes_per_peer_over_mux():
    """The chaos schedule's per-peer client-send scoping keeps working
    when the transport flips to mux: peer A's link drops, peer B's
    serves — exactly the HTTP targeting contract."""
    srv_a, netloc_a = _real_mux_server()
    srv_b, netloc_b = _real_mux_server()
    tr = MuxTransport(_cfg(), timeout=5.0)
    try:
        failpoints.configure(f"client-send@{netloc_a}", "drop")
        with pytest.raises(MuxError):
            tr.request("GET", netloc_a, "/s")
        assert tr.request("GET", netloc_b, "/s")[0] == 200
    finally:
        failpoints.reset()
        tr.close()
        srv_a.close()
        srv_b.close()


# ------------------------------------------------------- 3-node clusters


MUX_OFF = 2000


def free_port_pair():
    """A free HTTP port whose mux twin (port + MUX_OFF) is also free."""
    for _ in range(64):
        p = free_port()
        if p + MUX_OFF > 65000:
            continue
        try:
            probe = socket.socket()
            probe.bind(("localhost", p + MUX_OFF))
            probe.close()
        except OSError:
            continue
        return p
    raise RuntimeError("no free http+mux port pair")


def _mk_cluster(tmp_path, enabled_nodes, clock=None):
    ports = [free_port_pair() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        tc = (TransportConfig(enabled=True, port_offset=MUX_OFF)
              if i in enabled_nodes else None)
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=2,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            member_monitor_interval=0,
            executor_workers=0,
            transport_config=tc,
            resilience_config=ResilienceConfig(
                breaker_backoff=0.2, breaker_backoff_max=1.0,
                retry_budget=50.0, retry_refill=1.0,
            ),
        )
        s.open()
        if clock is not None:
            s.cluster.health.clock = clock
        servers.append(s)
    return servers, hosts


def _close_cluster(servers):
    failpoints.reset()
    for s in servers:
        try:
            s.close()
        except Exception:
            pass


def _get_json(host, path):
    with urllib.request.urlopen(f"http://{host}{path}") as r:
        return json.loads(r.read())


def _fanout_shards(s0, index="t"):
    """Three shards that FORCE a remote hop from s0: placement is
    port-dependent (ModHasher over node ids), so fixed shard numbers
    sometimes land every shard on the coordinator and the executor
    legitimately serves without any remote: hop."""
    locals_, remotes = [], []
    for sh in range(48):
        owners = s0.cluster.shard_nodes(index, sh)
        if any(o.id == s0.node.id for o in owners):
            locals_.append(sh)
        else:
            remotes.append(sh)
        if locals_ and len(remotes) >= 2:
            return [locals_[0]] + remotes[:2]
    raise AssertionError(f"no fan-out mix in 48 shards: "
                         f"local={locals_[:3]} remote={remotes[:3]}")


def test_cluster_serves_entirely_over_mux(tmp_path):
    """3 mux nodes: every internal hop rides pmux (requests_http stays
    0), /debug/vars grows the transport group, and the coordinator's
    remote spans are tagged transport=mux."""
    servers, hosts = _mk_cluster(tmp_path, enabled_nodes={0, 1, 2})
    try:
        c = InternalClient()
        h0 = hosts[0]
        c.ensure_index(h0, "t")
        c.ensure_field(h0, "t", "f")
        time.sleep(0.05)
        # One bit per chosen shard: at least two are remote to node0,
        # so the Count MUST fan out over mux.
        for sh in _fanout_shards(servers[0]):
            c.query(h0, "t", f"Set({sh * SHARD_WIDTH + 5}, f=1)")
        assert c.query(h0, "t", "Count(Row(f=1))")["results"] == [3]

        snap = servers[0].transport_stats.snapshot()
        assert snap["requests_mux"] > 0
        assert snap["requests_http"] == 0, "an internal hop fell back"
        assert snap["connects"] >= 1
        assert sum(s.transport_stats.snapshot()["accepts"]
                   for s in servers[1:]) >= 1

        dv = _get_json(h0, "/debug/vars")
        assert dv["transport"]["enabled"] is True
        assert dv["transport"]["requests_mux"] == snap["requests_mux"]
        assert dv["transport"]["server"]["listening"] is True

        traces = _get_json(h0, "/debug/traces?index=t")["traces"]
        hop_tags = [sp.get("tags", {}) for t in traces for sp in t["spans"]
                    if sp["name"].startswith("remote:")]
        assert hop_tags, f"no remote hop was traced: {traces!r}"
        assert all(tags.get("transport") == "mux" for tags in hop_tags), \
            hop_tags
    finally:
        _close_cluster(servers)


def test_mixed_cluster_serves_via_handshake_fallback(tmp_path):
    """Only the coordinator speaks mux; its peers are mux-disabled. The
    refused handshakes demote per-peer and every hop serves over HTTP —
    a mixed cluster never stops answering."""
    servers, hosts = _mk_cluster(tmp_path, enabled_nodes={0})
    try:
        c = InternalClient()
        h0 = hosts[0]
        c.ensure_index(h0, "t")
        c.ensure_field(h0, "t", "f")
        time.sleep(0.05)
        for sh in _fanout_shards(servers[0]):
            c.query(h0, "t", f"Set({sh * SHARD_WIDTH + 5}, f=1)")
        assert c.query(h0, "t", "Count(Row(f=1))")["results"] == [3]

        snap = servers[0].transport_stats.snapshot()
        assert snap["handshake_fallbacks"] >= 1, "no fallback was exercised"
        assert snap["requests_http"] >= 1
        assert snap["requests_mux"] == 0
        # The spans carry the fallback transport.
        traces = _get_json(h0, "/debug/traces?index=t")["traces"]
        hop_tags = [sp.get("tags", {}) for t in traces for sp in t["spans"]
                    if sp["name"].startswith("remote:")]
        assert hop_tags and all(
            tags.get("transport") == "http" for tags in hop_tags)
    finally:
        _close_cluster(servers)


@pytest.mark.chaos
def test_chaos_smoke_over_mux(tmp_path):
    """Seed-pinned twin of the FAULT chaos smoke with pmux carrying the
    internal hops: same invariant (correct result or typed error, then
    full convergence), same pinned seed, same fault schedule riding the
    per-peer client-send scoping."""
    clock = FakeClock()
    servers, hosts = _mk_cluster(tmp_path, enabled_nodes={0, 1, 2},
                                 clock=clock)
    try:
        ok, _err = _run_chaos(servers, hosts, clock, seed=1207,
                              rounds=4, queries_per_round=5)
        assert ok > 0
        # Proof the schedule actually rode pmux, not a silent fallback.
        assert any(s.transport_stats.snapshot()["requests_mux"] > 0
                   for s in servers)
    finally:
        _close_cluster(servers)
