"""Tests for URI, iterators, diagnostics, sysinfo, topology, holder cleaner,
stats, time quantum, and translate replication."""

import json
import time
from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu import timeq
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.cluster.topology import HolderCleaner, Topology
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.diagnostics import DiagnosticsCollector
from pilosa_tpu.iterator import BufIterator, fragment_iterator, limit_iterator, slice_iterator
from pilosa_tpu.stats import InMemoryStatsClient, MultiStatsClient, NopStatsClient, Timer
from pilosa_tpu.sysinfo import system_info
from pilosa_tpu.translate import TranslateStore
from pilosa_tpu.uri import URI, URIError


def test_uri_parse():
    u = URI.parse("https://example.com:8080")
    assert (u.scheme, u.host, u.port) == ("https", "example.com", 8080)
    assert URI.parse("example.com").port == 10101
    assert URI.parse(":9999").host == "localhost"
    assert URI.parse("localhost:1").normalize() == "http://localhost:1"
    with pytest.raises(URIError):
        URI.parse("")


def test_fragment_iterator(tmp_path):
    f = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 2)
    f.open()
    from pilosa_tpu.constants import SHARD_WIDTH

    base = 2 * SHARD_WIDTH
    f.set_bit(0, base + 5)
    f.set_bit(3, base + 1)
    pairs = list(fragment_iterator(f))
    assert pairs == [(0, base + 5), (3, base + 1)]
    assert list(fragment_iterator(f, seek_row=1)) == [(3, base + 1)]
    f.close()


def test_buf_slice_limit_iterators():
    it = BufIterator(slice_iterator([2, 1, 1], [5, 9, 3]))
    assert it.peek() == (1, 3)
    assert it.next() == (1, 3)
    it.unread((1, 3))
    assert it.next() == (1, 3)
    assert list(limit_iterator(slice_iterator([0, 1, 2], [1, 2, 3]), 2, 100)) == [
        (0, 1), (1, 2),
    ]


def test_time_quantum_views():
    t = datetime(2018, 3, 5, 14)
    assert timeq.views_by_time("standard", t, "YMDH") == [
        "standard_2018", "standard_201803", "standard_20180305",
        "standard_2018030514",
    ]
    views = timeq.views_by_time_range(
        "standard", datetime(2018, 1, 31, 22), datetime(2018, 2, 2, 0), "YMDH"
    )
    # 2 hours + 1 day cover the range minimally.
    assert views == [
        "standard_2018013122", "standard_2018013123", "standard_20180201",
    ]


def test_stats_clients():
    s = InMemoryStatsClient()
    s.count("x", 2)
    s.count("x", 3)
    s.gauge("g", 7)
    tagged = s.with_tags("index:i")
    tagged.count("x", 1)
    snap = s.snapshot()
    assert snap["counters"]["x"] == 5
    assert snap["counters"]["x|index:i"] == 1
    assert snap["gauges"]["g"] == 7
    multi = MultiStatsClient([NopStatsClient(), s])
    multi.count("y", 1)
    assert s.snapshot()["counters"]["y"] == 1
    with Timer(s, "op"):
        pass
    assert "op" in s.snapshot()["timings"]


def test_sysinfo():
    info = system_info()
    assert info["OS"] == "Linux"
    assert info["numCPU"] > 0
    assert info["memTotal"] > 0


def test_topology_persistence(tmp_path):
    path = str(tmp_path / ".topology")
    t = Topology.load(path)
    assert t.node_ids == []
    t.save([Node(id="a"), Node(id="b")])
    t2 = Topology.load(path)
    assert t2.node_ids == ["a", "b"]
    assert t2.contains_id("a") and not t2.contains_id("c")


class _FakeServer:
    def __init__(self, holder, cluster):
        self.holder = holder
        self.cluster = cluster


def test_holder_cleaner(tmp_path):
    from pilosa_tpu.cluster.hash import ModHasher
    from pilosa_tpu.constants import SHARD_WIDTH
    from pilosa_tpu.core.holder import Holder

    holder = Holder(str(tmp_path / "data"))
    holder.open()
    idx = holder.create_index("i")
    fld = idx.create_field("f")
    for s in range(4):
        fld.set_bit(1, s * SHARD_WIDTH + 1)
    nodes = [Node(id="me"), Node(id="other")]
    cluster = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    removed = HolderCleaner(_FakeServer(holder, cluster)).clean_holder()
    view = fld.view("standard")
    kept = set(view.fragments)
    assert all(cluster.owns_shard("me", "i", s) for s in kept)
    assert len(removed) == 4 - len(kept)
    holder.close()


def test_diagnostics_gather_and_flush(tmp_path):
    import http.server
    import threading

    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("localhost", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    from pilosa_tpu.core.holder import Holder

    holder = Holder(None)
    holder.open()
    holder.create_index("i").create_field("f")
    cluster = Cluster()
    server = _FakeServer(holder, cluster)
    d = DiagnosticsCollector(
        server, endpoint=f"http://localhost:{httpd.server_address[1]}/diag"
    )
    assert d.flush()
    assert received[0]["numIndexes"] == 1
    assert received[0]["numFields"] == 1
    assert received[0]["OS"] == "Linux"
    httpd.shutdown()
    # No endpoint -> gather only.
    d2 = DiagnosticsCollector(server)
    assert not d2.flush()
    assert d2.last_report["numIndexes"] == 1


def test_translate_replication(tmp_path):
    primary = TranslateStore(str(tmp_path / "primary")).open()
    primary.translate_columns_to_uint64("i", ["a", "b"])
    primary.translate_rows_to_uint64("i", "f", ["x"])
    replica = TranslateStore(str(tmp_path / "replica"), read_only=True).open()
    data = primary.read_from(0)
    replica.apply_log(data)
    assert replica.translate_columns_to_uint64("i", ["a", "b"]) == [1, 2]
    assert replica.translate_row_to_string("i", "f", 1) == "x"
    # Replica refuses new keys.
    from pilosa_tpu.errors import TranslateStoreReadOnlyError

    with pytest.raises(TranslateStoreReadOnlyError):
        replica.translate_columns_to_uint64("i", ["new"])
    # Incremental tail.
    size = replica.size()
    primary.translate_columns_to_uint64("i", ["c"])
    replica.apply_log(primary.read_from(size))
    assert replica.translate_columns_to_uint64("i", ["c"]) == [3]


def test_statsd_client_wire_format():
    import socket
    import threading as th

    from pilosa_tpu.stats import StatsDClient, new_stats_client

    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(2)
    port = sock.getsockname()[1]
    c = StatsDClient("127.0.0.1", port, tags=["env:test"])
    c.count("setBit", 3)
    c.gauge("heap", 42.5)
    c.with_tags("index:i").timing("query", 1.25)
    msgs = sorted(sock.recv(1024).decode() for _ in range(3))
    assert msgs[0] == "pilosa_tpu.heap:42.5|g|#env:test"
    assert msgs[1] == "pilosa_tpu.query:1.25|ms|#env:test,index:i"
    assert msgs[2] == "pilosa_tpu.setBit:3|c|#env:test"
    sock.close()
    # Factory selection.
    from pilosa_tpu.stats import InMemoryStatsClient, MultiStatsClient, NopStatsClient

    assert isinstance(new_stats_client("nop"), NopStatsClient)
    assert isinstance(new_stats_client("inmem"), InMemoryStatsClient)
    assert isinstance(new_stats_client("statsd", "127.0.0.1:8125"), MultiStatsClient)


def test_bitmap_check():
    import numpy as np

    from pilosa_tpu.storage.bitmap import Bitmap

    b = Bitmap([1, 2, 3, 100000])
    assert b.check() == []
    b.containers[99] = np.array([5, 5, 4], dtype=np.uint16)  # corrupt
    problems = b.check()
    assert any("ascending" in p for p in problems)


def test_diagnostics_version_compare():
    """compareVersion parity (diagnostics.go:133-146)."""
    from pilosa_tpu.diagnostics import DiagnosticsCollector, _version_segments
    from pilosa_tpu import __version__

    assert _version_segments("v1.2.3-rc1") == [1, 2, 3]
    assert _version_segments("2.0") == [2, 0, 0]
    d = DiagnosticsCollector.__new__(DiagnosticsCollector)
    d.logger = None
    major = _version_segments(__version__)
    newer_major = f"v{major[0]+1}.0.0"
    w = d.compare_version(newer_major)
    assert w and "newer version" in w
    assert d.compare_version(__version__) is None
    newer_patch = f"v{major[0]}.{major[1]}.{major[2]+1}"
    w = d.compare_version(newer_patch)
    assert w and "patch release" in w
    # Unreachable endpoint: swallowed, returns None.
    assert d.check_version("http://127.0.0.1:1/none") is None


def test_translate_store_binary_log_reopen(tmp_path):
    """Offset-indexed binary log: keys round-trip across reopen with only
    offsets held in memory (reference translate.go:733-900)."""
    from pilosa_tpu.translate import TranslateStore

    path = str(tmp_path / "keys")
    ts = TranslateStore(path).open()
    ids = ts.translate_columns_to_uint64("i", [f"user-{n}" for n in range(500)])
    assert ids == list(range(1, 501))
    rids = ts.translate_rows_to_uint64("i", "f", ["alpha", "beta", "alpha"])
    assert rids == [1, 2, 1]
    ts.close()

    ts2 = TranslateStore(path).open()
    # existing keys resolve to the same ids; new keys continue the sequence
    assert ts2.translate_columns_to_uint64("i", ["user-7", "user-new"]) == [8, 501]
    assert ts2.translate_column_to_string("i", 8) == "user-7"
    assert ts2.translate_row_to_string("i", "f", 2) == "beta"
    assert ts2.translate_rows_to_string("i", "f", [1, 2, 99]) == ["alpha", "beta", ""]
    ts2.close()


def test_translate_store_legacy_json_migration(tmp_path):
    import json as _json
    import struct as _struct

    from pilosa_tpu.translate import TranslateStore

    path = str(tmp_path / "keys")
    with open(path, "wb") as f:
        for ns, key, id in [("i:x", "a", 1), ("i:x", "b", 2), ("f:x:g", "r", 1)]:
            e = _json.dumps([ns, key, id]).encode()
            f.write(_struct.pack("<I", len(e)) + e)
    ts = TranslateStore(path).open()
    assert ts.translate_columns_to_uint64("x", ["a", "b", "c"]) == [1, 2, 3]
    assert ts.translate_row_to_string("x", "g", 1) == "r"
    ts.close()
    # migrated file reopens as binary
    ts2 = TranslateStore(path).open()
    assert ts2.translate_column_to_string("x", 3) == "c"
    ts2.close()


def test_translate_legacy_readonly_does_not_rewrite(tmp_path):
    """A read-only replica opening a round-1 legacy log must not mutate the
    shared on-disk file; it decodes in memory and still serves lookups and
    downstream streaming (read-only contract)."""
    import json as _json
    import struct as _struct

    from pilosa_tpu.translate import TranslateStore

    path = str(tmp_path / "keys")
    with open(path, "wb") as f:
        for ns, key, id in [("i:x", "a", 1), ("i:x", "b", 2)]:
            e = _json.dumps([ns, key, id]).encode()
            f.write(_struct.pack("<I", len(e)) + e)
    before = open(path, "rb").read()
    ts = TranslateStore(path, read_only=True).open()
    assert ts.translate_columns_to_uint64("x", ["a", "b"]) == [1, 2]
    assert open(path, "rb").read() == before  # untouched on disk
    # Downstream streaming serves the decoded binary entries from the tail.
    data = ts.read_from(0)
    assert len(data) == ts.size() and data
    chained = TranslateStore(None, read_only=True)
    chained.apply_log(data)
    assert chained.translate_column_to_string("x", 2) == "b"
    ts.close()


def test_translate_readonly_read_from_includes_tail(tmp_path):
    """read_from on a read-only replica with a path must serve applied log
    entries living only in the in-memory tail — size() already counts them,
    so a chained replica polling read_from(size) would otherwise stall."""
    from pilosa_tpu.translate import TranslateStore

    primary = TranslateStore(str(tmp_path / "primary")).open()
    primary.translate_columns_to_uint64("i", ["a", "b"])
    replica = TranslateStore(str(tmp_path / "replica"), read_only=True).open()
    replica.apply_log(primary.read_from(0))
    assert replica.size() == primary.size()
    # The replica's copy is all tail (its own disk file is empty): stream it.
    data = replica.read_from(0)
    assert data == primary.read_from(0)
    # Offsets into the tail work too.
    assert replica.read_from(4) == data[4:]
    assert replica.read_from(replica.size()) == b""
    primary.close()
    replica.close()


def test_translate_store_memory_is_offsets_not_keys(tmp_path):
    """1M keys must not hold 1M python strings resident."""
    import sys

    from pilosa_tpu.translate import TranslateStore

    ts = TranslateStore(str(tmp_path / "keys")).open()
    n = 100_000
    CHUNK = 10_000
    for i in range(0, n, CHUNK):
        ts.translate_columns_to_uint64("big", [f"key-{j:012d}" for j in range(i, i + CHUNK)])
    # table slots + id offsets are numpy/array-backed: ~16B/key, far below
    # what 100k resident str objects (~60B+ each) would need.
    table_bytes = ts._table.slots.nbytes
    ids_bytes = sum(a.itemsize * len(a) for a in ts._ids.values())
    assert table_bytes + ids_bytes < 6_000_000
    assert ts.translate_columns_to_uint64("big", ["key-000000000042"]) == [43]
    assert ts.translate_column_to_string("big", 43) == "key-000000000042"
    ts.close()


def test_translate_store_truncated_tail_recovery(tmp_path):
    """A crash mid-append leaves a partial entry; reopen must truncate it so
    new entries land at clean offsets."""
    from pilosa_tpu.translate import TranslateStore

    path = str(tmp_path / "keys")
    ts = TranslateStore(path).open()
    ts.translate_columns_to_uint64("i", ["a", "b"])
    ts.close()
    with open(path, "ab") as f:
        f.write(b"\xff\x00\x00\x00partial")  # garbage tail
    ts2 = TranslateStore(path).open()
    assert ts2.translate_columns_to_uint64("i", ["a", "c"]) == [1, 3]
    ts2.close()
    ts3 = TranslateStore(path).open()
    assert ts3.translate_column_to_string("i", 3) == "c"
    assert ts3.translate_columns_to_uint64("i", ["c"]) == [3]
    ts3.close()
