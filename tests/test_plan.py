"""Query-plan compiler tests (docs/query-compiler.md).

Covers the canonicalization contract end to end: commutative/associative
respellings of one query share ONE compiled program (proven by the
engine's compile-cache counters), one memo space, and one micro-batcher
group; signatures are injective over canonical programs (equal signature
+ equal leaf binding implies equal semantics, and structurally different
programs never collide); the per-query plan cache on the Call tree
compiles once per query instead of once per dispatch site; and compiled
results stay bit-exact against the per-shard walk and the host ladder —
including while the fused program's signature breaker opens mid-run.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.cluster.health import ResilienceConfig
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel import EngineConfig
from pilosa_tpu.parallel.engine import ShardedQueryEngine
from pilosa_tpu.plan import build_plan, cached_plan, snapshot as plan_snapshot
from pilosa_tpu.pql.parser import parse
from pilosa_tpu.sched import MicroBatcher

N_SHARDS = 2
SHARDS = tuple(range(N_SHARDS))


@pytest.fixture
def holder():
    h = Holder(None)
    h.open()
    idx = h.create_index("i")
    fld = idx.create_field("f")
    rng = np.random.default_rng(11)
    for row in range(8):
        cols = []
        for s in range(N_SHARDS):
            local = np.flatnonzero(rng.random(4096) < 0.2)
            cols.extend(int(s * SHARD_WIDTH + c) for c in local)
        fld.import_bits([row] * len(cols), cols)
    vfld = idx.create_field("v", FieldOptions(type="int", min=0, max=100))
    for col in range(0, 600, 7):
        vfld.set_value(col, col % 97)
    yield h
    h.close()


def tree(q: str):
    return parse(q).calls[0].children[0] if q.startswith("Count(") \
        else parse(q).calls[0]


def sig_of(holder, q: str):
    plan = build_plan(holder, "i", tree(q))
    return plan.sig_tuple, tuple(plan.leaves)


# --------------------------------------------------- canonical sharing


RESPELLINGS = [
    "Count(Intersect(Union(Row(f=0), Row(f=1)), Row(f=2), Row(f=3)))",
    "Count(Intersect(Row(f=3), Union(Row(f=1), Row(f=0)), Row(f=2)))",
    "Count(Intersect(Intersect(Row(f=2), Row(f=3)), Union(Row(f=0), Row(f=1))))",
    "Count(Intersect(Union(Row(f=1), Row(f=0)), Intersect(Row(f=3), Row(f=2))))",
]


def test_respellings_share_one_compiled_program(holder, monkeypatch):
    """THE canonicalization acceptance: commutative operand reorderings
    and associative renestings of one tree share one compiled program —
    the compile-cache counters prove it (one build, hits thereafter)."""
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")  # every count dispatches
    eng = ShardedQueryEngine(holder)
    results = [eng.count("i", tree(q), SHARDS) for q in RESPELLINGS]
    assert len(set(results)) == 1
    snap = eng.snapshot()
    assert snap["fn_cache_builds"] == 1, snap
    assert snap["fn_cache_hits"] >= len(RESPELLINGS) - 1, snap
    # All respellings share signature AND leaf-binding order.
    sigs = {sig_of(holder, q) for q in RESPELLINGS}
    assert len(sigs) == 1


def test_respellings_share_collective_descriptor_and_program(holder):
    """The COLLECTIVE plane's descriptor signature is the same canonical
    plan signature (parallel/collective.py _call_sig): every respelling
    in the corpus produces one descriptor sig, shares one collective
    compiled program, and answers identically through the one-pod
    collective path (PR 12 satellite)."""
    from types import SimpleNamespace

    from pilosa_tpu.cluster.node import Cluster, Node
    from pilosa_tpu.logger import NopLogger
    from pilosa_tpu.parallel import CollectiveConfig
    from pilosa_tpu.parallel.collective import CollectiveBackend

    node = Node(id="n0", process_idx=0)
    backend = CollectiveBackend(
        SimpleNamespace(
            holder=holder, logger=NopLogger(),
            cluster=Cluster(node=node, nodes=[node], replica_n=1),
            client=None,
        ),
        CollectiveConfig(single_process=1),
    )
    try:
        sigs = {backend._call_sig("i", tree(q)) for q in RESPELLINGS}
        assert len(sigs) == 1, sigs
        results = {backend.count("i", tree(q)) for q in RESPELLINGS}
        assert len(results) == 1
        count_fns = [k for k in backend._fn_cache if k[0] == "count"]
        assert len(count_fns) == 1, count_fns
    finally:
        backend.close()


def test_respellings_share_memo(holder):
    """With memos on, a respelling of an answered query is a memo hit —
    no second dispatch at all."""
    eng = ShardedQueryEngine(holder)
    r1 = eng.count("i", tree(RESPELLINGS[0]), SHARDS)
    d1 = eng.snapshot()["count_dispatches"]
    for q in RESPELLINGS[1:]:
        assert eng.count("i", tree(q), SHARDS) == r1
    snap = eng.snapshot()
    assert snap["count_dispatches"] == d1, snap
    assert snap["memo_hits"] >= len(RESPELLINGS) - 1, snap


def test_difference_normalizations_bit_exact(holder, monkeypatch):
    """Difference canonicalization: head-nesting flattens, subtracting
    Unions merge into the tail, the tail sorts — one signature, one
    program, answers equal to the reference per-shard walk."""
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
    spellings = [
        "Count(Difference(Difference(Row(f=0), Row(f=1)), Row(f=2)))",
        "Count(Difference(Row(f=0), Row(f=2), Row(f=1)))",
        "Count(Difference(Row(f=0), Union(Row(f=1), Row(f=2))))",
    ]
    assert len({sig_of(holder, q) for q in spellings}) == 1
    ex = Executor(holder, workers=0)
    got = [ex.execute("i", q)[0] for q in spellings]
    walk = sum(
        ex._execute_bitmap_call_shard("i", tree(spellings[0]), s).count()
        for s in SHARDS)
    assert got == [walk] * len(spellings)
    assert ex.engine.snapshot()["fn_cache_builds"] == 1


def test_head_nested_difference_is_not_flattened_into_tail(holder):
    """a \\ (b \\ c) is NOT a \\ b \\ c: only head-position nesting and
    subtracting Unions may flatten."""
    s1 = sig_of(holder, "Count(Difference(Row(f=0), Difference(Row(f=1), Row(f=2))))")
    s2 = sig_of(holder, "Count(Difference(Row(f=0), Row(f=1), Row(f=2)))")
    assert s1[0] != s2[0]


# ------------------------------------------------------- injectivity


DISTINCT_CORPUS = [
    "Count(Row(f=0))",
    "Count(Intersect(Row(f=0), Row(f=1)))",
    "Count(Intersect(Row(f=0), Row(f=0)))",      # slot aliasing differs
    "Count(Intersect(Row(f=0), Row(f=1), Row(f=2)))",  # arity differs
    "Count(Union(Row(f=0), Row(f=1)))",
    "Count(Xor(Row(f=0), Row(f=1)))",
    "Count(Difference(Row(f=0), Row(f=1)))",
    "Count(Difference(Row(f=0), Difference(Row(f=1), Row(f=2))))",
    "Count(Intersect(Union(Row(f=0), Row(f=1)), Row(f=2)))",
    "Count(Union(Intersect(Row(f=0), Row(f=1)), Row(f=2)))",
    "Count(Range(v > 3))",
    "Count(Range(v > 4))",                        # baked predicate differs
    "Count(Range(v >= 3))",
    "Count(Range(v < 3))",
    "Count(Range(v == 3))",
    "Count(Range(v != 3))",
    "Count(Range(v != null))",
    "Count(Range(v >< [1, 2]))",
    "Count(Range(v >< [2, 3]))",
    "Count(Intersect(Row(f=0), Range(v > 3)))",
]


def test_semantically_different_programs_never_collide(holder):
    """Every corpus entry lowers to a distinct signature: the signature
    is a faithful serialization of the canonical program (ops, arities,
    slot aliasing, baked predicates), so no two different programs can
    share one. Verified doubly: the evaluated answers that DO differ
    prove the programs are genuinely different."""
    sigs = [sig_of(holder, q)[0] for q in DISTINCT_CORPUS]
    assert len(set(sigs)) == len(sigs), "signature collision in corpus"


def test_equal_signature_equal_binding_implies_equal_answer(holder):
    """The no-collision contract, stated positively: any two trees that
    canonicalize to the same (signature, leaf binding) must answer
    identically — checked over every pair the corpus + respellings
    produce, against the reference per-shard walk."""
    ex = Executor(holder, workers=0)
    pool = DISTINCT_CORPUS + RESPELLINGS + [
        "Count(Union(Row(f=1), Row(f=0)))",
        "Count(Xor(Row(f=1), Row(f=0)))",
    ]
    by_key = {}
    for q in pool:
        key = sig_of(holder, q)
        walk = sum(
            ex._execute_bitmap_call_shard("i", tree(q), s).count()
            for s in SHARDS)
        by_key.setdefault(key, set()).add(walk)
    collisions = {k: v for k, v in by_key.items() if len(v) > 1}
    assert not collisions, collisions


# ------------------------------------------------------ per-query cache


def test_plan_cached_on_call_across_dispatch_sites(holder):
    """The satellite fix: one canonical lowering per query, reused across
    every dispatch-site touch of the same Call tree (support gate, count,
    host ladder), instead of one rebuild per touch."""
    eng = ShardedQueryEngine(holder)
    call = tree("Count(Intersect(Row(f=0), Row(f=1)))")
    before = plan_snapshot()
    assert eng.supports(call, "i")
    eng.count("i", call, SHARDS)
    eng.host_count("i", call, SHARDS)
    delta = {k: v - before[k] for k, v in plan_snapshot().items()}
    assert delta["plan_builds"] == 1, delta
    assert delta["plan_cache_hits"] >= 2, delta


def test_plan_cache_invalidated_by_write_epoch(holder):
    """A write anywhere in the index invalidates the cached plan (a write
    can create time views or stretch a BSI range, changing the correct
    lowering)."""
    eng = ShardedQueryEngine(holder)
    call = tree("Count(Row(f=0))")
    cached_plan(holder, "i", call)
    p1 = cached_plan(holder, "i", call)
    holder.field("i", "f").set_bit(0, 9)
    before = plan_snapshot()
    p2 = cached_plan(holder, "i", call)
    assert plan_snapshot()["plan_builds"] == before["plan_builds"] + 1
    assert p2 is not p1


def test_plan_cache_knob_disables(holder):
    eng = ShardedQueryEngine(holder, config=EngineConfig(plan_cache=0))
    call = tree("Count(Row(f=1))")
    before = plan_snapshot()
    assert eng.supports(call, "i")
    assert eng.supports(call, "i")
    delta = plan_snapshot()
    assert delta["plan_builds"] - before["plan_builds"] == 2
    assert delta["plan_cache_hits"] == before["plan_cache_hits"]


# ------------------------------------------- ladder bit-exactness/chaos


def test_fused_answers_bit_exact_under_sig_breaker_chaos(holder, monkeypatch):
    """Seed-pinned chaos acceptance: the fused program's signature
    breaker opens MID-RUN (one injected dispatch error at
    device-sig-failures=1) and the ladder serves the SAME answers — the
    fault is a routing event, never a correctness event."""
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
    ex = Executor(holder, workers=0)
    ex.cluster.health.configure(ResilienceConfig(
        device_sig_failures=1, device_sig_backoff=60.0).validate())
    queries = RESPELLINGS + ["Count(Union(Row(f=2), Row(f=4)))"]
    try:
        baseline = [ex.execute("i", q)[0] for q in queries]
        host = [ex.engine.host_count("i", tree(q), SHARDS) for q in queries]
        assert baseline == host
        failpoints.configure("device-dispatch", "error", count=1)
        chaos = [ex.execute("i", q)[0] for q in queries]
        assert chaos == baseline
        dh = ex.engine.device_health.snapshot()
        assert dh["sig_quarantined"] >= 1, dh
        # Still quarantined (backoff 60s): a second pass routes the
        # per-shard rung and stays bit-exact with zero new dispatches
        # for the quarantined shape.
        d0 = ex.engine.snapshot()["count_dispatches"]
        assert [ex.execute("i", q)[0] for q in RESPELLINGS] == \
            baseline[: len(RESPELLINGS)]
        assert ex.engine.snapshot()["count_dispatches"] == d0
    finally:
        failpoints.reset()
        ex.close()


def test_plan_lower_failpoint_falls_back_per_shard(holder):
    """An injected lowering failure makes the support gate refuse; the
    query is served by the reference per-shard walk, not an error."""
    ex = Executor(holder, workers=0)
    want = ex.execute("i", "Count(Intersect(Row(f=0), Row(f=1)))")[0]
    refusals0 = ex.engine.snapshot()["compile_gate_refusals"]
    failpoints.configure("plan-lower", "error")
    try:
        got = ex.execute("i", "Count(Intersect(Row(f=0), Row(f=1)))")[0]
    finally:
        failpoints.reset()
    assert got == want
    assert ex.engine.snapshot()["compile_gate_refusals"] > refusals0


# ------------------------------------------------ batcher generalization


def _batcher_setup(holder, monkeypatch, n):
    monkeypatch.setenv("PILOSA_MEMO_ENTRIES", "0")
    ex = Executor(holder, workers=0)
    engine = ex.engine
    batcher = MicroBatcher(
        lambda: engine, window=2.0, window_max=10.0, batch_max=n,
        depth_fn=lambda: n,
    )
    ex.batcher = batcher
    return ex, engine, batcher


def test_batcher_coalesces_commutative_respellings(holder, monkeypatch):
    """The generalized compatibility key is the CANONICAL signature:
    operand-shuffled spellings of one shape land in ONE group and one
    fused launch."""
    n = 4
    ex0 = Executor(holder, workers=0)
    truth = [ex0.execute("i", q)[0] for q in RESPELLINGS]
    ex, engine, batcher = _batcher_setup(holder, monkeypatch, n)
    results = [None] * n
    barrier = threading.Barrier(n)

    def client(i):
        barrier.wait(timeout=10)
        results[i] = ex.execute("i", RESPELLINGS[i])[0]

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    before = engine.counters["count_dispatches"]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == truth
    assert engine.counters["count_dispatches"] - before == 1
    assert batcher.counters["launches"] == 1
    assert batcher.counters["coalesced"] == n - 1


def test_batcher_batches_bitmap_expressions(holder, monkeypatch):
    """Beyond Counts: same-signature BITMAP dispatches coalesce into one
    fused bitmap_batch launch, each caller getting its own exact Row."""
    n = 4
    ex0 = Executor(holder, workers=0)
    queries = [f"Intersect(Row(f={r}), Row(f={r + 1}))" for r in range(n)]
    truth = [sorted(ex0.execute("i", q)[0].columns()) for q in queries]
    ex, engine, batcher = _batcher_setup(holder, monkeypatch, n)
    results = [None] * n
    barrier = threading.Barrier(n)

    def client(i):
        barrier.wait(timeout=10)
        results[i] = sorted(ex.execute("i", queries[i])[0].columns())

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    before = engine.counters["bitmap_dispatches"]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == truth
    assert engine.counters["bitmap_dispatches"] - before == 1
    assert batcher.counters["launches"] == 1
    assert batcher.counters["coalesced"] == n - 1


def test_bitmap_batch_direct_matches_unbatched(holder):
    """engine.bitmap_batch == engine.bitmap per query, including the
    non-set-op (BSI) per-call fallback path."""
    eng = ShardedQueryEngine(holder)
    calls = [tree("Count(Intersect(Row(f=0), Row(f=1)))"),
             tree("Count(Intersect(Row(f=2), Row(f=3)))"),
             # Duplicate of the first: the within-batch dedup computes
             # its plane once and both Rows must still be exact.
             tree("Count(Intersect(Row(f=1), Row(f=0)))")]
    rows = eng.bitmap_batch("i", calls, SHARDS)
    for call, row in zip(calls, rows):
        assert sorted(row.columns()) == \
            sorted(eng.bitmap("i", call, SHARDS).columns())
    bsi = [tree("Count(Range(v > 10))"), tree("Count(Range(v > 20))")]
    rows = eng.bitmap_batch("i", bsi, SHARDS)
    for call, row in zip(bsi, rows):
        assert sorted(row.columns()) == \
            sorted(eng.bitmap("i", call, SHARDS).columns())


# ----------------------------------------------------------- plumbing


def test_plan_counter_group_shape():
    snap = plan_snapshot()
    for key in ("plan_builds", "plan_cache_hits", "plan_reorders",
                "plan_flattens"):
        assert key in snap
