"""Cluster-logic tests: placement math, replication, anti-entropy sync,
and coordinator-driven resize (models: reference cluster_internal_test.go,
server/cluster_test.go TestClusterResize)."""

import socket
import time

import numpy as np
import pytest

from pilosa_tpu.cluster.hash import JmpHasher, ModHasher, jump_hash, partition
from pilosa_tpu.cluster.node import Cluster, Node
from pilosa_tpu.cluster.resize import ResizeCoordinator, fragment_sources
from pilosa_tpu.cluster.syncer import HolderSyncer
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ----------------------------------------------------------- placement math


def test_jump_hash_distribution():
    # Keys spread over buckets; consistent: changing n only moves ~1/n keys.
    n_keys = 1000
    h3 = [jump_hash(k, 3) for k in range(n_keys)]
    h4 = [jump_hash(k, 4) for k in range(n_keys)]
    assert set(h3) == {0, 1, 2}
    moved = sum(1 for a, b in zip(h3, h4) if a != b)
    assert moved < n_keys / 2  # only keys moving to the new bucket move
    assert all(b == 3 for a, b in zip(h3, h4) if a != b)


def test_partition_deterministic():
    assert partition("i", 0) == partition("i", 0)
    assert partition("i", 0) != partition("other", 0) or True  # may collide
    assert 0 <= partition("i", 12345) < 256


def test_replica_placement():
    nodes = [Node(id=f"node{i}") for i in range(4)]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=2)
    owners = c.shard_nodes("i", 7)
    assert len(owners) == 2
    assert owners[0].id != owners[1].id
    # Replicas are consecutive on the ring.
    i0 = nodes.index(c.node_by_id(owners[0].id))
    assert owners[1].id == nodes[(i0 + 1) % 4].id


def test_contains_shards():
    nodes = [Node(id=f"node{i}") for i in range(3)]
    c = Cluster(node=nodes[0], nodes=nodes, replica_n=1, hasher=ModHasher())
    all_shards = set()
    for n in nodes:
        all_shards.update(c.contains_shards("i", 9, n))
    assert all_shards == set(range(10))


# -------------------------------------------------------------- replication


@pytest.fixture
def cluster2r(tmp_path):
    """2 nodes, replica_n=2: every shard lives on both nodes."""
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=2,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,  # manual sync in tests
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_replicated_write(cluster2r):
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "r")
    client.create_field(h0, "r", "f")
    time.sleep(0.05)
    client.query(h0, "r", "Set(5, f=1)")
    # Both replicas hold the bit.
    for s in cluster2r:
        frag = s.holder.fragment("r", "f", "standard", 0)
        assert frag is not None and frag.bit(1, 5), s.node.id


def test_anti_entropy_repairs_divergence(cluster2r):
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "r")
    client.create_field(h0, "r", "f")
    time.sleep(0.05)
    client.query(h0, "r", "Set(5, f=1)")
    # Diverge: plant a bit directly in node0's holder only.
    frag0 = cluster2r[0].holder.fragment("r", "f", "standard", 0)
    frag0.set_bit(1, 99)
    frag1 = cluster2r[1].holder.fragment("r", "f", "standard", 0)
    assert not frag1.bit(1, 99)
    # Run anti-entropy on node0: even-split consensus keeps the bit and
    # pushes it to the replica.
    HolderSyncer(cluster2r[0]).sync_holder()
    assert frag1.bit(1, 99)
    assert frag0.bit(1, 99)


def test_anti_entropy_syncs_nonstandard_views(cluster2r):
    """Divergent bsig and time-quantum views converge: the blocks RPC is
    view-addressed (the reference's is standard-only, http/handler.go:1058)
    and non-standard diffs are pushed via the view-exact block endpoint
    since Set/Clear PQL can only reach the standard view."""
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "vw")
    client.create_field(h0, "vw", "t", {"type": "time", "timeQuantum": "YMD"})
    time.sleep(0.05)
    client.query(h0, "vw", "Set(5, t=1, 2018-01-02T00:00)")

    # Diverge a time view: plant a raw bit in node0's fragment only.
    tview = next(n for n in cluster2r[0].holder.field("vw", "t").view_names()
                 if n.startswith("standard_"))
    tf0 = cluster2r[0].holder.fragment("vw", "t", tview, 0)
    tf0.set_bit(1, 42)
    tf1 = cluster2r[1].holder.fragment("vw", "t", tview, 0)
    assert not tf1.bit(1, 42)

    # A whole view the replica has never heard of must also converge.
    bview = cluster2r[0].holder.field("vw", "t").create_view_if_not_exists("bsig_t")
    bfrag = bview.create_fragment_if_not_exists(0, broadcast=False)
    bfrag.set_bit(2, 99)
    assert cluster2r[1].holder.fragment("vw", "t", "bsig_t", 0) is None

    HolderSyncer(cluster2r[0]).sync_holder()
    assert tf1.bit(1, 42)
    bfrag1 = cluster2r[1].holder.fragment("vw", "t", "bsig_t", 0)
    assert bfrag1 is not None and bfrag1.bit(2, 99)
    # The replicated time bit survived the sweep on both nodes.
    assert client.query(h0, "vw", "Count(Row(t=1))")["results"][0] == 1


def test_anti_entropy_creates_missing_replica_fragment(cluster2r):
    """A replica that never saw a fragment receives it via anti-entropy:
    remote 404 on the blocks RPC counts as an empty block set so diffs are
    pushed (client.go:666-668 ErrFragmentNotFound -> empty)."""
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "mf")
    client.create_field(h0, "mf", "f")
    time.sleep(0.05)
    # Create the fragment only on node0, bypassing replication.
    fld0 = cluster2r[0].holder.field("mf", "f")
    view0 = fld0.create_view_if_not_exists("standard")
    frag0 = view0.create_fragment_if_not_exists(0, broadcast=False)
    frag0.set_bit(3, 17)
    assert cluster2r[1].holder.fragment("mf", "f", "standard", 0) is None

    HolderSyncer(cluster2r[0]).sync_holder()
    frag1 = cluster2r[1].holder.fragment("mf", "f", "standard", 0)
    assert frag1 is not None and frag1.bit(3, 17)


def test_anti_entropy_attr_sync(cluster2r):
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "r")
    client.create_field(h0, "r", "f")
    time.sleep(0.05)
    # Set attrs only on node1 directly.
    cluster2r[1].holder.field("r", "f").row_attr_store.set_attrs(3, {"tag": "x"})
    HolderSyncer(cluster2r[0]).sync_holder()
    assert cluster2r[0].holder.field("r", "f").row_attr_store.attrs(3) == {"tag": "x"}


# ------------------------------------------------------------------- resize


def test_fragment_sources_diff():
    old_nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    new_nodes = old_nodes + [Node(id="c", uri="c")]
    old = Cluster(node=old_nodes[0], nodes=old_nodes, hasher=ModHasher())
    new = Cluster(node=old_nodes[0], nodes=new_nodes, hasher=ModHasher())
    schema = [{"name": "i", "fields": [{"name": "f", "views": [{"name": "standard"}]}]}]
    sources = fragment_sources(old, new, schema, {"i": 5})
    # Node c must fetch every shard it now owns, from an old owner.
    c_fetches = {s["shard"] for s in sources["c"]}
    expected = {
        shard for shard in range(6)
        if any(n.id == "c" for n in new.shard_nodes("i", shard))
    }
    assert c_fetches == expected
    assert all(s["sourceNodeID"] in ("a", "b") for s in sources["c"])


_SCHEMA_1F = [
    {"name": "i", "fields": [{"name": "f", "views": [{"name": "standard"}]}]}
]


def test_fragment_sources_node_removal():
    """Removing a node: every shard it exclusively held is fetched by its
    new owner, sourced from an OLD owner (the leaver stays reachable as a
    source during the job)."""
    old_nodes = [Node(id="a", uri="a"), Node(id="b", uri="b"),
                 Node(id="c", uri="c")]
    new_nodes = old_nodes[:2]
    old = Cluster(node=old_nodes[0], nodes=old_nodes, hasher=ModHasher())
    new = Cluster(node=old_nodes[0], nodes=new_nodes, hasher=ModHasher())
    sources = fragment_sources(old, new, _SCHEMA_1F, {"i": 7})
    fetched = {s["shard"] for lst in sources.values() for s in lst}
    changed = {
        sh for sh in range(8)
        if [n.id for n in old.shard_nodes("i", sh)]
        != [n.id for n in new.shard_nodes("i", sh)]
    }
    assert fetched == changed
    for lst in sources.values():
        for s in lst:
            assert s["sourceNodeID"] in {
                n.id for n in old.shard_nodes("i", s["shard"])}


def test_fragment_sources_replica_overlap():
    """replica_n=2: a node that already holds a shard as a replica in the
    OLD placement never re-fetches it in the new one."""
    old_nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    new_nodes = old_nodes + [Node(id="c", uri="c")]
    old = Cluster(node=old_nodes[0], nodes=old_nodes, replica_n=2,
                  hasher=ModHasher())
    new = Cluster(node=old_nodes[0], nodes=new_nodes, replica_n=2,
                  hasher=ModHasher())
    sources = fragment_sources(old, new, _SCHEMA_1F, {"i": 7})
    for node_id, lst in sources.items():
        for s in lst:
            old_owners = {n.id for n in old.shard_nodes("i", s["shard"])}
            # Only genuinely-NEW owners appear; an overlap owner is never
            # instructed to fetch what it already has.
            assert node_id not in old_owners
            assert s["sourceNodeID"] in old_owners


def test_fragment_sources_noop_resize_is_empty():
    """Identical topologies produce zero instructions for every node."""
    nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    old = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    new = Cluster(node=nodes[0], nodes=list(nodes), hasher=ModHasher())
    sources = fragment_sources(old, new, _SCHEMA_1F, {"i": 9})
    assert all(lst == [] for lst in sources.values())


def test_fragment_sources_empty_old_owners():
    """A shard with NO old owner (empty prior cluster) is skipped instead
    of raising IndexError on old_owners[0]."""
    nodes = [Node(id="a", uri="a")]
    old = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    old.nodes = []  # constructor refuses an empty list; force it
    new = Cluster(node=nodes[0], nodes=nodes, hasher=ModHasher())
    sources = fragment_sources(old, new, _SCHEMA_1F, {"i": 3})
    assert sources == {"a": []}


def test_fragment_sources_prefers_healthy_source():
    """source_ok steers selection to a healthy replica; when it rejects
    every old owner, placement order wins (a degraded source beats no
    source)."""
    old_nodes = [Node(id="a", uri="a"), Node(id="b", uri="b")]
    new_nodes = old_nodes + [Node(id="c", uri="c")]
    old = Cluster(node=old_nodes[0], nodes=old_nodes, replica_n=2,
                  hasher=ModHasher())
    new = Cluster(node=old_nodes[0], nodes=new_nodes, replica_n=2,
                  hasher=ModHasher())

    sources = fragment_sources(
        old, new, _SCHEMA_1F, {"i": 7},
        source_ok=lambda nid, *frag: nid != "a")
    entries = [s for lst in sources.values() for s in lst]
    assert entries
    assert all(s["sourceNodeID"] == "b" for s in entries)

    sources = fragment_sources(
        old, new, _SCHEMA_1F, {"i": 7},
        source_ok=lambda nid, *frag: False)
    for lst in sources.values():
        for s in lst:
            # Fallback: first old owner in placement order.
            assert s["sourceNodeID"] == old.shard_nodes("i", s["shard"])[0].id


def test_resize_add_node_moves_data(tmp_path):
    """Add a third node to a 2-node cluster with data; moved shards must be
    queryable from the new topology (reference ClusterResize_AddNode)."""
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i in range(2):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=ports[i],
            cluster_hosts=hosts[:2],
            hasher=ModHasher(),
            cache_flush_interval=0,
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    client = InternalClient()
    h0 = hosts[0]
    try:
        client.create_index(h0, "rz")
        client.create_field(h0, "rz", "f")
        time.sleep(0.05)
        cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
        for col in cols:
            client.query(h0, "rz", f"Set({col}, f=1)")
        assert client.query(h0, "rz", "Count(Row(f=1))")["results"][0] == 4

        # Boot node2 (empty, same static membership limited to itself for now).
        s2 = Server(
            data_dir=str(tmp_path / "node2"),
            port=ports[2],
            cluster_hosts=[hosts[2]],
            hasher=ModHasher(),
            cache_flush_interval=0,
            executor_workers=0,
        )
        s2.open()
        servers.append(s2)

        # Coordinator (node0) runs the resize to the 3-node topology.
        coordinator = ResizeCoordinator(servers[0])
        servers[0].resize_coordinator = coordinator
        new_nodes = [Node(id=h, uri=h) for h in hosts]
        coordinator.begin(new_nodes)
        deadline = time.time() + 10
        while coordinator.job is not None and time.time() < deadline:
            time.sleep(0.05)
        assert coordinator.job is None, "resize did not complete"
        assert servers[0].cluster.state == "NORMAL"
        assert len(servers[0].cluster.nodes) == 3

        # All data still answerable through node0 with the new placement.
        assert client.query(h0, "rz", "Count(Row(f=1))")["results"][0] == 4
        row = client.query(h0, "rz", "Row(f=1)")
        assert row["results"][0]["columns"] == cols
        # node2 actually received the shards it now owns.
        owned = [
            s for s in range(4)
            if any(n.id == hosts[2] for n in servers[0].cluster.shard_nodes("rz", s))
        ]
        got = [
            s for s in owned
            if servers[2].holder.fragment("rz", "f", "standard", s) is not None
        ]
        assert got == owned
    finally:
        for s in servers:
            s.close()


def test_anti_entropy_syncs_oversized_divergence(cluster2r):
    """A divergence larger than max_writes_per_request (5000) must still
    converge: the pushed Set/Clear diff is chunked, where a single giant
    PQL request would be rejected by the peer's write cap and previously
    aborted the whole sweep."""
    client = InternalClient()
    h0 = f"localhost:{cluster2r[0].port}"
    client.create_index(h0, "big")
    client.create_field(h0, "big", "f")
    time.sleep(0.05)
    client.query(h0, "big", "Set(1, f=1)")  # both replicas have the seed

    # Diverge node 0 by 6500 bits applied directly to its fragment.
    frag0 = cluster2r[0].holder.fragment("big", "f", "standard", 0)
    cols = np.arange(10, 6510, dtype=np.uint64)
    frag0.bulk_import(np.ones(len(cols), dtype=np.uint64), cols)
    frag1 = cluster2r[1].holder.fragment("big", "f", "standard", 0)
    assert frag1.row_count(1) == 1  # replica lagging

    HolderSyncer(cluster2r[0]).sync_holder()
    assert frag1.row_count(1) == frag0.row_count(1) == 6501


def test_keyed_cluster_end_to_end(tmp_path):
    """A cluster with a shared gossip key: replication, remote fan-out,
    and anti-entropy all authenticate through the keyed /internal/* plane
    (public clients need no key)."""
    keyfile = tmp_path / "key"
    keyfile.write_text("cluster-secret-1")
    ports = [free_port() for _ in range(2)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"kn{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=2,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,
            executor_workers=0,
            internal_key_path=str(keyfile),
        )
        s.open()
        servers.append(s)
    try:
        client = InternalClient()  # public plane: no key required
        h0 = hosts[0]
        client.create_index(h0, "k")
        client.create_field(h0, "k", "f")
        time.sleep(0.05)
        for col in (1, 2, 3):
            client.query(h0, "k", f"Set({col}, f=9)")
        for s in servers:
            frag = s.holder.fragment("k", "f", "standard", 0)
            assert frag is not None and frag.row_count(9) == 3, s.node.id
        # Diverge one replica; anti-entropy repairs through keyed routes.
        frag0 = servers[0].holder.fragment("k", "f", "standard", 0)
        frag0.bulk_import(np.full(50, 9, dtype=np.uint64),
                          np.arange(100, 150, dtype=np.uint64))
        HolderSyncer(servers[0]).sync_holder()
        assert servers[1].holder.fragment(
            "k", "f", "standard", 0).row_count(9) == 53
    finally:
        for s in servers:
            s.close()
