"""Elastic membership: dynamic node join (with and without data) and
coordinator-driven node removal (model: reference server/cluster_test.go
ClusterResize_AddNode / RemoveNode)."""

import socket
import time

import pytest

from pilosa_tpu.cluster.hash import ModHasher
from pilosa_tpu.cluster.node import Node
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.server.server import Server


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_server(tmp_path, name, port, **kw):
    kw.setdefault("cache_flush_interval", 0)
    kw.setdefault("member_monitor_interval", 0)
    kw.setdefault("executor_workers", 0)
    kw.setdefault("hasher", ModHasher())
    s = Server(data_dir=str(tmp_path / name), port=port, **kw)
    s.open()
    return s


def wait_for(cond, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_join_empty_cluster(tmp_path):
    """A node joins a single-node cluster with no data: status-only path."""
    port0 = free_port()
    s0 = make_server(tmp_path, "n0", port0, cluster_hosts=[f"localhost:{port0}"])
    servers = [s0]
    try:
        s1 = make_server(tmp_path, "n1", free_port(), join_addr=s0.node.uri)
        servers.append(s1)
        assert len(s1.cluster.nodes) == 2
        assert wait_for(lambda: len(s0.cluster.nodes) == 2)
        assert {n.id for n in s0.cluster.nodes} == {s0.node.id, s1.node.id}
        # Schema created after the join propagates to both.
        client = InternalClient()
        client.create_index(s0.node.uri, "j")
        client.create_field(s0.node.uri, "j", "f")
        assert wait_for(lambda: s1.holder.field("j", "f") is not None)
    finally:
        for s in servers:
            s.close()


def test_join_with_data_triggers_resize(tmp_path):
    port0 = free_port()
    s0 = make_server(tmp_path, "n0", port0, cluster_hosts=[f"localhost:{port0}"])
    servers = [s0]
    client = InternalClient()
    try:
        client.create_index(s0.node.uri, "jd")
        client.create_field(s0.node.uri, "jd", "f")
        cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3]
        for col in cols:
            client.query(s0.node.uri, "jd", f"Set({col}, f=1)")

        s1 = make_server(tmp_path, "n1", free_port(), join_addr=s0.node.uri)
        servers.append(s1)
        assert wait_for(lambda: len(s0.cluster.nodes) == 2 and s0.cluster.state == "NORMAL")
        # Schema moved to the new node and it holds the shards it now owns.
        assert s1.holder.field("jd", "f") is not None
        owned = [
            sh for sh in range(3)
            if any(n.id == s1.node.id for n in s0.cluster.shard_nodes("jd", sh))
        ]
        for sh in owned:
            assert s1.holder.fragment("jd", "f", "standard", sh) is not None, sh
        # Full query still answers from either node.
        for s in servers:
            assert client.query(s.node.uri, "jd", "Count(Row(f=1))")["results"][0] == 3
    finally:
        for s in servers:
            s.close()


def test_remove_node(tmp_path):
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = [
        make_server(tmp_path, f"n{i}", ports[i], cluster_hosts=hosts)
        for i in range(3)
    ]
    client = InternalClient()
    try:
        h0 = servers[0].node.uri
        client.create_index(h0, "rm")
        client.create_field(h0, "rm", "f")
        time.sleep(0.05)
        cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
        for col in cols:
            client.query(h0, "rm", f"Set({col}, f=1)")

        # Remove a non-coordinator node through the public endpoint.
        victim = servers[2]
        import json
        import urllib.request

        req = urllib.request.Request(
            f"http://{h0}/cluster/resize/remove-node",
            data=json.dumps({"id": victim.node.id}).encode(),
            method="POST",
        )
        urllib.request.urlopen(req)
        assert wait_for(
            lambda: len(servers[0].cluster.nodes) == 2
            and servers[0].cluster.state == "NORMAL"
        )
        assert all(n.id != victim.node.id for n in servers[0].cluster.nodes)
        victim.close()
        # All data still answerable from the remaining nodes.
        assert client.query(h0, "rm", "Count(Row(f=1))")["results"][0] == 4
        row = client.query(servers[1].node.uri, "rm", "Row(f=1)")
        assert row["results"][0]["columns"] == cols
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass


def test_coordinator_startup_quorum(tmp_path):
    """A restarting coordinator with a persisted multi-node topology stays
    STARTING (rejecting queries) until the previously-known nodes rejoin
    (reference considerTopology, cluster.go:1582-1613)."""
    from pilosa_tpu.errors import PilosaError

    port0, port1 = free_port(), free_port()
    s0 = make_server(tmp_path, "n0", port0)
    client = InternalClient()
    client.create_index(s0.node.uri, "q")
    client.create_field(s0.node.uri, "q", "f")
    client.query(s0.node.uri, "q", "Set(1, f=1)")
    s1 = make_server(tmp_path, "n1", port1, join_addr=s0.node.uri)
    assert wait_for(lambda: len(s0.cluster.nodes) == 2 and s0.cluster.state == "NORMAL")
    s1_id = s1.node.id
    s1.close()
    s0.close()

    # Coordinator restarts alone: topology on disk lists both nodes.
    s0 = make_server(tmp_path, "n0", port0)
    try:
        assert s0.cluster.state == "STARTING"
        with pytest.raises(PilosaError):
            s0.api.query("q", "Count(Row(f=1))")
        # The previously-known node rejoins (same port -> same id): NORMAL.
        s1 = make_server(tmp_path, "n1", port1, join_addr=s0.node.uri)
        assert wait_for(lambda: s0.cluster.state == "NORMAL")
        assert {n.id for n in s0.cluster.nodes} == {s0.node.id, s1_id}
        assert s0.api.query("q", "Count(Row(f=1))")
        s1.close()
    finally:
        s0.close()


def test_coordinator_restart_recovers_without_peer_restart(tmp_path):
    """Only the coordinator restarts: it must solicit the still-healthy
    peer back into the cluster instead of wedging in STARTING until every
    peer process is also bounced (ADVICE r2 medium; the reference recovers
    via memberlist gossip re-join events, cluster.go:1615 nodeJoin)."""
    port0, port1 = free_port(), free_port()
    s0 = make_server(tmp_path, "n0", port0)
    client = InternalClient()
    client.create_index(s0.node.uri, "cr")
    client.create_field(s0.node.uri, "cr", "f")
    client.query(s0.node.uri, "cr", "Set(1, f=1)")
    s1 = make_server(tmp_path, "n1", port1, join_addr=s0.node.uri)
    assert wait_for(lambda: len(s0.cluster.nodes) == 2 and s0.cluster.state == "NORMAL")
    s0.close()

    # s1 keeps running; the restarted coordinator comes up STARTING and
    # must discover s1 on its own.
    s0 = make_server(tmp_path, "n0", port0)
    try:
        assert wait_for(lambda: s0.cluster.state == "NORMAL", timeout=15)
        assert {n.id for n in s0.cluster.nodes} == {s0.node.id, s1.node.id}
        assert s0.api.query("cr", "Count(Row(f=1))")
    finally:
        s1.close()
        s0.close()


def test_schema_converges_after_missed_broadcast(tmp_path):
    """A node that was down during create-field converges via the member
    monitor's NodeStatus schema merge after it comes back, without a restart
    of anything else (reference gossip push/pull sync, gossip.go:240-273)."""
    ports = [free_port(), free_port()]
    hosts = [f"localhost:{p}" for p in ports]
    s0 = make_server(tmp_path, "n0", ports[0], cluster_hosts=hosts,
                     member_monitor_interval=0.2)
    s1 = make_server(tmp_path, "n1", ports[1], cluster_hosts=hosts,
                     is_coordinator=False, member_monitor_interval=0.2)
    client = InternalClient()
    try:
        client.create_index(s0.node.uri, "sc")
        assert wait_for(lambda: s1.holder.index("sc") is not None)
        s1.close()

        # s1 is down: the create-field broadcast never reaches it.
        client.create_field(s0.node.uri, "sc", "missed")

        s1 = make_server(tmp_path, "n1", ports[1], cluster_hosts=hosts,
                         is_coordinator=False, member_monitor_interval=0.2)
        # No broadcast is replayed — only the monitor's schema pull can
        # deliver the field.
        assert wait_for(lambda: s1.holder.field("sc", "missed") is not None)
        client.query(s0.node.uri, "sc", "Set(1, missed=1)")
        assert client.query(
            s1.node.uri, "sc", "Count(Row(missed=1))"
        )["results"][0] == 1
    finally:
        s1.close()
        s0.close()


def test_startup_quorum_refuses_unknown_host(tmp_path):
    port0 = free_port()
    s0 = make_server(tmp_path, "n0", port0)
    client = InternalClient()
    client.create_index(s0.node.uri, "q2")
    s1 = make_server(tmp_path, "n1", free_port(), join_addr=s0.node.uri)
    assert wait_for(lambda: len(s0.cluster.nodes) == 2)
    s1.close()
    s0.close()

    s0 = make_server(tmp_path, "n0", port0)
    try:
        assert s0.cluster.state == "STARTING"
        # A brand-new host (different port/id) is refused while STARTING.
        from pilosa_tpu.errors import PilosaError

        with pytest.raises(PilosaError):
            make_server(tmp_path, "n2", free_port(), join_addr=s0.node.uri)
        assert s0.cluster.state == "STARTING"
    finally:
        s0.close()


def test_resize_aborts_on_failed_fetch(tmp_path):
    """A node that cannot retrieve a source fragment must abort the whole
    resize (reference cluster.go followResizeInstruction error -> job
    abort): completing with holes would lose the fragment at replica_n=1
    when the old owner garbage-collects. The membership must stay on the
    OLD topology and return to NORMAL."""
    from pilosa_tpu.cluster.node import Node, STATE_NORMAL, STATE_RESIZING
    from pilosa_tpu.cluster.resize import (
        ResizeCoordinator,
        ResizeJob,
        follow_resize_instruction,
    )
    from pilosa_tpu.server.server import Server

    s = Server(data_dir=str(tmp_path / "n0"), cache_flush_interval=0,
               member_monitor_interval=0, executor_workers=0)
    s.open()
    try:
        idx = s.holder.create_index("r")
        idx.create_field("f")
        s.executor.execute("r", "Set(1, f=1)")
        coord = ResizeCoordinator(s)
        s.resize_coordinator = coord
        old_nodes = list(s.cluster.nodes)

        # Path 1: an undeliverable instruction aborts begin() itself
        # (otherwise the cluster hangs in RESIZING forever).
        coord.begin(old_nodes + [Node(id="zz-new", uri="localhost:1")])
        assert coord.job is None
        assert s.cluster.state == STATE_NORMAL
        assert [n.id for n in s.cluster.nodes] == [n.id for n in old_nodes]

        # Path 2: a follower whose source fetch fails acks with an error;
        # the coordinator aborts instead of completing with holes.
        coord.job = ResizeJob("j1", {s.cluster.node.id: []}, old_nodes)
        s.cluster.state = STATE_RESIZING
        instr = {
            "type": "resize-instruction",
            "jobID": "j1",
            "coordinatorID": s.cluster.node.id,
            "schema": [],
            "sources": [{
                "index": "r", "field": "f", "view": "standard", "shard": 0,
                "sourceNodeID": "dead-node",
            }],
            "nodeURIs": {"dead-node": "localhost:9"},  # nothing listening
            "maxShards": {},
        }
        follow_resize_instruction(s, instr)  # acks with error -> abort

        assert coord.job is None
        assert s.cluster.state == STATE_NORMAL
        assert [n.id for n in s.cluster.nodes] == [n.id for n in old_nodes]
        # Data untouched.
        assert s.executor.execute("r", "Count(Row(f=1))") == [1]
    finally:
        s.close()


def test_coordinator_failover_and_join_via_successor(tmp_path):
    """Kill the coordinator of a 3-node cluster: the surviving node with
    the lowest id must assume coordinatorship on its own (no manual
    set-coordinator — the reference blocks here, api.go:777), the other
    survivor must learn the new coordinator, and a brand-new node must
    then be able to join via EITHER survivor."""
    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    coord_host = min(hosts)  # make the DYING node the lowest id: the
    # successor choice (lowest alive) then provably re-evaluates
    servers = {}
    try:
        for i, port in enumerate(ports):
            servers[hosts[i]] = make_server(
                tmp_path, f"n{i}", port,
                cluster_hosts=hosts,
                is_coordinator=hosts[i] == coord_host,
                member_monitor_interval=0.2,
                member_probe_timeout=0.5,
                coordinator_failover_probes=2,
            )
        survivors = sorted(h for h in hosts if h != coord_host)
        # Everyone learns the configured coordinator via status probes.
        assert wait_for(lambda: all(
            (servers[h].cluster.coordinator_node() or Node(id="")).id == coord_host
            for h in survivors
        )), "peers never learned the configured coordinator"

        servers.pop(coord_host).close()

        successor = survivors[0]  # lowest surviving id
        assert wait_for(lambda: servers[successor].node.is_coordinator, 15), \
            "successor never assumed coordinatorship"
        # The other survivor learns it via the set-coordinator broadcast.
        other = survivors[1]
        assert wait_for(lambda: (
            servers[other].cluster.coordinator_node() or Node(id="")
        ).id == successor, 15)

        # A new node can now join via the NON-coordinator survivor (the
        # join is forwarded to the successor).
        s3 = make_server(tmp_path, "n3", free_port(), join_addr=other)
        servers["n3"] = s3
        assert wait_for(
            lambda: servers[successor].cluster.node_by_id(s3.node.id) is not None
        ), "join via successor failed"
    finally:
        for s in servers.values():
            s.close()


def test_failover_survivor_that_missed_broadcast_self_heals(tmp_path):
    """A survivor that missed the set-coordinator broadcast must still
    converge: probing the successor (an alive self-claimer) clears the
    dead coordinator's stale flag, and coordinator_node() prefers the
    alive claimant meanwhile."""
    ports = [free_port() for _ in range(3)]
    hosts = sorted(f"localhost:{p}" for p in ports)
    coord, succ, other = hosts[0], hosts[1], hosts[2]
    servers = {}
    try:
        for h in hosts:
            servers[h] = make_server(
                tmp_path, h.replace(":", "_"), int(h.rsplit(":", 1)[1]),
                cluster_hosts=hosts, is_coordinator=h == coord,
                member_monitor_interval=0.2, member_probe_timeout=0.5,
                coordinator_failover_probes=2,
            )
        assert wait_for(lambda: all(
            (servers[h].cluster.coordinator_node() or Node(id="")).id == coord
            for h in (succ, other)
        ))
        # Simulate the missed broadcast: drop set-coordinator sends to
        # `other` by making the successor's client fail for that node.
        real_send = servers[succ].client.send_message

        def lossy_send(node, msg):
            if msg.get("type") == "set-coordinator" and node.id == other:
                from pilosa_tpu.server.client import ClientError
                raise ClientError("injected drop", status=0)
            return real_send(node, msg)

        servers[succ].client.send_message = lossy_send
        servers.pop(coord).close()
        assert wait_for(lambda: servers[succ].node.is_coordinator, 15)
        # `other` never got the broadcast, but its probe of the successor
        # sees the self-claim, clears the dead holdover, and routes to the
        # live coordinator.
        assert wait_for(lambda: (
            servers[other].cluster.coordinator_node() or Node(id="")
        ).id == succ, 15)
        dead = servers[other].cluster.node_by_id(coord)
        assert wait_for(lambda: not dead.is_coordinator, 15)
    finally:
        for s in servers.values():
            s.close()


def test_failover_promotion_survives_restart(tmp_path):
    """A promoted successor restarting on its original (non-coordinator)
    config must re-assume the role from the persisted topology — else the
    cluster converges to zero coordinators."""
    ports = [free_port() for _ in range(3)]
    hosts = sorted(f"localhost:{p}" for p in ports)
    coord, succ, other = hosts[0], hosts[1], hosts[2]
    servers = {}
    try:
        for h in hosts:
            servers[h] = make_server(
                tmp_path, h.replace(":", "_"), int(h.rsplit(":", 1)[1]),
                cluster_hosts=hosts, is_coordinator=h == coord,
                member_monitor_interval=0.2, member_probe_timeout=0.5,
                coordinator_failover_probes=2,
            )
        assert wait_for(lambda: all(
            (servers[h].cluster.coordinator_node() or Node(id="")).id == coord
            for h in (succ, other)
        ))
        servers.pop(coord).close()
        assert wait_for(lambda: servers[succ].node.is_coordinator, 15)
        # Restart the successor with its ORIGINAL config (is_coordinator
        # False): the persisted topology must restore the claim.
        servers.pop(succ).close()
        servers[succ] = make_server(
            tmp_path, succ.replace(":", "_"), int(succ.rsplit(":", 1)[1]),
            cluster_hosts=hosts, is_coordinator=False,
            member_monitor_interval=0.2, member_probe_timeout=0.5,
            coordinator_failover_probes=2,
        )
        assert servers[succ].node.is_coordinator, \
            "promotion did not survive restart"
    finally:
        for s in servers.values():
            s.close()


def test_late_starter_learns_coordinator_third_party(tmp_path):
    """A node that starts while knowing no coordinator must adopt a peer's
    view of who holds the role (third-party claim), so failover can still
    identify whose death to detect."""
    ports = [free_port() for _ in range(3)]
    hosts = sorted(f"localhost:{p}" for p in ports)
    coord, mid, late = hosts[0], hosts[1], hosts[2]
    servers = {}
    try:
        for h in (coord, mid):
            servers[h] = make_server(
                tmp_path, h.replace(":", "_"), int(h.rsplit(":", 1)[1]),
                cluster_hosts=hosts, is_coordinator=h == coord,
                member_monitor_interval=0.2, member_probe_timeout=0.5,
                coordinator_failover_probes=0,  # no promotion racing the
                # third-party adoption this test asserts
            )
        assert wait_for(lambda: (
            servers[mid].cluster.coordinator_node() or Node(id="")
        ).id == coord)
        # Kill the coordinator BEFORE the late node starts: the late node
        # can only learn the role third-party, from mid's view.
        servers.pop(coord).close()
        servers[late] = make_server(
            tmp_path, late.replace(":", "_"), int(late.rsplit(":", 1)[1]),
            cluster_hosts=hosts, is_coordinator=False,
            member_monitor_interval=0.2, member_probe_timeout=0.5,
        )
        assert wait_for(lambda: (
            servers[late].cluster.node_by_id(coord) or Node(id="")
        ).is_coordinator, 15), "late starter never learned the coordinator"
    finally:
        for s in servers.values():
            s.close()


def test_failover_requires_strict_majority(tmp_path):
    """In a 2-node cluster the survivor is NOT a strict majority (1 of 2):
    it must never self-promote — a network partition would otherwise
    elect a second coordinator on each side."""
    ports = sorted(free_port() for _ in range(2))
    hosts = [f"localhost:{p}" for p in ports]
    coord, other = hosts[0], hosts[1]
    servers = {}
    try:
        for h in hosts:
            servers[h] = make_server(
                tmp_path, h.replace(":", "_"), int(h.rsplit(":", 1)[1]),
                cluster_hosts=hosts, is_coordinator=h == coord,
                member_monitor_interval=0.2, member_probe_timeout=0.5,
                coordinator_failover_probes=2,
            )
        assert wait_for(lambda: (
            servers[other].cluster.coordinator_node() or Node(id="")
        ).id == coord)
        servers.pop(coord).close()
        # Give the survivor ample probe rounds to (wrongly) promote.
        time.sleep(3.0)
        assert not servers[other].node.is_coordinator, \
            "survivor promoted without a strict majority"
    finally:
        for s in servers.values():
            s.close()
