"""Tier-1 guard for bench.py: BENCH_SMOKE=1 must run EVERY stanza at micro
scale and emit a complete, parseable JSON line.

Two measurement rounds were lost to rc=124 / `parsed: null` because bench
breakage only surfaced at measurement time; this test makes a broken
stanza (or a hung bring-up path) a PR-time failure instead.

Timing-RATIO gates (TIER qps vs drop-and-regather, OBS traced-vs-untraced
qps) can flake when the whole suite's load shares the box: a failed ratio
gate reruns JUST that stanza once in isolation — with the retry recorded
in the test output — before failing. Correctness gates never retry.
"""

import importlib.util
import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _registered_stanzas():
    """Read the stanza registry from bench.py itself: the guard asserts
    EVERY registered stanza rides the final JSON line, so a stanza added
    to bench can never silently fall out of it (sched/mixed each went
    missing once before this was keyed off the registry)."""
    spec = importlib.util.spec_from_file_location("_bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return tuple(name for name, _ in mod.STANZAS)


def _run_bench(out_path, only=None):
    """One BENCH_SMOKE subprocess; `only` reruns a single stanza in
    isolation (every other stanza skipped via its BENCH_<NAME>=0 gate).
    Returns the parsed detail dict of the final JSON line."""
    env = dict(os.environ)
    env.update(
        BENCH_SMOKE="1",
        BENCH_OUT=str(out_path),
        # One CPU device: smoke validates bench CODE; the 8-device test
        # mesh only slows the subprocess's compiles down.
        XLA_FLAGS="",
        JAX_PLATFORMS="cpu",
        # Belt and braces: if a stanza still wedges, the bench's own
        # watchdog emits a partial line well inside the pytest timeout.
        BENCH_DEADLINE="240",
    )
    if only is not None:
        for name in _registered_stanzas():
            if name != only:
                env[f"BENCH_{name}"] = "0"
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, f"bench rc={r.returncode}\n{r.stderr[-2000:]}"

    # The driver parses the LAST JSON line of stdout; hold bench to that.
    last = None
    for line in r.stdout.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            last = line
    assert last is not None, f"no JSON line in stdout:\n{r.stdout[-2000:]}"
    return json.loads(last)


def _retry_ratio_gate(name, stanza, gate, tmp_path):
    """Deflake for timing-RATIO gates: when `gate(stanza)` fails under
    full-suite load, rerun the one stanza in isolation ONCE (recorded in
    the test output) and judge the rerun. Known flake: the TIER
    qps-ratio assert under box load."""
    if gate(stanza):
        return stanza
    import warnings

    # warnings.warn, not print: pytest swallows captured stdout on
    # PASSING tests, and the whole point is that a chronically flaky
    # gate leaves a visible record even when the rerun saves it.
    warnings.warn(
        f"{name} ratio gate failed under full-suite load; "
        f"reran {name} alone once (first result: {stanza})")
    parsed = _run_bench(tmp_path / f"bench_retry_{name.lower()}.json",
                        only=name)
    retried = parsed["detail"][name.lower()]
    retried["retried_in_isolation"] = True
    print(f"{name} isolation rerun result: {retried}")
    return retried


def test_bench_smoke_runs_every_stanza(tmp_path):
    parsed = _run_bench(tmp_path / "bench_out.json")
    detail = parsed["detail"]
    assert not detail.get("partial"), detail.get("partial")
    assert parsed["value"] > 0
    stanzas = _registered_stanzas()
    assert len(stanzas) >= 23  # the registry itself didn't shrink
    for name in stanzas:
        stanza = detail.get(name.lower())
        assert isinstance(stanza, dict), f"stanza {name} missing: {stanza}"
        assert "error" not in stanza, f"stanza {name}: {stanza['error']}"
    # The MIXED stanza is the delta-refresh acceptance metric: delta-on
    # must move fewer bytes to the device than delta-off.
    mixed = detail["mixed"]
    assert mixed["delta_ok"], mixed
    # The INGEST stanza is the amortized-ingest acceptance metric:
    # WAL-amortized bulk imports must beat snapshot-per-batch >= 5x at
    # smoke scale.
    ingest = detail["ingest"]
    assert ingest["amortized_vs_snapshot"] >= 5.0, ingest
    assert ingest["ingest_ok"], ingest
    # The FAULT stanza is the resilience acceptance metric: the scripted
    # brown-out must end with converged routing and a recovery time.
    fault = detail["fault"]
    assert fault["recovered"], fault
    assert fault["recovery_s"] < 30, fault
    # The REPLICATION stanza is the durable-write-replication acceptance
    # metric (docs/durability.md "Write-path consistency"): across a
    # replica SIGKILL + restart under write-consistency=quorum, ZERO
    # acked writes may be lost and the restarted replica's fragments
    # must be byte-identical after the hint drain; during the outage
    # every write must meet quorum with missed forwards costing a hint
    # append (counters prove the breaker-open path never pays a connect
    # timeout per write). All correctness gates — never retried. The
    # hint-drain timing gate gets the standard one-shot isolation rerun.
    repl = detail["replication"]
    assert repl["lost_acked_writes"] == 0, repl
    assert repl["byte_identical"], repl
    assert repl["hinted_ok"], repl
    assert repl["outage_counters"]["WriteConsistencyUnmet"] == 0, repl
    repl = _retry_ratio_gate(
        "REPLICATION", repl,
        lambda r: r["drained"] and r["hint_drain_s"] < 30, tmp_path)
    assert repl["drained"], repl
    assert repl["hint_drain_s"] < 30, repl
    # The CDC stanza is the change-data-capture acceptance metric
    # (docs/cdc.md): the tailing consumer must see a dense, loss-free
    # position stream whose replay is byte-identical to the live
    # fragment; at-position reads must equal the answers frozen at each
    # checkpoint; and the standing Count must re-push within ONE
    # evaluator sweep of a change — and never for an unrelated write.
    # All correctness gates — never retried. The delivery-lag timing
    # gate gets the standard one-shot isolation rerun.
    cdc = detail["cdc"]
    assert cdc["tail"]["dense"], cdc
    assert cdc["tail"]["bit_exact"], cdc
    assert cdc["pit"]["bit_exact"], cdc
    assert cdc["standing"]["pushed_on_change"], cdc
    assert not cdc["standing"]["pushed_on_unrelated"], cdc
    assert cdc["cdc_ok"], cdc
    cdc = _retry_ratio_gate(
        "CDC", cdc, lambda c: c["tail"]["lag_p99_ms"] < 250, tmp_path)
    assert cdc["tail"]["lag_p99_ms"] < 250, cdc
    # The DEGRADE stanza is the device-fault acceptance metric: with
    # every engine dispatch failing, the degraded phase must serve with
    # ZERO query errors and bit-exact results (the host ladder), injected
    # OOM must be absorbed (backpressure, no client error), and clearing
    # the fault must re-close the plane breaker with queries proven back
    # on the device path.
    degrade = detail["degrade"]
    assert degrade["device_fault"]["errors"] == 0, degrade
    assert degrade["correct"], degrade
    assert degrade["oom"]["errors"] == 0, degrade
    assert degrade["recovered"], degrade
    # The COMPILE stanza is the query-plan-compiler acceptance metric:
    # the fused whole-tree path (production shape, incl. the canonical-
    # signature memo per-op structurally lacks) must beat per-op
    # dispatch >= 1.5x on qps, AND the memo-off raw dispatch floor must
    # hold (a lowering regression cannot hide behind memo hits). The
    # floor is 0.3, not parity: at micro smoke scale each fused dispatch
    # pays a full in-process device round trip that per-op's pure-python
    # container walk avoids (observed 0.4-1.4x under box noise) — the
    # floor catches order-of-magnitude lowering regressions; full-scale/
    # TPU captures are where the dispatch path leads. Both timing ratios
    # get one isolation rerun. Every compiled result must
    # be bit-exact against both the per-op walk and the host ladder, and
    # the seed-pinned chaos leg — the fused program's signature breaker
    # opening mid-run — must serve the same answers from the ladder.
    # Correctness gates never retry.
    comp = detail["compile"]
    assert comp["bit_exact"], comp
    assert comp["chaos"]["bit_exact"], comp
    assert comp["chaos"]["sig_quarantined"] >= 1, comp
    comp = _retry_ratio_gate(
        "COMPILE", comp,
        lambda c: c["fused_vs_per_op"] >= 1.5
        and c["dispatch_vs_per_op"] >= 0.3, tmp_path)
    assert comp["fused_vs_per_op"] >= 1.5, comp
    assert comp["dispatch_vs_per_op"] >= 0.3, comp
    # The TIER stanza is the tiered-storage acceptance metric: with the
    # working set ~3x the HBM budget, tiered eviction must beat
    # drop-and-regather on qps, with ZERO full regathers once the tiers
    # are warm — including after writes that stay within the delta bound
    # (the journal folds on promotion instead of poisoning to a walk).
    # The qps RATIO is a known box-load flake: it gets one isolation
    # rerun; the regather counters are correctness gates and never retry.
    tier = detail["tier"]
    assert tier["tiered"]["full_regathers"] == 0, tier
    assert tier["tiered"]["post_write_full_regathers"] == 0, tier
    assert tier["prefetch"]["promotions"] > 0, tier
    tier = _retry_ratio_gate(
        "TIER", tier,
        lambda t: t["tiered"]["qps"] > t["drop_regather"]["qps"], tmp_path)
    assert tier["tiered"]["qps"] > tier["drop_regather"]["qps"], tier
    # The MULTICHIP stanza is the collective-plane acceptance metric
    # (docs/multichip.md): every answer on BOTH paths must equal the
    # host-computed reference (warm and under concurrency), the fast
    # path must actually have served (a silent fallback would fake the
    # ratio), and the barrier-timeout chaos leg must serve with zero
    # wrong answers and zero errors, then re-close the plane breaker
    # once the fault clears. All correctness gates — never retried.
    # The batched resident-stack collective vs HTTP fan-out qps ratio
    # is a timing gate: one isolation rerun per the TIER-flake
    # precedent.
    mc = detail["multichip"]
    assert mc["bit_exact"], mc
    assert mc["collective_served"], mc
    assert mc["chaos"]["wrong_answers"] == 0, mc
    assert mc["chaos"]["errors"] == 0, mc
    assert mc["chaos"]["barrier_timeouts"] >= 1, mc
    assert mc["chaos"]["plane_opened"] >= 1, mc
    assert mc["chaos"]["recovered"], mc
    mc = _retry_ratio_gate(
        "MULTICHIP", mc,
        lambda m: m["collective_vs_fanout"] >= 1.5, tmp_path)
    assert mc["collective_vs_fanout"] >= 1.5, mc
    # The OBS stanza is the tracing acceptance metric: sample-rate 1.0
    # must hold qps within 5% of tracing-disabled on the SCHED-shaped
    # workload (ratio gate: one isolation rerun), every query must land
    # a trace, and the injected-latency slow-query log line must fire
    # with its stage breakdown (deterministic: never retried).
    obs = detail["obs"]
    assert obs["slow_query_logged"], obs
    assert obs["slow_query"]["has_breakdown"], obs
    assert obs["traced_all"], obs
    obs = _retry_ratio_gate("OBS", obs, lambda o: o["obs_ok"], tmp_path)
    assert obs["obs_ok"], obs
    # The GEO stanza is the geo-replication acceptance metric
    # (docs/geo-replication.md): across a leader SIGKILL + follower
    # promotion + old-leader rejoin (fenced, demoted, re-tailed), ZERO
    # acked writes may be lost on EITHER cluster and the two clusters'
    # fragments must be byte-identical; the staleness contract must
    # serve in-bound reads locally and 409 an unsatisfiable bound; the
    # promotion must bump the geo epoch and the fence must land. All
    # correctness gates — never retried. The replication-lag
    # percentiles are timing gates: one isolation rerun per the TIER-
    # flake precedent.
    geo = detail["geo"]
    assert geo["lost_acked_writes"] == 0, geo
    assert geo["byte_identical"], geo
    assert geo["caught_up"], geo
    assert geo["stale_409_seen"], geo
    assert geo["promoted_epoch"] >= 1, geo
    assert geo["demoted"], geo
    assert geo["converged"], geo
    assert geo["geo_ok"], geo
    geo = _retry_ratio_gate(
        "GEO", geo,
        lambda g: g["lag_samples"] > 0 and g["lag_p99_ms"] < 5000,
        tmp_path)
    assert geo["lag_samples"] > 0, geo
    assert geo["lag_p99_ms"] < 5000, geo
    # The MULTITENANT stanza is the QoS/autoscale acceptance metric
    # (docs/scheduler.md "Tenancy", docs/rebalance.md "Autoscaler"):
    # the noisy tenant must be shed with the typed 429 (per-tenant
    # Retry-After + X-Pilosa-Tenant header) while the quiet tenant sees
    # ZERO 429s; sustained load must scale the cluster out with no
    # operator action (membership + checkpoint prove it); and the
    # seed-pinned chaos leg — an abort mid-migration under the armed
    # revert contract — must fully restore the prior placement with
    # ZERO lost acked writes. All correctness gates — never retried.
    # The quiet-tenant p99 BOUND vs its solo baseline is a timing gate:
    # ratio-or-absolute (8x solo, floored at 500ms — a solo query at
    # smoke scale is ~2ms while any concurrency legitimately opens the
    # micro-batcher's coalescing window, so a pure ratio is
    # meaningless; an unpoliced flood pushes quiet to multi-second
    # p99s). One isolation rerun per the TIER-flake precedent.
    mt = detail["multitenant"]
    assert mt["isolation"]["typed_429"], mt
    assert mt["isolation"]["quiet_429"] == 0, mt
    assert mt["autoscale"]["scaled_out"], mt
    assert mt["autoscale"]["checkpointed"], mt
    assert mt["chaos"]["reverted"], mt
    assert mt["chaos"]["routing_restored"], mt
    assert mt["chaos"]["lost_acked_writes"] == 0, mt
    assert mt["chaos"]["write_after_revert"], mt
    assert mt["multitenant_ok"], mt
    mt = _retry_ratio_gate(
        "MULTITENANT", mt,
        lambda m: m["isolation"]["quiet_p99_bounded"], tmp_path)
    assert mt["isolation"]["quiet_p99_bounded"], mt
    # The TRANSPORT stanza is the pmux acceptance metric
    # (docs/transport.md "Measured"): every internal hop in the mux leg
    # must really ride mux (zero fallbacks, zero HTTP requests through
    # the mux-attached client), the REPLICATION-shaped leg must drain
    # its hints over mux with the replica count converged, and the
    # REBALANCE-shaped migration-stream bytes must be transport-
    # invariant. All correctness gates — never retried. The fan-out
    # qps RATIO (mux >= 1.3x HTTP on the identical workload) is a
    # timing gate: one isolation rerun per the TIER-flake precedent.
    tp = detail["transport"]
    assert tp["mux_counters"]["handshake_fallbacks"] == 0, tp
    assert tp["mux_counters"]["requests_http"] == 0, tp
    assert tp["mux_counters"]["requests_mux"] > 0, tp
    assert tp["replication_leg"]["drained"], tp
    assert tp["replication_leg"]["replica_count_ok"], tp
    assert tp["replication_leg"]["total_count_ok"], tp
    assert tp["rebalance_leg"]["bit_exact"], tp
    assert tp["transport_ok"], tp
    tp = _retry_ratio_gate(
        "TRANSPORT", tp,
        lambda t: t["mux_vs_http_qps"] >= 1.3, tmp_path)
    assert tp["mux_vs_http_qps"] >= 1.3, tp

    # BENCH_OUT got the same line atomically.
    out_path = tmp_path / "bench_out.json"
    assert json.loads(out_path.read_text())["detail"]["mixed"]["delta_ok"]
