"""Tier-1 guard for bench.py: BENCH_SMOKE=1 must run EVERY stanza at micro
scale and emit a complete, parseable JSON line.

Two measurement rounds were lost to rc=124 / `parsed: null` because bench
breakage only surfaced at measurement time; this test makes a broken
stanza (or a hung bring-up path) a PR-time failure instead.
"""

import importlib.util
import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _registered_stanzas():
    """Read the stanza registry from bench.py itself: the guard asserts
    EVERY registered stanza rides the final JSON line, so a stanza added
    to bench can never silently fall out of it (sched/mixed each went
    missing once before this was keyed off the registry)."""
    spec = importlib.util.spec_from_file_location("_bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return tuple(name.lower() for name, _ in mod.STANZAS)


def test_bench_smoke_runs_every_stanza(tmp_path):
    out_path = tmp_path / "bench_out.json"
    env = dict(os.environ)
    env.update(
        BENCH_SMOKE="1",
        BENCH_OUT=str(out_path),
        # One CPU device: smoke validates bench CODE; the 8-device test
        # mesh only slows the subprocess's compiles down.
        XLA_FLAGS="",
        JAX_PLATFORMS="cpu",
        # Belt and braces: if a stanza still wedges, the bench's own
        # watchdog emits a partial line well inside the pytest timeout.
        BENCH_DEADLINE="240",
    )
    r = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, f"bench rc={r.returncode}\n{r.stderr[-2000:]}"

    # The driver parses the LAST JSON line of stdout; hold bench to that.
    last = None
    for line in r.stdout.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            last = line
    assert last is not None, f"no JSON line in stdout:\n{r.stdout[-2000:]}"
    parsed = json.loads(last)
    detail = parsed["detail"]
    assert not detail.get("partial"), detail.get("partial")
    assert parsed["value"] > 0
    stanzas = _registered_stanzas()
    assert len(stanzas) >= 15  # the registry itself didn't shrink
    for name in stanzas:
        stanza = detail.get(name)
        assert isinstance(stanza, dict), f"stanza {name} missing: {stanza}"
        assert "error" not in stanza, f"stanza {name}: {stanza['error']}"
    # The MIXED stanza is the delta-refresh acceptance metric: delta-on
    # must move fewer bytes to the device than delta-off.
    mixed = detail["mixed"]
    assert mixed["delta_ok"], mixed
    # The INGEST stanza is the amortized-ingest acceptance metric:
    # WAL-amortized bulk imports must beat snapshot-per-batch >= 5x at
    # smoke scale.
    ingest = detail["ingest"]
    assert ingest["amortized_vs_snapshot"] >= 5.0, ingest
    assert ingest["ingest_ok"], ingest
    # The FAULT stanza is the resilience acceptance metric: the scripted
    # brown-out must end with converged routing and a recovery time.
    fault = detail["fault"]
    assert fault["recovered"], fault
    assert fault["recovery_s"] < 30, fault
    # The DEGRADE stanza is the device-fault acceptance metric: with
    # every engine dispatch failing, the degraded phase must serve with
    # ZERO query errors and bit-exact results (the host ladder), injected
    # OOM must be absorbed (backpressure, no client error), and clearing
    # the fault must re-close the plane breaker with queries proven back
    # on the device path.
    degrade = detail["degrade"]
    assert degrade["device_fault"]["errors"] == 0, degrade
    assert degrade["correct"], degrade
    assert degrade["oom"]["errors"] == 0, degrade
    assert degrade["recovered"], degrade
    # The TIER stanza is the tiered-storage acceptance metric: with the
    # working set ~3x the HBM budget, tiered eviction must beat
    # drop-and-regather on qps, with ZERO full regathers once the tiers
    # are warm — including after writes that stay within the delta bound
    # (the journal folds on promotion instead of poisoning to a walk).
    tier = detail["tier"]
    assert tier["tiered"]["qps"] > tier["drop_regather"]["qps"], tier
    assert tier["tiered"]["full_regathers"] == 0, tier
    assert tier["tiered"]["post_write_full_regathers"] == 0, tier
    assert tier["prefetch"]["promotions"] > 0, tier

    # BENCH_OUT got the same line atomically.
    assert json.loads(out_path.read_text())["detail"]["mixed"]["delta_ok"]
