"""lockcheck self-test: the runtime lock-order / blocking-under-lock
checker (pilosa_tpu/devtools/lockcheck.py) proven on deliberate
violations — an AB/BA order inversion, a sleep under a lock, a join
under a lock — and on clean patterns that must stay silent, then the
enforcement runs: an instrumented subprocess pass over the concurrency-
heavy test files (chaos/tier/rebalance) asserting ZERO findings in
tier-1, and the full suite instrumented the same way marked `slow`.
See docs/static-analysis.md.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from pilosa_tpu.devtools import lockcheck

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# When the whole session is already instrumented (conftest installed the
# patches because PILOSA_TPU_LOCKCHECK=1), the unit tests below must not
# run: they uninstall the session's instrumentation on exit and their
# DELIBERATE violations would land in the session-wide findings list that
# the outer driver asserts is empty. The outer (uninstrumented) tier-1
# run covers them; the instrumented run covers the production tree.
INSTRUMENTED = os.environ.get("PILOSA_TPU_LOCKCHECK") == "1"

needs_own_install = pytest.mark.skipif(
    INSTRUMENTED,
    reason="session already instrumented; unit tests own install/uninstall",
)


@pytest.fixture
def lc():
    assert not lockcheck.active()
    lockcheck.install()
    lockcheck.reset()
    try:
        yield lockcheck
    finally:
        lockcheck.reset()
        lockcheck.uninstall()


def kinds(fs):
    return sorted(f["kind"] for f in fs)


# ------------------------------------------------------------ order graph


@needs_own_install
class TestLockOrder:
    def test_ab_ba_inversion_across_threads(self, lc):
        """THE deadlock shape: thread 1 takes A then B, thread 2 takes B
        then A. Run sequentially (joined between) so the test itself can
        never deadlock — the order graph is global, so the inverted edge
        still closes the cycle."""
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()

        fs = lc.findings()
        assert kinds(fs) == ["lock-order-cycle"]
        cycle = fs[0]
        assert len(cycle["locks"]) == 2
        # Both creation sites point into this file, and the closing edge
        # names the acquisition sites — the report is actionable.
        assert all("test_lockcheck.py" in s for s in cycle["locks"])
        assert "test_lockcheck.py" in cycle["closing_edge"]["acquired_at"]

    def test_consistent_nesting_is_clean(self, lc):
        """A -> B taken in the same order from two threads is the
        sanctioned nested-lock pattern: no cycle, no findings."""
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        for _ in range(2):
            t = threading.Thread(target=ab)
            t.start()
            t.join()
        with a:
            with b:
                pass
        assert lc.findings() == []

    def test_contended_condition_wait_keeps_bookkeeping_honest(self, lc):
        """Regression: _RLockProxy._release_save used to release the
        inner lock BEFORE resetting owner/count, so a concurrent
        acquire() landing in that window got its ownership claim stomped
        by the waiter's late `self._owner = None` — notify() then raised
        'cannot notify on un-acquired lock' and the stale held-stack
        entry turned every later deny-listed call into a false
        blocking-under-lock finding. Hammer a default (RLock-backed)
        Condition with waiters and notifiers under an aggressive thread
        switch interval and assert nobody crashes and the checker stays
        silent."""
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-6)
        try:
            cond = threading.Condition()
            stop = threading.Event()
            errors = []

            def waiter():
                try:
                    while not stop.is_set():
                        with cond:
                            cond.wait(timeout=0.01)
                except RuntimeError as e:  # the historical crash
                    errors.append(e)

            def notifier():
                try:
                    while not stop.is_set():
                        with cond:
                            cond.notify_all()
                        # Outside the with: clean UNLESS a stomped
                        # release left the lock stranded in this
                        # thread's held stack — then it reports as a
                        # false blocking-under-lock finding below.
                        time.sleep(0)
                except RuntimeError as e:
                    errors.append(e)

            threads = [threading.Thread(target=waiter) for _ in range(4)]
            threads += [threading.Thread(target=notifier) for _ in range(2)]
            for t in threads:
                t.start()
            time.sleep(0.5)
            stop.set()
            with cond:
                cond.notify_all()
            for t in threads:
                t.join(timeout=5)
            assert not any(t.is_alive() for t in threads)
            assert errors == []
            assert lc.findings() == []
        finally:
            sys.setswitchinterval(old_interval)

    def test_rlock_reacquisition_adds_no_edges(self, lc):
        """Re-entering an RLock you own is not a second acquisition: no
        self-edge, no cycle, and the held stack stays balanced."""
        r = threading.RLock()
        with r:
            with r:
                pass
        with r:
            pass
        assert lc.findings() == []

    def test_three_lock_cycle(self, lc):
        """Cycles longer than 2 (A->B, B->C, C->A) are found by the path
        walk, not just direct inversions."""
        a, b, c = threading.Lock(), threading.Lock(), threading.Lock()
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        fs = lc.findings()
        assert kinds(fs) == ["lock-order-cycle"]
        assert len(fs[0]["locks"]) == 3


# ------------------------------------------------------ blocking under lock


@needs_own_install
class TestBlockingUnderLock:
    def test_sleep_under_lock(self, lc):
        mu = threading.Lock()
        with mu:
            time.sleep(0.001)
        fs = lc.findings()
        assert kinds(fs) == ["blocking-under-lock"]
        f = fs[0]
        assert f["call"] == "time.sleep"
        assert "test_lockcheck.py" in f["site"]
        assert any("test_lockcheck.py" in h for h in f["held"])

    def test_sleep_outside_lock_is_clean(self, lc):
        mu = threading.Lock()
        with mu:
            pass
        time.sleep(0.001)
        assert lc.findings() == []

    def test_annotation_on_call_line_suppresses(self, lc):
        mu = threading.Lock()
        with mu:
            time.sleep(0.001)  # pilint: allow-blocking(fixture: proves the runtime checker shares pilint's annotation grammar)
        assert lc.findings() == []

    def test_caller_annotation_covers_callee(self, lc):
        """The frame holding the lock takes responsibility for blocking
        work in its callees: an allow-blocking on the CALL SITE suppresses
        a sleep that only happens inside the helper."""

        def helper():
            time.sleep(0.001)

        mu = threading.Lock()
        with mu:
            # pilint: allow-blocking(fixture: the lock-holding caller vouches for its callee's blocking work)
            helper()
        assert lc.findings() == []

    def test_join_under_lock(self, lc):
        t = threading.Thread(target=lambda: None, name="lc-join-target")
        t.start()
        mu = threading.Lock()
        with mu:
            t.join()
        fs = lc.findings()
        assert kinds(fs) == ["join-under-lock"]
        assert fs[0]["thread"] == "lc-join-target"

    def test_duplicate_findings_collapse(self, lc):
        """The same violation hit in a loop reports once — the report is
        a work list, not a frequency histogram."""
        mu = threading.Lock()
        for _ in range(3):
            with mu:
                time.sleep(0.0)
        assert len(lc.findings()) == 1


# ------------------------------------------------------------- reporting


@needs_own_install
class TestReports:
    def test_report_text_and_json_are_deterministic(self, lc, tmp_path):
        mu = threading.Lock()
        with mu:
            time.sleep(0.0)
        text1, text2 = lc.report(), lc.report()
        assert text1 == text2
        assert "blocking-under-lock: time.sleep" in text1
        assert "1 finding" in text1

        out = tmp_path / "findings.json"
        lc.write_report(str(out))
        payload = json.loads(out.read_text())
        assert payload["count"] == 1
        assert payload["findings"][0]["kind"] == "blocking-under-lock"
        # Stable across a rewrite (sorted keys + sorted findings).
        first = out.read_text()
        lc.write_report(str(out))
        assert out.read_text() == first

    def test_empty_report(self, lc, tmp_path):
        assert lc.report() == "lockcheck: 0 findings"
        out = tmp_path / "empty.json"
        lc.write_report(str(out))
        assert json.loads(out.read_text()) == {"count": 0, "findings": []}

    def test_reset_clears_findings_and_graph(self, lc):
        a, b = threading.Lock(), threading.Lock()
        with a:
            with b:
                time.sleep(0.0)
        assert lc.findings()
        lc.reset()
        assert lc.findings() == []
        # The A->B edge is gone too: B->A after reset closes no cycle.
        with b:
            with a:
                pass
        assert lc.findings() == []


# -------------------------------------------------- schedule perturbation


@needs_own_install
class TestSchedPerturbation:
    """Opt-in seeded yields at acquire boundaries
    (PILOSA_TPU_LOCKCHECK_SCHED): one global PRNG behind the checker
    lock, so a fixed acquire sequence replays the exact same decision
    sequence under the same seed."""

    def _drive(self):
        locks = [threading.Lock() for _ in range(4)]
        for _ in range(40):
            for lk in locks:
                with lk:
                    pass

    def test_disarmed_by_default(self, lc):
        self._drive()
        assert lc.sched_trace() == []

    def test_same_seed_replays_the_same_decisions(self, lc):
        lc.configure_sched(42)
        self._drive()
        first = lc.sched_trace()
        assert len(first) == 160
        assert any(y for y, _ in first), "seed 42 yielded nowhere in 160 draws"
        assert not all(y for y, _ in first)
        lc.configure_sched(42)
        self._drive()
        assert lc.sched_trace() == first
        lc.configure_sched(None)

    def test_different_seed_different_decisions(self, lc):
        lc.configure_sched(42)
        self._drive()
        first = lc.sched_trace()
        lc.configure_sched(7)
        self._drive()
        assert lc.sched_trace() != first
        lc.configure_sched(None)

    def test_non_numeric_env_seed_does_not_crash_install(self, lc, monkeypatch):
        # someone treats the knob as a boolean toggle: install() derives
        # a stable seed instead of dying mid-patch with a ValueError
        lc.uninstall()
        monkeypatch.setenv("PILOSA_TPU_LOCKCHECK_SCHED", "on")
        lc.install()
        with threading.Lock():
            pass
        assert len(lc.sched_trace()) == 1
        lc.configure_sched(None)

    def test_yields_produce_no_findings(self, lc):
        # the perturbation sleeps through the ORIGINAL time.sleep, so it
        # must never self-report blocking-under-lock — even when a yield
        # fires while another instrumented lock is held
        lc.configure_sched(3)
        outer = threading.Lock()
        inner = [threading.Lock() for _ in range(4)]
        with outer:
            for _ in range(40):
                for lk in inner:
                    with lk:
                        pass
        assert any(y for y, _ in lc.sched_trace())
        assert lc.findings() == []
        lc.configure_sched(None)


# ------------------------------------------------------- enforcement runs


def _run_instrumented(test_args, out_path, timeout, allow_test_failures=False,
                      sched_seed=None):
    env = dict(os.environ)
    env["PILOSA_TPU_LOCKCHECK"] = "1"
    env["PILOSA_TPU_LOCKCHECK_OUT"] = str(out_path)
    if sched_seed is not None:
        env["PILOSA_TPU_LOCKCHECK_SCHED"] = str(sched_seed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-m", "not slow",
         "-p", "no:cacheprovider", *test_args],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    # rc 1 = "some tests failed": the full-suite run tolerates it because
    # tier-1 carries 2 known environment-dependent multi-process failures
    # (jax API gap — see ROADMAP "compare DOTS_PASSED, not rc"); the
    # lockcheck JSON is still written at sessionfinish. Anything else
    # (collection error, crash) is a real problem either way.
    ok = (0, 1) if allow_test_failures else (0,)
    assert proc.returncode in ok, proc.stdout[-4000:] + proc.stderr[-2000:]
    payload = json.loads(open(out_path).read())
    return payload


@needs_own_install  # recursion guard: never re-spawn from inside a run
def test_instrumented_smoke_chaos_tier_rebalance(tmp_path):
    """Tier-1 enforcement: the concurrency-heavy test files (chaos fault
    injection, tier demote/promote/prefetch workers, live rebalance
    migration streams, the device-fault ladder's host-execution +
    breaker paths, the hinted-handoff append/deliver machinery under
    quorum-write replica flaps, the CDC change-log append/compact/
    long-poll paths nested inside the fragment mutex, the geo
    fencing chaos leg — concurrent writers against both clusters while
    promote/fence/demote walk the manager and tailer locks — and the
    multi-tenant autoscale chaos leg, where the abort-with-revert path
    walks the coordinator, scheduler, and QoS ledger locks while
    migration streams are mid-flight) run fully
    instrumented and must produce zero lock-order cycles and zero
    blocking-under-lock findings — the runtime half of the acceptance
    bar in docs/static-analysis.md."""
    payload = _run_instrumented(
        ["tests/test_chaos.py", "tests/test_tier.py",
         "tests/test_rebalance.py", "tests/test_device_faults.py",
         "tests/test_replication.py", "tests/test_cdc.py",
         "tests/test_geo.py::test_geo_chaos_fencing_no_shared_epoch",
         "tests/test_autoscale.py::test_abort_mid_migration_fully_reverts"],
        tmp_path / "lockcheck.json", timeout=600,
        # Seeded schedule perturbation (tiny randomized yields at every
        # lock-acquire boundary): the chaos smokes explore interleavings
        # the OS scheduler would rarely pick, deterministically
        # replayable via PILOSA_TPU_LOCKCHECK_SCHED=1337.
        sched_seed=1337,
    )
    assert payload["count"] == 0, json.dumps(payload["findings"], indent=2)


@needs_own_install
@pytest.mark.slow
def test_instrumented_full_suite(tmp_path):
    """The whole tier-1 suite under instrumentation (slow: ~2x the plain
    runtime). Run locally before touching lock topology:
    PILOSA_TPU_LOCKCHECK=1 pytest tests/ -m 'not slow'."""
    payload = _run_instrumented(
        ["tests/"], tmp_path / "lockcheck.json", timeout=900,
        allow_test_failures=True,
    )
    assert payload["count"] == 0, json.dumps(payload["findings"], indent=2)
