"""InternalClient keep-alive pooling: reuse, idle eviction, retry policy,
and server-side connection severing on close."""

import threading
import time

import pytest

from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.server import Server


@pytest.fixture
def server(tmp_path):
    s = Server(data_dir=str(tmp_path / "t"), cache_flush_interval=0,
               member_monitor_interval=0)
    s.open()
    yield s
    s.close()


def _pool(client):
    return getattr(client._local, "conns", {})


def test_connection_reused_within_idle_window(server):
    c = InternalClient()
    h = f"localhost:{server.port}"
    c.status(h)
    conn1 = next(iter(_pool(c).values()))[0]
    c.status(h)
    conn2 = next(iter(_pool(c).values()))[0]
    assert conn1 is conn2, "keep-alive connection was not reused"


def test_idle_connection_not_reused(server, monkeypatch):
    c = InternalClient()
    h = f"localhost:{server.port}"
    c.status(h)
    conn1 = next(iter(_pool(c).values()))[0]
    monkeypatch.setattr(InternalClient, "IDLE_REUSE_S", 0.0)
    c.status(h)
    conn2 = next(iter(_pool(c).values()))[0]
    assert conn1 is not conn2, "stale-idle connection was reused"


def test_pool_is_per_thread(server):
    c = InternalClient()
    h = f"localhost:{server.port}"
    c.status(h)
    main_conn = next(iter(_pool(c).values()))[0]
    seen = {}

    def worker():
        c.status(h)
        seen["conn"] = next(iter(_pool(c).values()))[0]

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["conn"] is not main_conn


def test_stale_pooled_connection_recovers_for_get(server, tmp_path):
    """Server restart on the same port: the pooled connection is dead; a
    GET must silently retry on a fresh connection."""
    c = InternalClient()
    h = f"localhost:{server.port}"
    c.status(h)
    port = server.port
    server.close()
    s2 = Server(data_dir=str(tmp_path / "t2"), port=port,
                cache_flush_interval=0, member_monitor_interval=0)
    s2.open()
    try:
        # The pooled connection points at the dead server's socket; the
        # GET retries once on a fresh connection and succeeds.
        assert c.status(h)["state"]
    finally:
        s2.close()


def test_dead_server_errors_fast(server):
    c = InternalClient(timeout=2.0)
    h = f"localhost:{server.port}"
    c.status(h)
    server.close()
    with pytest.raises(ClientError):
        c.status(h)


def test_server_close_severs_keepalive_connections(server):
    """A closed node must stop answering pooled peers: without severing,
    zombie keep-alive handler threads keep serving after close()."""
    c = InternalClient(timeout=2.0)
    h = f"localhost:{server.port}"
    c.status(h)  # establish the pooled connection
    server.close()
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            c.status(h)
        except ClientError:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("closed server still answers pooled connections")
