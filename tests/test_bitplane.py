"""Bitplane kernel tests vs numpy/python oracles.

Oracle strategy mirrors SURVEY.md §4 takeaway: dense kernels are compared
against plain set/int arithmetic on randomly generated column/value data.
Uses a small shard width via planes built at width 1<<16 where convenient —
kernels are width-agnostic (they only see the trailing word axis).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitplane as bp

WIDTH = 1 << 16  # small planes keep CPU tests fast; kernels are width-agnostic
RNG = np.random.default_rng(7)


def rand_cols(density=0.01):
    return np.flatnonzero(RNG.random(WIDTH) < density).astype(np.uint64)


def plane(cols):
    return bp.pack_bits(cols, WIDTH)


def test_pack_unpack_roundtrip():
    cols = rand_cols(0.1)
    assert np.array_equal(bp.unpack_bits(plane(cols)), cols)


def test_pack_empty():
    assert bp.unpack_bits(plane([])).size == 0


def test_algebra_vs_sets():
    a_cols, b_cols = rand_cols(), rand_cols()
    a_set, b_set = set(a_cols.tolist()), set(b_cols.tolist())
    a, b = plane(a_cols), plane(b_cols)
    assert set(bp.unpack_bits(np.asarray(bp.p_and(a, b)))) == a_set & b_set
    assert set(bp.unpack_bits(np.asarray(bp.p_or(a, b)))) == a_set | b_set
    assert set(bp.unpack_bits(np.asarray(bp.p_andnot(a, b)))) == a_set - b_set
    assert set(bp.unpack_bits(np.asarray(bp.p_xor(a, b)))) == a_set ^ b_set
    assert int(bp.and_count(a, b)) == len(a_set & b_set)
    assert int(bp.count(a)) == len(a_set)


def test_row_counts_batched():
    rows = [rand_cols() for _ in range(5)]
    filt = rand_cols(0.5)
    planes = np.stack([plane(r) for r in rows])
    got = np.asarray(bp.topn_counts(planes, plane(filt)))
    want = [len(set(r.tolist()) & set(filt.tolist())) for r in rows]
    assert got.tolist() == want
    got_nofilter = np.asarray(bp.topn_counts(planes))
    assert got_nofilter.tolist() == [len(r) for r in rows]


# ------------------------------------------------------------------- BSI

BIT_DEPTH = 8


def bsi_planes(values: dict):
    """values: col -> int. Build (BIT_DEPTH+1, words) planes like a fragment."""
    planes = []
    for i in range(BIT_DEPTH):
        planes.append(plane([c for c, v in values.items() if (v >> i) & 1]))
    planes.append(plane(list(values)))  # not-null row at index BIT_DEPTH
    return np.stack(planes)


@pytest.fixture
def values():
    cols = rand_cols(0.02)
    return {int(c): int(v) for c, v in zip(cols, RNG.integers(0, 200, len(cols)))}


def test_bsi_sum(values):
    planes = bsi_planes(values)
    counts = np.asarray(bp.bsi_plane_counts(planes))
    total = sum((1 << i) * int(counts[i]) for i in range(BIT_DEPTH))
    assert total == sum(values.values())
    assert int(counts[BIT_DEPTH]) == len(values)


def test_bsi_sum_filtered(values):
    filt = rand_cols(0.5)
    fset = set(filt.tolist())
    planes = bsi_planes(values)
    counts = np.asarray(bp.bsi_plane_counts(planes, plane(filt)))
    total = sum((1 << i) * int(counts[i]) for i in range(BIT_DEPTH))
    assert total == sum(v for c, v in values.items() if c in fset)
    assert int(counts[BIT_DEPTH]) == len([c for c in values if c in fset])


def test_bsi_min_max(values):
    planes = bsi_planes(values)
    bits, cnt = bp.bsi_min(planes, BIT_DEPTH)
    assert bp.compose_bits(np.asarray(bits)) == min(values.values())
    assert int(cnt) == sum(1 for v in values.values() if v == min(values.values()))
    bits, cnt = bp.bsi_max(planes, BIT_DEPTH)
    assert bp.compose_bits(np.asarray(bits)) == max(values.values())
    assert int(cnt) == sum(1 for v in values.values() if v == max(values.values()))


def test_bsi_min_max_filtered(values):
    filt = rand_cols(0.3)
    fset = set(filt.tolist())
    sub = {c: v for c, v in values.items() if c in fset}
    if not sub:
        pytest.skip("empty filter intersection")
    planes = bsi_planes(values)
    bits, cnt = bp.bsi_min(planes, BIT_DEPTH, plane(filt))
    assert bp.compose_bits(np.asarray(bits)) == min(sub.values())
    bits, cnt = bp.bsi_max(planes, BIT_DEPTH, plane(filt))
    assert bp.compose_bits(np.asarray(bits)) == max(sub.values())


@pytest.mark.parametrize("predicate", [0, 1, 37, 127, 128, 199, 255])
def test_bsi_range_ops(values, predicate):
    planes = bsi_planes(values)

    def cols_where(fn):
        return {c for c, v in values.items() if fn(v)}

    got = bp.unpack_bits(np.asarray(bp.bsi_range_eq(planes, BIT_DEPTH, predicate)))
    assert set(got.tolist()) == cols_where(lambda v: v == predicate)

    got = bp.unpack_bits(np.asarray(bp.bsi_range_neq(planes, BIT_DEPTH, predicate)))
    assert set(got.tolist()) == cols_where(lambda v: v != predicate)

    for eq in (False, True):
        got = bp.unpack_bits(
            np.asarray(bp.bsi_range_lt(planes, BIT_DEPTH, predicate, eq))
        )
        if predicate == 0 and not eq:
            # Reference quirk (fragment.go rangeLT leading-zeros path): strict
            # LT 0 yields the value==0 columns; the executor layer masks this
            # via bsiGroup.baseValue outOfRange (field.go:1256-1289).
            want = cols_where(lambda v: v == 0)
        else:
            want = cols_where(lambda v: v <= predicate if eq else v < predicate)
        assert set(got.tolist()) == want, f"LT eq={eq} pred={predicate}"

        got = bp.unpack_bits(
            np.asarray(bp.bsi_range_gt(planes, BIT_DEPTH, predicate, eq))
        )
        want = cols_where(lambda v: v >= predicate if eq else v > predicate)
        assert set(got.tolist()) == want, f"GT eq={eq} pred={predicate}"


@pytest.mark.parametrize("lo,hi", [(0, 255), (10, 20), (37, 37), (100, 250), (0, 0)])
def test_bsi_range_between(values, lo, hi):
    planes = bsi_planes(values)
    got = bp.unpack_bits(np.asarray(bp.bsi_range_between(planes, BIT_DEPTH, lo, hi)))
    want = {c for c, v in values.items() if lo <= v <= hi}
    assert set(got.tolist()) == want


def test_bsi_empty_consider():
    planes = np.zeros((BIT_DEPTH + 1, WIDTH // 32), np.uint32)
    bits, cnt = bp.bsi_min(planes, BIT_DEPTH)
    assert int(cnt) == 0
    bits, cnt = bp.bsi_max(planes, BIT_DEPTH)
    assert int(cnt) == 0
