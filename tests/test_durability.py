"""Crash-safety tests: durable WAL + torn-tail recovery, corrupt-fragment
quarantine + anti-entropy repair, and the failpoint fault-injection layer.

The subprocess tests prove the kill -9 contract end to end: a child
process is crashed (SIGKILL or an injected os._exit at an exact code
point) mid-op-append / mid-snapshot, and the parent reopens the holder
and asserts every acknowledged write survived.
"""

import io
import os
import signal
import struct
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from pilosa_tpu import failpoints
from pilosa_tpu.constants import SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.errors import CorruptFragmentError, PilosaError
from pilosa_tpu.storage import StorageConfig
from pilosa_tpu.storage.bitmap import OP_ADD, Bitmap, encode_op, parse_op


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def make_frag(tmp_path, name="0", **kw):
    f = Fragment(str(tmp_path / "fragments" / name), "i", "f", "standard", 0, **kw)
    f.open()
    return f


# ------------------------------------------------------------- failpoints


def test_failpoint_inactive_is_noop():
    failpoints.fire("anything")  # no registry, no error


def test_failpoint_error_and_count():
    failpoints.configure("p", "error", count=2, message="boom")  # pilint: allow-failpoint(registry test fires the point by hand below)
    with pytest.raises(failpoints.InjectedFault, match="boom"):
        failpoints.fire("p")
    with pytest.raises(failpoints.InjectedFault):
        failpoints.fire("p")
    failpoints.fire("p")  # count exhausted: inert but still counted
    assert failpoints.hits("p") == 3


def test_failpoint_spec_parsing():
    failpoints.activate("a=error;b=3*crash;c=1*error(disk gone)")  # pilint: allow-failpoint(spec-grammar test, never fired)
    assert failpoints.active() == {"a": "error", "b": "3*crash", "c": "1*error"}
    with pytest.raises(failpoints.InjectedFault, match="disk gone"):
        failpoints.fire("c")
    failpoints.deactivate("a")
    assert "a" not in failpoints.active()
    with pytest.raises(ValueError, match="bad failpoint spec"):
        failpoints.activate("oops")
    with pytest.raises(ValueError):
        failpoints.activate("x=explode")


# ------------------------------------------------- torn-tail WAL recovery


def test_parse_op_checksum_is_typed_with_offset():
    op = encode_op(OP_ADD, 7)
    bad = bytes([op[0] ^ 1]) + op[1:]
    with pytest.raises(CorruptFragmentError) as ei:
        parse_op(b"\x00" * 4 + bad, 4)
    assert ei.value.offset == 4
    assert isinstance(ei.value, ValueError)  # legacy callers keep working


def test_from_buffer_truncates_incomplete_tail():
    bm = Bitmap([1, 2, 3])
    base = bm.to_bytes()
    data = base + encode_op(OP_ADD, 99) + encode_op(OP_ADD, 100)[:5]
    out = Bitmap.from_buffer(data)
    assert out.contains(99)
    assert not out.contains(100)
    assert out.valid_len == len(base) + 13
    assert out.truncated_bytes == 5


def test_from_buffer_truncates_corrupt_final_record():
    """A checksum-failing FINAL record is a torn append: truncate."""
    bm = Bitmap([1])
    base = bm.to_bytes()
    good = encode_op(OP_ADD, 50)
    bad = bytearray(encode_op(OP_ADD, 60))
    bad[2] ^= 0xFF
    out = Bitmap.from_buffer(base + good + bytes(bad))
    assert out.contains(50) and not out.contains(60)
    assert out.valid_len == len(base) + 13
    assert out.truncated_bytes == 13


def test_from_buffer_rejects_mid_log_checksum_failure():
    """A checksum failure with more data beyond it cannot be a torn append
    (appends only tear the final record) — it's bit rot. Raising routes
    the fragment to quarantine + replica repair instead of silently
    truncating away every acknowledged op after the bad sector."""
    bm = Bitmap([1])
    base = bm.to_bytes()
    bad = bytearray(encode_op(OP_ADD, 60))
    bad[2] ^= 0xFF
    good = encode_op(OP_ADD, 70)
    with pytest.raises(CorruptFragmentError, match="mid-log"):
        Bitmap.from_buffer(base + bytes(bad) + good)


def test_from_buffer_rejects_short_container_payload():
    """A container region cut mid-payload is typed corruption, not a bare
    numpy ValueError — repair loops keying on PilosaError must catch it."""
    bm = Bitmap(np.arange(10, dtype=np.uint64))
    data = bm.to_bytes()
    with pytest.raises(CorruptFragmentError, match="out of bounds"):
        Bitmap.from_buffer(data[: len(data) - 3])


def test_fragment_reopen_truncates_torn_tail(tmp_path):
    frag = make_frag(tmp_path)
    for i in range(10):
        frag.set_bit(2, i)
    frag.close()
    path = frag.path
    clean_size = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(encode_op(OP_ADD, 12345)[:7])  # torn mid-record
    frag2 = make_frag(tmp_path)
    assert all(frag2.bit(2, i) for i in range(10))
    assert frag2.recovered_tail_bytes == 7
    assert os.path.getsize(path) == clean_size  # file cut to valid boundary
    # The next append lands on a clean boundary and replays fine.
    frag2.set_bit(2, 11)
    frag2.close()
    frag3 = make_frag(tmp_path)
    assert frag3.bit(2, 11) and frag3.bit(2, 9)
    frag3.close()


# ------------------------------------------------------ quarantine at open


def test_fragment_quarantine_on_corrupt_file(tmp_path):
    frag = make_frag(tmp_path)
    frag.set_bit(1, 5)
    frag.close()
    path = frag.path
    with open(path, "r+b") as fh:
        fh.write(b"\xff" * 8)  # clobber the cookie
    frag2 = make_frag(tmp_path)
    assert frag2.quarantined
    assert frag2.quarantine_reason
    assert os.path.exists(path + ".corrupt")
    assert frag2.row_count(1) == 0  # serves empty, not an error
    # Still writable while degraded; acks are durable in the fresh file.
    assert frag2.set_bit(1, 7)
    frag2.close()
    # Quarantine persists across restart (the .corrupt file is the marker)
    # so a later anti-entropy sweep still knows to repair.
    frag3 = make_frag(tmp_path)
    assert frag3.quarantined
    assert frag3.bit(1, 7) and not frag3.bit(1, 5)
    frag3.close()


def test_holder_open_survives_corrupt_fragment(tmp_path):
    holder = Holder(str(tmp_path / "indexes")).open()
    idx = holder.create_index("q")
    fld = idx.create_field("f")
    fld.set_bit(3, 11)
    frag = holder.fragment("q", "f", "standard", 0)
    path = frag.path
    holder.close()
    with open(path, "r+b") as fh:
        fh.write(b"junkjunk")
    holder.reopen()  # must not raise
    qs = holder.quarantined_fragments()
    assert len(qs) == 1 and qs[0].shard == 0
    assert holder.fragment("q", "f", "standard", 0).row_count(3) == 0
    holder.close()


# --------------------------------------------------------- snapshot safety


def test_snapshot_fail_recovers_and_file_stays_whole(tmp_path):
    frag = make_frag(tmp_path)
    for i in range(20):
        frag.set_bit(0, i)
    failpoints.configure("snapshot-rename", "error", count=1)
    with pytest.raises(failpoints.InjectedFault):
        frag.snapshot()
    assert not os.path.exists(frag.path + ".snapshotting")
    # WAL handle was restored: writes keep working after the failure...
    assert frag.set_bit(0, 21)
    # ...and a later snapshot succeeds.
    frag.snapshot()
    frag.close()
    frag2 = make_frag(tmp_path)
    assert all(frag2.bit(0, i) for i in range(20)) and frag2.bit(0, 21)
    assert frag2.op_n == 0  # snapshot folded the ops in
    frag2.close()


def test_open_cleans_leftover_snapshot_tmp(tmp_path):
    frag = make_frag(tmp_path)
    frag.set_bit(0, 1)
    frag.close()
    tmp = frag.path + ".snapshotting"
    with open(tmp, "wb") as fh:
        fh.write(b"partial snapshot garbage")
    frag2 = make_frag(tmp_path)
    assert not os.path.exists(tmp)
    assert frag2.bit(0, 1)
    frag2.close()


def test_fsync_modes(tmp_path, monkeypatch):
    calls = {"n": 0}
    real_fsync = os.fsync

    def counting_fsync(fd):
        calls["n"] += 1
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)

    frag = make_frag(tmp_path, name="never",
                     storage_config=StorageConfig(fsync="never"))
    for i in range(10):
        frag.set_bit(0, i)
    frag.close()
    assert calls["n"] == 0

    calls["n"] = 0
    frag = make_frag(tmp_path, name="always",
                     storage_config=StorageConfig(fsync="always"))
    for i in range(10):
        frag.set_bit(0, i)
    assert calls["n"] == 10  # one per acknowledged op

    calls["n"] = 0
    frag = make_frag(tmp_path, name="batch",
                     storage_config=StorageConfig(fsync="batch", fsync_batch_ops=4))
    for i in range(10):
        frag.set_bit(0, i)
    assert calls["n"] == 2  # at ops 4 and 8
    frag.close()  # close boundary syncs the 2 stragglers
    assert calls["n"] == 3


# ------------------------------------------------- cache + stream hardening


def test_load_cache_tolerates_truncation(tmp_path):
    frag = make_frag(tmp_path)
    for r in range(5):
        frag.set_bit(r, r)
    frag.close()  # flushes the TopN cache
    cache = frag.cache_path()
    with open(cache, "rb") as fh:
        data = fh.read()
    with open(cache, "wb") as fh:
        fh.write(data[: len(data) - 6])  # torn cache write
    frag2 = make_frag(tmp_path)  # must not raise
    assert frag2.cache.top()  # rebuilt from storage
    frag2.close()


def test_read_from_rejects_short_stream(tmp_path):
    frag = make_frag(tmp_path)
    with pytest.raises(PilosaError, match="expected 8 header bytes"):
        frag.read_from(io.BytesIO(b"\x01\x02"))
    data = Bitmap([1, 2]).to_bytes()
    stream = struct.pack("<Q", len(data) + 50) + data
    with pytest.raises(PilosaError, match=r"expected \d+ payload bytes"):
        frag.read_from(io.BytesIO(stream))
    # And a payload whose op tail is torn is a sender fault, not a local
    # recovery situation: reject rather than install partial data.
    torn = data + encode_op(OP_ADD, 9)[:6]
    stream = struct.pack("<Q", len(torn)) + torn
    with pytest.raises(PilosaError, match="torn op log"):
        frag.read_from(io.BytesIO(stream))
    frag.close()


# ------------------------------------------------------------- config knobs


def test_storage_config_sources(tmp_path, monkeypatch):
    from pilosa_tpu.config import Config

    toml = tmp_path / "c.toml"
    toml.write_text("[storage]\nfsync = \"never\"\nfsync-batch-ops = 7\n")
    cfg = Config.load(str(toml))
    assert cfg.storage.fsync == "never" and cfg.storage.fsync_batch_ops == 7
    monkeypatch.setenv("PILOSA_TPU_STORAGE_FSYNC", "always")
    cfg = Config.load(str(toml))
    assert cfg.storage.fsync == "always"  # env beats file
    cfg = Config.load(str(toml), flags={"storage_fsync": "batch"})
    assert cfg.storage.fsync == "batch"  # flags beat env
    assert "[storage]" in cfg.to_toml()
    with pytest.raises(ValueError, match="storage.fsync"):
        StorageConfig(fsync="sometimes").validate()


# --------------------------------------------- kill -9 subprocess recovery


CHILD_PRELUDE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from pilosa_tpu import failpoints
    from pilosa_tpu.core.fragment import Fragment
""")


def _run_child(body, *args, timeout=120):
    return subprocess.Popen(
        [sys.executable, "-c", CHILD_PRELUDE + textwrap.dedent(body), *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_sigkill_mid_append_preserves_acked_writes(tmp_path):
    """The raw kill -9 contract: the parent SIGKILLs the writer at an
    arbitrary acked point; every write acknowledged before the kill must
    be present after reopen (WAL appends flush before the ack)."""
    path = str(tmp_path / "fragments" / "0")
    child = _run_child("""
        frag = Fragment(sys.argv[1], "i", "f", "standard", 0)
        frag.open()
        for i in range(10_000):
            frag.set_bit(1, i)
            print(i, flush=True)  # the ack
    """, path)
    acked = -1
    try:
        for line in child.stdout:
            acked = int(line)
            if acked >= 120:
                break
    finally:
        child.kill()
        child.wait(timeout=30)
    assert acked >= 120
    frag = Fragment(path, "i", "f", "standard", 0)
    frag.open()
    missing = [i for i in range(acked + 1) if not frag.bit(1, i)]
    assert not missing, f"lost acked writes: {missing[:10]}"
    frag.close()


def test_injected_crash_mid_append_then_torn_tail(tmp_path):
    """Deterministic variant: a failpoint crashes the child at the exact
    WAL-append boundary of write N+1 (the os._exit models kill -9: no
    flush, no unwinding). The parent then also tears the tail by hand and
    asserts recovery truncates to the last valid boundary."""
    path = str(tmp_path / "fragments" / "0")
    child = _run_child("""
        frag = Fragment(sys.argv[1], "i", "f", "standard", 0)
        frag.open()
        for i in range(50):
            frag.set_bit(1, i)
        print("acked 50", flush=True)
        failpoints.configure("wal-append", "crash")
        frag.set_bit(1, 50)  # crashes before the record hits the file
        print("NEVER", flush=True)
    """, path)
    out, err = child.communicate(timeout=120)
    assert child.returncode == failpoints.CRASH_EXIT_CODE, err
    assert "acked 50" in out and "NEVER" not in out
    with open(path, "ab") as fh:
        fh.write(b"\x00\x01\x02")  # a torn half-record on top
    frag = Fragment(path, "i", "f", "standard", 0)
    frag.open()
    assert all(frag.bit(1, i) for i in range(50))
    assert not frag.bit(1, 50)
    assert frag.recovered_tail_bytes == 3
    frag.close()


def test_injected_crash_mid_snapshot(tmp_path):
    """Crash at the snapshot rename boundary: the temp file is garbage,
    the original file (container section + full op log) is the durable
    truth, and reopen recovers every acked write and cleans the temp."""
    path = str(tmp_path / "fragments" / "0")
    child = _run_child("""
        failpoints.configure("snapshot-rename", "crash")
        frag = Fragment(sys.argv[1], "i", "f", "standard", 0, max_op_n=8)
        frag.open()
        for i in range(8):  # the 8th append triggers the snapshot
            frag.set_bit(1, i)
        print("NEVER", flush=True)
    """, path)
    out, err = child.communicate(timeout=120)
    assert child.returncode == failpoints.CRASH_EXIT_CODE, err
    assert "NEVER" not in out
    assert os.path.exists(path + ".snapshotting")
    frag = Fragment(path, "i", "f", "standard", 0)
    frag.open()
    assert not os.path.exists(path + ".snapshotting")
    assert all(frag.bit(1, i) for i in range(8))
    assert not frag.quarantined
    frag.close()


def test_sigkill_mid_bulk_import_preserves_acked_batches(tmp_path):
    """kill -9 during a stream of WAL-amortized bulk imports: every batch
    acknowledged before the kill must replay whole after reopen (bulk
    records flush before the ack; a torn final record truncates)."""
    path = str(tmp_path / "fragments" / "0")
    child = _run_child("""
        import numpy as np
        frag = Fragment(sys.argv[1], "i", "f", "standard", 0)
        frag.open()
        for i in range(10_000):
            rows = np.full(100, i % 7, dtype=np.uint64)
            cols = np.arange(i * 100, (i + 1) * 100, dtype=np.uint64) % (1 << 20)
            frag.bulk_import(rows, cols)
            print(i, flush=True)  # the ack
    """, path)
    acked = -1
    try:
        for line in child.stdout:
            acked = int(line)
            if acked >= 30:
                break
    finally:
        child.kill()
        child.wait(timeout=30)
    assert acked >= 30
    frag = Fragment(path, "i", "f", "standard", 0)
    frag.open()
    for i in range(acked + 1):
        col = (i * 100) % (1 << 20)
        assert frag.bit(i % 7, col), f"lost acked batch {i}"
    frag.close()


def test_injected_crash_mid_bulk_append_torn_tail(tmp_path):
    """Deterministic: crash at the bulk-append boundary, then tear the
    tail by hand — recovery truncates back to the last whole record."""
    path = str(tmp_path / "fragments" / "0")
    child = _run_child("""
        import numpy as np
        frag = Fragment(sys.argv[1], "i", "f", "standard", 0)
        frag.open()
        frag.bulk_import(np.zeros(50, dtype=np.uint64),
                         np.arange(50, dtype=np.uint64))
        print("acked", flush=True)
        failpoints.configure("bulk-wal-append", "crash")
        frag.bulk_import(np.ones(50, dtype=np.uint64),
                         np.arange(50, dtype=np.uint64))
        print("NEVER", flush=True)
    """, path)
    out, err = child.communicate(timeout=120)
    assert child.returncode == failpoints.CRASH_EXIT_CODE, err
    assert "acked" in out and "NEVER" not in out
    from pilosa_tpu.storage.bitmap import encode_bulk_op

    rec = encode_bulk_op(np.arange(10, dtype=np.uint64), None)
    with open(path, "ab") as fh:
        fh.write(rec[: len(rec) - 5])  # torn bulk record on top
    frag = Fragment(path, "i", "f", "standard", 0)
    frag.open()
    assert frag.row_count(0) == 50
    assert frag.row_count(1) == 0  # the crashed batch was never acked
    assert frag.recovered_tail_bytes == len(rec) - 5
    frag.close()


def test_sigkill_mid_hint_append_truncates_torn_tail(tmp_path):
    """Hinted-handoff durability twin of the WAL kill -9 contract
    (cluster/hints.py): the parent SIGKILLs a writer appending hint
    records at an arbitrary acked point. After reopen, every ACKED hint
    is present in order and parseable; a torn tail (the mid-append
    artifact, plus hand-written garbage) truncates at the last whole
    record and is NEVER replayed toward a peer."""
    hints_dir = str(tmp_path / "hints")
    child = _run_child("""
        from pilosa_tpu.cluster.hints import HintStore, ReplicationConfig
        from pilosa_tpu.storage.bitmap import encode_op, OP_ADD

        class F:
            index = "i"; field = "f"; view = "standard"; shard = 0
        hs = HintStore(sys.argv[1], ReplicationConfig())
        for i in range(100_000):
            assert hs.add("peer-a:1", "i", 0, [(F, encode_op(OP_ADD, i))])
            print(i, flush=True)  # the ack
    """, hints_dir)
    acked = -1
    try:
        for line in child.stdout:
            acked = int(line)
            if acked >= 150:
                break
    finally:
        child.kill()
        child.wait(timeout=30)
    assert acked >= 150
    from pilosa_tpu.cluster.hints import HintStore, ReplicationConfig
    from pilosa_tpu.storage.bitmap import decode_op_records

    hs = HintStore(hints_dir, ReplicationConfig())
    recs = hs.records("peer-a:1")
    assert len(recs) >= acked + 1, f"lost acked hints: {len(recs)}/{acked+1}"
    for i, rec in enumerate(recs[: acked + 1]):
        adds, rems = decode_op_records(rec.ops)[0]
        assert adds.tolist() == [i] and not len(rems)
    hs.close()
    # Tear the tail by hand on top: reopen truncates, counts it, and
    # the surviving prefix still parses whole.
    log_path = os.path.join(hints_dir, "peer-a%3A1", "log")
    whole = os.path.getsize(log_path)
    with open(log_path, "ab") as fh:
        fh.write(b"\x00\x01\x02garbage")
    hs2 = HintStore(hints_dir, ReplicationConfig())
    assert hs2.snapshot()["hints_truncated"] == 1
    assert os.path.getsize(log_path) == whole
    assert len(hs2.records("peer-a:1")) == len(recs)
    hs2.close()


def test_sigkill_mid_background_snapshot(tmp_path):
    """Crash at the BACKGROUND snapshot's rename boundary (the crash
    fires on the snapshotter thread; os._exit models kill -9): the
    original file with its bulk op log is the durable truth, reopen
    recovers every acked write and cleans the leftover temp."""
    data_dir = str(tmp_path / "indexes")
    child = _run_child("""
        import numpy as np, time
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.storage import StorageConfig

        failpoints.configure("snapshot-rename", "crash")
        h = Holder(sys.argv[1],
                   storage_config=StorageConfig(snapshot_interval=0))
        h.open()
        fld = h.create_index("t").create_field("f")
        rows = np.repeat(np.arange(4, dtype=np.uint64), 50_000)
        cols = np.tile(np.arange(50_000, dtype=np.uint64), 4)
        fld.import_bits(rows, cols)  # 1.6 MB WAL record: policy fires
        print("acked", flush=True)
        time.sleep(30)  # the snapshotter thread crashes the process
        print("NEVER", flush=True)
    """, data_dir)
    out, err = child.communicate(timeout=120)
    assert child.returncode == failpoints.CRASH_EXIT_CODE, err
    assert "acked" in out and "NEVER" not in out
    from pilosa_tpu.core.holder import Holder

    h = Holder(data_dir).open()
    frag = h.fragment("t", "f", "standard", 0)
    assert not frag.quarantined
    assert not os.path.exists(frag.path + ".snapshotting.bg")
    for r in range(4):
        assert frag.row_count(r) == 50_000, r
    h.close()


# ----------------------------------- quarantine repair via anti-entropy


def free_port():
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def cluster3r(tmp_path):
    """3 nodes, replica_n=3: every shard lives everywhere, and majority
    voting (2 of 3) is live — the case where a quarantined-empty fragment
    voting in the block merge could drop acked bits."""
    from pilosa_tpu.cluster.hash import ModHasher
    from pilosa_tpu.server.server import Server

    ports = [free_port() for _ in range(3)]
    hosts = [f"localhost:{p}" for p in ports]
    servers = []
    for i, port in enumerate(ports):
        s = Server(
            data_dir=str(tmp_path / f"node{i}"),
            port=port,
            cluster_hosts=hosts,
            replica_n=3,
            hasher=ModHasher(),
            cache_flush_interval=0,
            anti_entropy_interval=0,  # manual sync in tests
            executor_workers=0,
        )
        s.open()
        servers.append(s)
    yield servers
    for s in servers:
        s.close()


def test_quarantine_repair_from_replica(cluster3r):
    from pilosa_tpu.cluster.syncer import HolderSyncer
    from pilosa_tpu.server.client import InternalClient

    client = InternalClient()
    h0 = f"localhost:{cluster3r[0].port}"
    client.create_index(h0, "q")
    client.create_field(h0, "q", "f")
    time.sleep(0.05)
    for i in range(6):
        client.query(h0, "q", f"Set({i}, f=1)")
    client.query(h0, "q", "Set(3, f=2)")

    # Corrupt node0's fragment file on disk and reboot its holder: the node
    # must finish opening with the fragment quarantined, not crash.
    frag0 = cluster3r[0].holder.fragment("q", "f", "standard", 0)
    path = frag0.path
    cluster3r[0].holder.close()
    with open(path, "r+b") as fh:
        fh.write(b"\xde\xad\xbe\xef" * 4)
    cluster3r[0].holder.reopen()
    frag0 = cluster3r[0].holder.fragment("q", "f", "standard", 0)
    assert frag0.quarantined
    assert os.path.exists(path + ".corrupt")
    # Quarantined-but-unrepaired serves empty instead of erroring.
    assert frag0.row_count(1) == 0
    r = client.query(h0, "q", "Count(Row(f=1))")
    assert r["results"][0] == 0

    # A quarantined fragment must refuse to serve as a shard-ship source
    # (a resize pulling the empty copy would then GC the healthy replicas).
    from pilosa_tpu.server.client import ClientError

    with pytest.raises(ClientError, match="quarantined"):
        client.retrieve_shard_from_uri(h0, "q", "f", "standard", 0)

    # A write acknowledged while degraded (fans out to all replicas).
    client.query(h0, "q", "Set(90, f=1)")

    # ONE anti-entropy sweep: restore from a replica BEFORE block voting,
    # then the normal checksum walk finds replicas already converged.
    HolderSyncer(cluster3r[0]).sync_holder()
    frag0 = cluster3r[0].holder.fragment("q", "f", "standard", 0)
    assert not frag0.quarantined
    for i in range(6):
        assert frag0.bit(1, i), i
    assert frag0.bit(2, 3)
    assert frag0.bit(1, 90)  # degraded-period ack survived the repair

    # Byte-identical to its replica once both sit at a canonical snapshot
    # (read_from snapshots the repaired fragment internally).
    frag1 = cluster3r[1].holder.fragment("q", "f", "standard", 0)
    frag1.snapshot()
    frag0.snapshot()
    with open(frag0.path, "rb") as a, open(frag1.path, "rb") as b:
        assert a.read() == b.read()
    assert frag0.checksum() == frag1.checksum()

    # The healthy replicas never lost anything to the empty local vote.
    frag2 = cluster3r[2].holder.fragment("q", "f", "standard", 0)
    for i in range(6):
        assert frag2.bit(1, i)
